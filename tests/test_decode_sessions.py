"""Stateful decode serving: KV slot pool, decode sessions under
continuous batching, streaming, and hot-swap migration.

What these pin:
  * the sampling helper (utils/sampling.py) is the one shared
    truncation/sampling implementation for generate() and served decode
  * `session_step` (per-slot positions, masked lanes) reproduces the
    sequential `rnn_time_step` decode exactly, per slot
  * a freed slot NEVER leaks the previous session's keys/values — both
    defenses independently: the pool's reset zeroes the rows, and the
    rolling ring's visibility arithmetic masks stale rows even when
    they are poisoned (reset-masking at the decode_carry level)
  * concurrent sessions coalesce into shared scheduler dispatches with
    ZERO recompiles after warmup (the fixed-shape decode contract)
  * deadlines expire sessions, cancel frees slots, exhaustion is an
    admission error, and hot-swap migrates live sessions (rollback on
    an incompatible candidate keeps them serving the old version)
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.attention import (
    PositionEmbeddingLayer, TransformerEncoderBlock,
)
from deeplearning4j_tpu.nn.layers.feedforward import EmbeddingSequenceLayer
from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
from deeplearning4j_tpu.observe.watchdog import get_watchdog
from deeplearning4j_tpu.optim.updaters import Adam

V, T = 13, 6


def _make_net(seed=0, emb=12, max_len=64, window=8, max_cache=16):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .activation("identity")
            .list(EmbeddingSequenceLayer(n_in=V, n_out=emb),
                  PositionEmbeddingLayer(max_length=max_len),
                  TransformerEncoderBlock(num_heads=2, causal=True,
                                          window=window,
                                          rolling_cache=True,
                                          max_cache=max_cache),
                  RnnOutputLayer(n_out=V, activation="softmax"))
            .set_input_type(InputType.recurrent(1, T)).build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture(scope="module")
def net():
    return _make_net()


def _control_plane(net, slots=2, chunk=4):
    from deeplearning4j_tpu.serving import (
        ContinuousBatchingScheduler, ModelRegistry, ServingStats,
    )
    from deeplearning4j_tpu.serving.sessions import DecodeSessionManager

    registry = ModelRegistry()
    registry.deploy("default", 1, net, warm=False)
    stats = ServingStats()
    sched = ContinuousBatchingScheduler(registry, stats, max_batch_size=8)
    mgr = DecodeSessionManager(registry, sched, "default", slots=slots,
                               prefill_chunk=chunk,
                               metrics=stats.registry)
    return registry, sched, mgr


# ------------------------------------------------------------ sampling
class TestSamplingHelper:
    def test_truncate_is_shared_with_textgen(self):
        from deeplearning4j_tpu.utils import textgen
        from deeplearning4j_tpu.utils.sampling import truncate_probs
        assert textgen._truncate is truncate_probs

    def test_top_k_top_p(self):
        from deeplearning4j_tpu.utils.sampling import truncate_probs
        p = np.array([[0.4, 0.3, 0.2, 0.1]])
        k2 = truncate_probs(p, 2, None)
        assert (k2 > 0).sum() == 2 and k2[0, 0] > 0 and k2[0, 1] > 0
        nuc = truncate_probs(p, None, 0.6)
        assert (nuc > 0).sum() == 2       # 0.4+0.3 covers 0.6

    def test_params_validate(self):
        from deeplearning4j_tpu.utils.sampling import SamplingParams
        with pytest.raises(ValueError):
            SamplingParams(top_k=0)
        with pytest.raises(ValueError):
            SamplingParams(top_p=1.5)
        with pytest.raises(ValueError):
            SamplingParams(temperature=0.0)

    def test_greedy_and_temperature(self):
        from deeplearning4j_tpu.utils.sampling import (
            SamplingParams, sample_next,
        )
        p = np.array([[0.1, 0.7, 0.2]])
        rng = np.random.default_rng(0)
        tok = sample_next(p, SamplingParams(greedy=True), rng)
        assert tok[0] == 1
        # low temperature sharpens toward the mode
        cold = [int(sample_next(p, SamplingParams(temperature=0.05),
                                np.random.default_rng(i))[0])
                for i in range(20)]
        assert cold.count(1) >= 18


# ------------------------------------------------- session-step parity
class TestSessionStepParity:
    def test_session_step_matches_sequential_decode(self, net):
        """Two slots stepped through the batched per-slot seam must
        reproduce two independent sequential rnn_time_step streams."""
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, V, 9), rng.integers(0, V, 9)]

        seq = []
        for pr in prompts:
            net.rnn_clear_previous_state()
            outs = [np.asarray(net.rnn_time_step(
                pr[None, i:i + 1, None].astype(np.float32)))[0, 0]
                for i in range(len(pr))]
            seq.append(np.stack(outs))
        net.rnn_clear_previous_state()

        carries = net.session_carries(2)
        got = [[], []]
        for i in range(9):
            x = np.stack([prompts[0][i:i + 1], prompts[1][i:i + 1]]
                         )[..., None].astype(np.float32)
            act = np.array([True, True])
            val = np.ones((2, 1), np.float32)
            out, carries = net.session_step(x, carries, active=act,
                                            valid=val)
            out = np.asarray(out)
            got[0].append(out[0, 0])
            got[1].append(out[1, 0])
        for s in range(2):
            np.testing.assert_allclose(np.stack(got[s]), seq[s],
                                       rtol=2e-4, atol=2e-5)

    def test_inactive_lane_holds_carries(self, net):
        carries = net.session_carries(2)
        x = np.ones((2, 1, 1), np.float32)
        val = np.ones((2, 1), np.float32)
        _, c1 = net.session_step(x, carries,
                                 active=np.array([True, False]),
                                 valid=val)
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(carries),
                        jax.tree_util.tree_leaves(c1)):
            a, b = np.asarray(a), np.asarray(b)
            if a.shape[0] == 2:
                np.testing.assert_array_equal(a[1], b[1])   # held
        # active lane advanced its position
        pos = [np.asarray(l) for l in jax.tree_util.tree_leaves(c1)
               if np.asarray(l).shape == (2,)]
        assert any(p[0] == 1 and p[1] == 0 for p in pos)


# ------------------------------------------------------------ the pool
class TestKVSlotPool:
    def test_alloc_free_exhaustion_gauges(self, net):
        from deeplearning4j_tpu.observe.registry import MetricsRegistry
        from deeplearning4j_tpu.serving.kv_pool import (
            KVSlotPool, SlotPoolExhaustedError,
        )
        reg = MetricsRegistry()
        pool = KVSlotPool(net, 2, metrics=reg)
        a, b = pool.alloc(), pool.alloc()
        assert {a, b} == {0, 1}
        assert pool.in_use() == 2
        assert reg.gauge("serving_kv_slots_in_use",
                         model="default").value == 2
        with pytest.raises(SlotPoolExhaustedError):
            pool.alloc()
        pool.free(a)
        pool.free(a)                      # idempotent
        assert pool.in_use() == 1
        assert reg.gauge("serving_kv_slots_in_use",
                         model="default").value == 1
        assert pool.alloc() == a

    def test_alloc_timeout_unblocks_on_free(self, net):
        from deeplearning4j_tpu.serving.kv_pool import KVSlotPool
        pool = KVSlotPool(net, 1)
        s = pool.alloc()
        threading.Timer(0.05, pool.free, args=(s,)).start()
        assert pool.alloc(timeout_s=2.0) == s

    def test_freed_slot_never_leaks_previous_session(self, net):
        """The wraparound-reuse satellite, both defenses separately.

        (1) free() zeroes the slot's rows — checked directly.
        (2) even WITHOUT the zeroing, a fresh slot at position 0 cannot
            see stale ring rows: we poison the freed slot's caches with
            huge finite garbage and the re-run still matches a clean
            pool bit-for-bit — the held-position arithmetic gives the
            stale rows exactly zero attention weight. (NaN poison would
            be over-adversarial: a 0-weight NaN value still pollutes
            `0 * NaN`; stale data from a real session is finite.)"""
        import jax
        from deeplearning4j_tpu.serving.kv_pool import KVSlotPool

        def run(pool, slot, toks):
            outs = []
            for t in toks:
                x = np.full((pool.slots, 1, 1), 0, np.float32)
                x[slot, 0, 0] = t
                act = np.zeros((pool.slots,), bool)
                act[slot] = True
                val = np.zeros((pool.slots, 1), np.float32)
                val[slot] = 1.0
                out, new = pool.net.session_step(
                    x, pool.carries, active=act, valid=val)
                with pool.lock():
                    pool.swap_carries(new)
                outs.append(np.asarray(out)[slot, 0])
            return np.stack(outs)

        rng = np.random.default_rng(7)
        # long enough to wrap the ring (max_cache 16) several times
        session_a = rng.integers(0, V, 40)
        session_b = rng.integers(0, V, 12)

        pool = KVSlotPool(net, 2)
        slot = pool.alloc()
        run(pool, slot, session_a)
        pool.free(slot)

        # defense 1: rows are actually zeroed
        for leaf in jax.tree_util.tree_leaves(pool.carries):
            leaf = np.asarray(leaf)
            if leaf.ndim >= 1 and leaf.shape[0] == 2:
                assert not np.any(leaf[slot]), "freed slot not reset"

        # defense 2: poison the freed slot's KV rows, then reuse it —
        # the ring's visibility mask alone must hide the garbage
        def poison(c):
            def p(a):
                if getattr(a, "ndim", 0) >= 3 and a.shape[0] == 2:
                    a = np.asarray(a).copy()
                    a[slot] = 7777.0
                    return a
                return a
            return jax.tree_util.tree_map(p, c)
        with pool.lock():
            pool.swap_carries(poison(pool.carries))

        assert pool.alloc() == slot       # same slot, new tenant
        got = run(pool, slot, session_b)
        assert np.isfinite(got).all(), "stale poisoned KV leaked in"
        assert np.abs(got).max() <= 1.0   # softmax outputs, no garbage

        clean = KVSlotPool(net, 2)
        s2 = clean.alloc()
        want = run(clean, s2, session_b)
        np.testing.assert_array_equal(got, want)

    def test_rebind_rejects_incompatible(self, net):
        from deeplearning4j_tpu.serving.kv_pool import (
            IncompatibleSessionSwapError, KVSlotPool,
        )
        pool = KVSlotPool(net, 2)
        pool.rebind(_make_net(seed=5))            # same shapes: fine
        with pytest.raises(IncompatibleSessionSwapError):
            pool.rebind(_make_net(seed=5, emb=16))


# --------------------------------------------- sessions + batching
class TestDecodeSessions:
    def test_concurrent_sessions_share_dispatches_zero_recompiles(self,
                                                                  net):
        registry, sched, mgr = _control_plane(net)
        try:
            c0 = get_watchdog().compiles()
            s1 = mgr.open_session([1, 2, 3, 4, 5], max_tokens=8, seed=1)
            s2 = mgr.open_session([6, 7], max_tokens=8, seed=2)
            t1, t2 = s1.result(timeout=60), s2.result(timeout=60)
            assert len(t1) == len(t2) == 8
            assert get_watchdog().compiles() == c0, \
                "decode sessions caused a recompile after warmup"
            snap = mgr.snapshot()
            assert snap["sessions"]["completed"] == 2
            assert snap["tokens_streamed"] == 16
            assert snap["dispatches"]["shared"] >= 1, \
                "sessions never coalesced into one dispatch"
            assert snap["slots"]["in_use"] == 0
        finally:
            sched.shutdown()
            registry.close()

    def test_stream_events_and_outcomes(self, net):
        registry, sched, mgr = _control_plane(net)
        try:
            s = mgr.open_session([1, 2], max_tokens=3, seed=0)
            evs = list(s.stream(timeout=60))
            toks = [e["token"] for e in evs if "token" in e]
            assert toks == s.result(timeout=5)
            assert evs[-1] == {"done": True, "outcome": "completed",
                               "tokens": 3}
            assert s.ttft_ms is not None and s.ttft_ms >= 0
        finally:
            sched.shutdown()
            registry.close()

    def test_eos_stops_early(self, net):
        registry, sched, mgr = _control_plane(net)
        try:
            # greedy: first token is deterministic; use it as eos
            probe = mgr.open_session([3, 1], max_tokens=1, greedy=True)
            eos = probe.result(timeout=60)[0]
            s = mgr.open_session([3, 1], max_tokens=50, greedy=True,
                                 eos_id=int(eos))
            toks = s.result(timeout=60)
            assert toks[-1] == eos and len(toks) < 50
        finally:
            sched.shutdown()
            registry.close()

    def test_deadline_expires_and_frees_slot(self, net):
        from deeplearning4j_tpu.serving import DeadlineExceededError
        registry, sched, mgr = _control_plane(net)
        try:
            s = mgr.open_session([1, 2, 3], max_tokens=50,
                                 deadline_ms=1)
            with pytest.raises(DeadlineExceededError):
                s.result(timeout=60)
            assert s.outcome == "expired"
            assert mgr.pool.in_use() == 0
        finally:
            sched.shutdown()
            registry.close()

    def test_cancel_frees_slot(self, net):
        registry, sched, mgr = _control_plane(net)
        try:
            s = mgr.open_session([1, 2], max_tokens=50)
            s.cancel()
            # cancel is not an error: result() returns what was
            # generated before the cancel landed
            partial = s.result(timeout=60)
            assert s.outcome == "cancelled"
            assert len(partial) < 50
            assert mgr.pool.in_use() == 0
        finally:
            sched.shutdown()
            registry.close()

    def test_budget_and_exhaustion(self, net):
        from deeplearning4j_tpu.serving import SlotPoolExhaustedError
        registry, sched, mgr = _control_plane(net)
        try:
            with pytest.raises(ValueError):    # 64-position embedding
                mgr.open_session([1, 2], max_tokens=500)
            held = [mgr.open_session([1], max_tokens=60, seed=i)
                    for i in range(2)]
            with pytest.raises(SlotPoolExhaustedError):
                mgr.open_session([1], max_tokens=5)
            for h in held:
                h.cancel()
        finally:
            sched.shutdown()
            registry.close()

    def test_shutdown_aborts_sessions_with_terminal_event(self, net):
        registry, sched, mgr = _control_plane(net)
        s = mgr.open_session([1, 2], max_tokens=50)
        mgr.shutdown()
        assert s.done.wait(10)
        assert s.outcome == "failed"
        evs = list(s.stream(timeout=5))
        assert "error" in evs[-1]
        sched.shutdown()
        registry.close()


# ------------------------------------------------------ DecodeState
class TestDecodeState:
    def test_hammer_is_race_free(self):
        from deeplearning4j_tpu.models.decode_state import DecodeState
        st = DecodeState()
        errors = []

        def work(i):
            try:
                for _ in range(300):
                    with st.lock():
                        before = st.pos
                        st.seed({"k": i})
                        st.update({"k": i, "v": i}, advance=1)
                        assert st.pos == before + 1
                        assert st.carries["k"] == i
            except BaseException as e:      # pragma: no cover
                errors.append(e)

        ts = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors
        assert st.pos == 4 * 300
        st.clear()
        assert st.pos == 0 and st.carries == {}

    def test_models_use_decode_state(self, net):
        from deeplearning4j_tpu.models.decode_state import DecodeState
        assert isinstance(net._decode_state, DecodeState)
        net.rnn_clear_previous_state()
        net.rnn_time_step(np.ones((1, 2, 1), np.float32))
        assert net._decode_pos == 2
        net.rnn_clear_previous_state()
        assert net._decode_pos == 0


# ------------------------------------------------------- HTTP + swap
@pytest.mark.slow
class TestServingDecodeHttp:
    def test_generate_streams_and_reconciles_metrics(self):
        import json
        import urllib.request
        from deeplearning4j_tpu.serving import InferenceServer

        # K=2 windows: enough round-trips per session (prefill + 3
        # windows for 6 tokens) that the two concurrent clients reliably
        # coalesce into shared dispatches, which this test asserts
        srv = InferenceServer(_make_net(), decode_slots=2,
                              decode_prefill_chunk=4, decode_fused_k=2)
        port = srv.start()
        base = f"http://127.0.0.1:{port}"
        try:
            outs = [[], []]

            def go(i):
                body = json.dumps({"prompt_ids": [1, 2, 3 + i],
                                   "max_tokens": 6, "seed": i}).encode()
                req = urllib.request.Request(base + "/generate",
                                             data=body)
                with urllib.request.urlopen(req) as r:
                    assert r.headers["Content-Type"].startswith(
                        "text/event-stream")
                    for line in r:
                        line = line.decode().strip()
                        if line.startswith("data: "):
                            outs[i].append(json.loads(line[6:]))

            ts = [threading.Thread(target=go, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for evs in outs:
                assert "session" in evs[0]
                assert len([e for e in evs if "token" in e]) == 6
                assert evs[-1]["outcome"] == "completed"

            with urllib.request.urlopen(base + "/metrics") as r:
                snap = json.load(r)
            d = snap["decode"]["default"]
            assert d["tokens_streamed"] == 12
            assert d["sessions"]["completed"] == 2
            assert d["dispatches"]["shared"] >= 1

            # non-streamed JSON body
            body = json.dumps({"prompt_ids": [5], "max_tokens": 3,
                               "stream": False, "greedy": True}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                    base + "/generate", data=body)) as r:
                res = json.load(r)
            assert len(res["tokens"]) == 3
            assert res["outcome"] == "completed"

            with urllib.request.urlopen(base + "/sessions") as r:
                assert json.load(r)["decode"]["default"][
                    "sessions"]["active"] == 0
        finally:
            srv.stop()

    def test_generate_exhaustion_503_and_cancel_endpoint(self):
        import json
        import urllib.error
        import urllib.request
        from deeplearning4j_tpu.serving import InferenceServer

        srv = InferenceServer(_make_net(), decode_slots=1,
                              decode_prefill_chunk=4)
        port = srv.start()
        base = f"http://127.0.0.1:{port}"
        try:
            mgr = srv._decode["default"]
            held = mgr.open_session([1], max_tokens=60, seed=4)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    base + "/generate",
                    data=json.dumps({"prompt_ids": [1]}).encode()))
            assert ei.value.code == 503
            body = json.dumps({"session": held.id}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                    base + "/generate/cancel", data=body)) as r:
                assert json.load(r)["cancelled"] is True
            assert held.done.wait(30)
            assert mgr.pool.in_use() == 0
        finally:
            srv.stop()


@pytest.mark.slow
class TestHotSwapWithSessions:
    def test_flip_migrates_and_rollback_keeps_sessions(self):
        from deeplearning4j_tpu.serving import (
            DeployRolledBackError, InferenceServer,
        )
        srv = InferenceServer(_make_net(seed=0), decode_slots=2,
                              decode_prefill_chunk=4)
        srv.start()
        try:
            mgr = srv._decode["default"]
            s = mgr.open_session([1, 2, 3], max_tokens=40, seed=1)
            srv.deploy("default", 2, _make_net(seed=7),
                       feat_shape=(T, 1))
            assert len(s.result(timeout=120)) == 40
            assert s.outcome == "completed"
            assert mgr.entry.version == 2

            # post-flip sessions must pay zero compiles (the warm-phase
            # hook compiled the new net's buckets inside the canary)
            c0 = get_watchdog().compiles()
            s2 = mgr.open_session([4], max_tokens=4, seed=2)
            s2.result(timeout=60)
            assert get_watchdog().compiles() == c0

            # incompatible candidate: deploy rolls back, live session
            # keeps decoding on the surviving version
            s3 = mgr.open_session([1, 2], max_tokens=30, seed=3)
            with pytest.raises(DeployRolledBackError):
                srv.deploy("default", 3, _make_net(seed=9, emb=16),
                           feat_shape=(T, 1))
            assert srv.registry.get("default").version == 2
            assert len(s3.result(timeout=120)) == 30
            assert s3.outcome == "completed"
        finally:
            srv.stop()
