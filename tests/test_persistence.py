"""Checkpoint / transfer-learning / early-stopping tests.

Mirrors reference suites: ModelSerializer tests, regression/serialization
compat tests, TransferLearning tests, TestEarlyStopping (SURVEY §4).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu import InputType
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import BatchNormalization, DenseLayer, OutputLayer
from deeplearning4j_tpu.models import ComputationGraph, MultiLayerNetwork
from deeplearning4j_tpu.models.serialize import save_model, load_model
from deeplearning4j_tpu.models.transfer import (
    FineTuneConfiguration, TransferLearning, TransferLearningHelper,
)
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    InMemoryModelSaver, InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver, MaxEpochsTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.optim.updaters import Adam, Sgd


def _toy(n=128, d=6, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal((d, classes))
    y = np.eye(classes, dtype=np.float32)[(x @ w).argmax(-1)]
    return x, y


def _net(d=6, classes=3, with_bn=False):
    layers = [DenseLayer(n_out=12)]
    if with_bn:
        layers.append(BatchNormalization())
    layers.append(OutputLayer(n_out=classes, activation="softmax"))
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(11).updater(Adam(1e-2)).activation("tanh")
         .list(*layers)
         .set_input_type(InputType.feed_forward(d))
         .build())).init()


class TestModelSerializer:
    def test_zip_round_trip_exact(self, tmp_path):
        x, y = _toy()
        net = _net(with_bn=True)
        net.fit(x, y, epochs=3, batch_size=32)
        p = tmp_path / "model.zip"
        save_model(net, p)
        net2 = load_model(p)
        np.testing.assert_allclose(net.params(), net2.params(), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(net.output(x)), np.asarray(net2.output(x)), rtol=1e-5)
        assert net2.iteration == net.iteration
        assert net2.epoch == net.epoch

    def test_training_resumes_identically(self, tmp_path):
        """Updater state round-trips: resumed training == uninterrupted."""
        x, y = _toy()
        a = _net()
        a.fit(x, y, epochs=2, batch_size=32)
        p = tmp_path / "mid.zip"
        save_model(a, p)
        a.fit(x, y, epochs=2, batch_size=32)

        b = load_model(p)
        b._rng = __import__("jax").random.PRNGKey(999)  # rng only affects dropout (none here)
        b.fit(x, y, epochs=2, batch_size=32)
        np.testing.assert_allclose(a.params(), b.params(), rtol=1e-4, atol=1e-6)

    def test_graph_round_trip(self, tmp_path):
        from deeplearning4j_tpu.nn.graph import MergeVertex
        x, y = _toy(d=6, classes=2)
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Sgd(0.1)).activation("relu")
                .graph_builder()
                .add_inputs("in")
                .add_layer("a", DenseLayer(n_out=4), "in")
                .add_layer("b", DenseLayer(n_out=4), "in")
                .add_vertex("m", MergeVertex(), "a", "b")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax"), "m")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(6))
                .build())
        net = ComputationGraph(conf).init()
        net.fit(x, y, epochs=2, batch_size=64)
        p = tmp_path / "graph.zip"
        save_model(net, p)
        net2 = load_model(p)
        assert isinstance(net2, ComputationGraph)
        np.testing.assert_allclose(
            np.asarray(net.output(x)), np.asarray(net2.output(x)), rtol=1e-5)


class TestOrbaxCheckpoints:
    def test_checkpoint_manager_round_trip(self, tmp_path):
        from deeplearning4j_tpu.models.serialize import CheckpointManager
        x, y = _toy()
        net = _net()
        net.fit(x, y, epochs=2, batch_size=32)
        mgr = CheckpointManager(tmp_path / "ckpts", async_save=False)
        mgr.save(0, net)
        mgr.wait()
        net2 = _net()
        mgr.restore(net2, 0)
        np.testing.assert_allclose(net.params(), net2.params(), rtol=1e-6)
        mgr.close()


class TestTransferLearning:
    def test_freeze_and_replace_head(self):
        x, y = _toy(classes=3)
        src = _net(classes=3)
        src.fit(x, y, epochs=3, batch_size=32)

        new = (TransferLearning.builder(src)
               .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.05)))
               .set_feature_extractor(0)
               .remove_layers_from_output(1)
               .add_layer(OutputLayer(n_out=5, activation="softmax",
                                      loss="mcxent"))
               .build())
        assert new.layers[0].frozen
        assert new.layers[-1].n_out == 5
        assert new.layers[-1].n_in == 12
        # frozen layer kept source params
        src_w = np.asarray(src.params_tree[src.layers[0].name]["W"])
        new_w = np.asarray(new.params_tree[new.layers[0].name]["W"])
        np.testing.assert_allclose(src_w, new_w)
        # training does not change frozen weights
        y5 = np.eye(5, dtype=np.float32)[np.random.default_rng(0).integers(0, 5, len(x))]
        new.fit(x, y5, epochs=2, batch_size=32)
        np.testing.assert_allclose(
            np.asarray(new.params_tree[new.layers[0].name]["W"]), src_w)

    def test_n_out_replace(self):
        src = _net()
        new = (TransferLearning.builder(src)
               .n_out_replace(0, 20)
               .build())
        assert new.layers[0].n_out == 20
        assert new.layers[1].n_in == 20
        assert np.asarray(new.params_tree[new.layers[0].name]["W"]).shape == (6, 20)

    def test_helper_featurize(self):
        x, y = _toy()
        src = _net()
        frozen = (TransferLearning.builder(src)
                  .set_feature_extractor(0)
                  .build())
        helper = TransferLearningHelper(frozen)
        feats = helper.featurize(x)
        assert feats.shape == (len(x), 12)
        helper.fit_featurized(x, y, epochs=2, batch_size=32)


class TestEarlyStopping:
    def test_max_epochs_and_best_model(self):
        x, y = _toy()
        net = _net()
        it = ArrayDataSetIterator(x, y, 32)
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(ArrayDataSetIterator(x, y, 64)),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(8)],
            model_saver=InMemoryModelSaver(),
        )
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.termination_reason == "EpochTermination"
        assert result.total_epochs <= 8 + 1
        assert result.best_model is not None
        assert np.isfinite(result.best_model_score)
        assert result.best_model_score <= max(result.score_vs_epoch.values())

    def test_score_improvement_patience(self):
        x, y = _toy()
        net = _net()
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(ArrayDataSetIterator(x, y, 64)),
            epoch_termination_conditions=[
                ScoreImprovementEpochTerminationCondition(2, min_improvement=5e-2),
                MaxEpochsTerminationCondition(100),
            ],
        )
        result = EarlyStoppingTrainer(
            cfg, net, ArrayDataSetIterator(x, y, 32)).fit()
        assert result.total_epochs < 100

    def test_invalid_score_abort(self):
        x, y = _toy()
        net = _net()
        net.conf = __import__("dataclasses").replace(net.conf)
        # Blow up the LR to force NaN quickly.
        bad = MultiLayerNetwork(
            (NeuralNetConfiguration.builder()
             .seed(1).updater(Sgd(1e6)).activation("tanh")
             .list(DenseLayer(n_out=12),
                   OutputLayer(n_out=3, activation="softmax"))
             .set_input_type(InputType.feed_forward(6))
             .build())).init()
        cfg = EarlyStoppingConfiguration(
            iteration_termination_conditions=[
                InvalidScoreIterationTerminationCondition()],
            epoch_termination_conditions=[MaxEpochsTerminationCondition(50)],
        )
        result = EarlyStoppingTrainer(
            cfg, bad, ArrayDataSetIterator(x, y, 32)).fit()
        assert result.termination_reason in ("IterationTermination", "EpochTermination")

    def test_local_file_saver(self, tmp_path):
        x, y = _toy()
        net = _net()
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(ArrayDataSetIterator(x, y, 64)),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            model_saver=LocalFileModelSaver(str(tmp_path)),
            save_last_model=True,
        )
        result = EarlyStoppingTrainer(
            cfg, net, ArrayDataSetIterator(x, y, 32)).fit()
        assert os.path.exists(tmp_path / "bestModel.zip")
        assert os.path.exists(tmp_path / "latestModel.zip")
        best = result.best_model
        assert np.asarray(best.output(x)).shape == (128, 3)
