"""models/fusion.py — the graph-level conv+BN fusion pass (the model-
transform answer to the reference's reflective cuDNN helper dispatch,
`ConvolutionLayer.java:67-77`)."""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import MultiDataSet
from deeplearning4j_tpu.models import ComputationGraph
from deeplearning4j_tpu.models.fusion import fuse_conv_bn
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    BatchNormalization, ConvolutionLayer, FusedConvBNLayer, OutputLayer,
)
from deeplearning4j_tpu.optim.updaters import Sgd


def _graph(conv_kw=None, two_consumers=False):
    """input -> conv -> bn -> [gap] -> output (+ optional second
    consumer of the conv)."""
    from deeplearning4j_tpu.nn.layers import GlobalPoolingLayer

    g = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.05))
         .graph_builder().add_inputs("in")
         .set_input_types(InputType.convolutional(8, 8, 3)))
    ckw = {"kernel": (1, 1), "has_bias": False,
           "activation": "identity"}
    ckw.update(conv_kw or {})
    g.add_layer("c", ConvolutionLayer(n_out=8, **ckw), "in")
    g.add_layer("b", BatchNormalization(activation="relu"), "c")
    g.add_layer("gap", GlobalPoolingLayer(pooling="avg"), "b")
    if two_consumers:
        g.add_layer("gap2", GlobalPoolingLayer(pooling="avg"), "c")
        from deeplearning4j_tpu.nn.graph import MergeVertex

        g.add_vertex("m", MergeVertex(), "gap", "gap2")
        g.add_layer("output", OutputLayer(n_out=3, activation="softmax"),
                    "m")
    else:
        g.add_layer("output", OutputLayer(n_out=3, activation="softmax"),
                    "gap")
    g.set_outputs("output")
    return ComputationGraph(g.build()).init()


def _data():
    r = np.random.default_rng(0)
    x = r.standard_normal((4, 8, 8, 3)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.integers(0, 3, 4)]
    return MultiDataSet([x], [y])


def test_pair_rewritten_with_exact_parity():
    net = _graph()
    fused = fuse_conv_bn(net)
    assert fused.fused_pairs == [("c", "b")]
    assert isinstance(fused.conf.vertices["b"].layer, FusedConvBNLayer)
    assert "c" not in fused.conf.vertices
    mds = _data()
    x = np.asarray(mds.features[0])
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(fused.output(x)),
                               rtol=1e-5, atol=1e-6)
    # training parity through a step (SGD: no updater-state difference)
    net.fit(mds)
    fused.fit(mds)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(fused.output(x)),
                               rtol=1e-4, atol=1e-5)
    # running stats transferred AND updated identically
    np.testing.assert_allclose(
        np.asarray(net.state_tree["b"]["mean"]),
        np.asarray(fused.state_tree["b"]["mean"]), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("conv_kw", [
    {"kernel": (3, 3)},           # 3x3 VALID (not SAME) stays unfused
    {"kernel": (3, 3), "convolution_mode": "same",
     "stride": (2, 2)},           # 3x3 strided stays unfused
    {"kernel": (5, 5), "convolution_mode": "same"},   # unsupported shape
    {"has_bias": True},           # biased conv
    {"activation": "relu"},       # non-identity conv activation
])
def test_ineligible_convs_left_alone(conv_kw):
    net = _graph(conv_kw)
    fused = fuse_conv_bn(net)
    assert fused.fused_pairs == []
    assert "c" in fused.conf.vertices


def test_multi_consumer_conv_not_fused():
    net = _graph(two_consumers=True)
    fused = fuse_conv_bn(net)
    assert fused.fused_pairs == []


def test_3x3_same_pair_rewritten_with_exact_parity():
    """The 3x3 stride-1 SAME conv+BN pair fuses (the in-kernel-stats
    Pallas conv, `ops/conv_fused.py:conv3x3_with_channel_stats`) with
    forward and one-step training parity against the unfused graph."""
    net = _graph({"kernel": (3, 3), "convolution_mode": "same"})
    fused = fuse_conv_bn(net)
    assert fused.fused_pairs == [("c", "b")]
    layer = fused.conf.vertices["b"].layer
    assert isinstance(layer, FusedConvBNLayer)
    assert tuple(layer.kernel) == (3, 3)
    mds = _data()
    x = np.asarray(mds.features[0])
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(fused.output(x)),
                               rtol=1e-5, atol=1e-6)
    net.fit(mds)
    fused.fit(mds)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(fused.output(x)),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(net.state_tree["b"]["mean"]),
        np.asarray(fused.state_tree["b"]["mean"]), rtol=1e-5, atol=1e-6)


def test_3x3_explicit_pad_same_equivalent_fuses():
    """padding=(1,1) truncate-mode is SAME for a stride-1 3x3 — the
    structural eligibility accepts the explicit-pad spelling too."""
    net = _graph({"kernel": (3, 3), "padding": (1, 1)})
    fused = fuse_conv_bn(net)
    assert fused.fused_pairs == [("c", "b")]


def test_resnet50_fuses_all_bottleneck_convs():
    from deeplearning4j_tpu.zoo import ResNet50

    net = ComputationGraph(ResNet50(
        num_classes=4, input_shape=(32, 32, 3),
        updater=Sgd(1e-3)).conf()).init()
    fused = fuse_conv_bn(net)
    # 16 blocks x (2 bottleneck 1x1s + 1 stride-1 SAME 3x3) + 4
    # projection shortcuts = 52; only the 7x7 stem stays unfused
    assert len(fused.fused_pairs) == 52
    x = np.random.default_rng(2).standard_normal(
        (2, 32, 32, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(fused.output(x)),
                               rtol=1e-4, atol=1e-5)


def test_sequential_net_rejected_with_clear_error():
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.layers import DenseLayer

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(0)
         .list(DenseLayer(n_out=4),
               OutputLayer(n_out=2, activation="softmax"))
         .set_input_type(InputType.feed_forward(3)).build())).init()
    with pytest.raises(TypeError, match="ComputationGraph"):
        fuse_conv_bn(net)


def test_training_config_and_updater_state_carry_over():
    """Global l2 cascade lands on the fused layer (loss parity holds
    under regularization) and untouched layers keep their Adam moments."""
    from deeplearning4j_tpu.nn.layers import GlobalPoolingLayer
    from deeplearning4j_tpu.optim.updaters import Adam

    g = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
         .l2(1e-3)
         .graph_builder().add_inputs("in")
         .set_input_types(InputType.convolutional(8, 8, 3)))
    g.add_layer("c", ConvolutionLayer(n_out=8, kernel=(1, 1),
                                      has_bias=False,
                                      activation="identity"), "in")
    g.add_layer("b", BatchNormalization(activation="relu"), "c")
    g.add_layer("gap", GlobalPoolingLayer(pooling="avg"), "b")
    g.add_layer("output", OutputLayer(n_out=3, activation="softmax"),
                "gap")
    g.set_outputs("output")
    net = ComputationGraph(g.build()).init()
    mds = _data()
    net.fit(mds)   # build up Adam moments
    fused = fuse_conv_bn(net)
    assert fused.fused_pairs == [("c", "b")]
    assert fused.conf.vertices["b"].layer.l2 == pytest.approx(1e-3)
    # untouched output layer kept its Adam first moment (non-zero)
    import jax

    old_m = jax.tree_util.tree_leaves(net.updater_state["output"])
    new_m = jax.tree_util.tree_leaves(fused.updater_state["output"])
    for a, b in zip(old_m, new_m):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert any(np.abs(np.asarray(l)).max() > 0 for l in new_m)
    # scores (incl. l2 term) agree
    assert net.score(mds) == pytest.approx(fused.score(mds), rel=1e-5)


@pytest.mark.parametrize("conv_kw", [
    None,                                              # 1x1
    {"kernel": (3, 3), "convolution_mode": "same"},    # 3x3 SAME
], ids=["1x1", "3x3"])
def test_fused_layer_central_difference_gradients(conv_kw):
    """The reference's correctness backbone applied to the fused layer:
    numeric central-difference vs analytic gradients through a graph
    containing FusedConvBNLayer (f64, interpret-mode Pallas)."""
    from deeplearning4j_tpu.gradientcheck import check_gradients

    net = _graph(conv_kw)
    fused = fuse_conv_bn(net)
    assert fused.fused_pairs == [("c", "b")]

    class _Shim:   # dict-IO adapter, the CG gradient-check convention
        params_tree = fused.params_tree
        state_tree = fused.state_tree

        @staticmethod
        def _loss(params, states, features, labels, fmask, lmask, rng,
                  train=False):
            return fused._loss(
                params, states, {"in": features}, {"output": labels},
                None, None, rng, train=train)

    r = np.random.default_rng(3)
    x = r.standard_normal((3, 8, 8, 3)).astype(np.float64)
    y = np.eye(3, dtype=np.float64)[r.integers(0, 3, 3)]
    assert check_gradients(_Shim, x, y, subset=40)
