"""Preemption-proof training under injected faults (ISSUE 6 tentpole).

Acceptance: a SIGTERM (or injected fault) at an arbitrary step mid-epoch,
followed by re-running the same fit(), produces a loss curve bit-identical
to an uninterrupted run — for MultiLayerNetwork, ComputationGraph, AND
ParallelWrapper — while `LossTracker.host_syncs` confirms the ≤1
sync/epoch contract survived the checkpoint cadence.

Every fault here comes from `parallel/chaos.py` (deterministic on CPU):
SIGTERM-at-step-N, checkpoint-writer IO errors at exact file boundaries
(the COMMIT protocol), iterator crashes/stalls, plus elastic shrink and
off-main-thread preemption degrade.
"""

import os
import threading

import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.models import ComputationGraph, MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observe.flight import (
    FlightRecorder, get_flight, set_flight,
)
from deeplearning4j_tpu.optim.updaters import Adam
from deeplearning4j_tpu.parallel import (
    CheckpointIOFault, FailingIterator, InjectedFault, ParallelWrapper,
    ShardedCheckpointer, SigtermAtStep, StallingIterator,
)
from deeplearning4j_tpu.parallel.elastic import PreemptionHandler
from deeplearning4j_tpu.parallel.mesh import AXIS_DATA

pytestmark = pytest.mark.chaos


def _net(seed=7):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .list(
            DenseLayer(n_in=12, n_out=16, activation="relu"),
            OutputLayer(n_in=16, n_out=4, activation="softmax",
                        loss="mcxent"),
        )
        .build()
    ).init()


def _graph(seed=7):
    return ComputationGraph(
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .graph_builder()
        .add_inputs("in")
        .add_layer("dense", DenseLayer(n_out=16, activation="relu"), "in")
        .add_layer("out", OutputLayer(n_out=4, activation="softmax",
                                      loss="mcxent"), "dense")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(12))
        .build()
    ).init()


def _data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 12)).astype(np.float32)
    yi = rng.integers(0, 4, n)
    x[np.arange(n), yi % 12] += 2.0
    return x, np.eye(4, dtype=np.float32)[yi]


def _batches(x, y, bs=64):
    return [DataSet(x[i:i + bs], y[i:i + bs]) for i in range(0, len(x), bs)]


class _Rec:
    """Loss-curve listener (losses stay deferred — no host sync)."""

    def __init__(self):
        self.losses = []

    def iteration_done(self, net, it, ep, loss):
        self.losses.append(loss)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a, **k: None
        raise AttributeError(name)


def _curve(losses):
    return [float(v) for v in losses]


# ---------------------------------------------------------------- tentpole
@pytest.mark.slow
class TestResumeBitIdentical:
    """Kill mid-epoch, re-run the same fit() → identical loss curve."""

    def test_mln_real_sigterm_mid_epoch(self, tmp_path):
        x, y = _data()

        ref_net, ref = _net(), _Rec()
        ref_net.listeners.append(ref)
        ref_net.fit(x, y, epochs=2, batch_size=64)       # 4 batches/epoch
        assert ref_net._loss_tracker.host_syncs <= 2     # ≤1 sync/epoch

        # interrupted: a REAL SIGTERM lands after iteration 3 completes;
        # preemption=True installs the plan-owned handler for the fit
        net_b, rec_b = _net(), _Rec()
        sig = SigtermAtStep(3)
        net_b.listeners += [rec_b, sig]
        ck = ShardedCheckpointer(str(tmp_path / "ck"))
        net_b.fit(x, y, epochs=2, batch_size=64,
                  checkpointer=ck, preemption=True)
        assert sig.fired and net_b.stopped_early
        assert len(rec_b.losses) == 3
        assert net_b._loss_tracker.host_syncs <= 2
        assert ck.latest_step() == 3

        # same fit() again, resume="auto": picks up at (step 3, batch 3)
        net_c, rec_c = _net(seed=99), _Rec()   # init gets overwritten
        net_c.listeners.append(rec_c)
        ck2 = ShardedCheckpointer(str(tmp_path / "ck"))
        net_c.fit(x, y, epochs=2, batch_size=64,
                  checkpointer=ck2, resume="auto")
        assert len(rec_c.losses) == 5
        assert net_c._loss_tracker.host_syncs <= 2
        np.testing.assert_allclose(
            _curve(rec_b.losses) + _curve(rec_c.losses),
            _curve(ref.losses), rtol=1e-6, atol=1e-7)

    def test_cg_stop_fn_mid_epoch(self, tmp_path):
        x, y = _data()

        ref_net, ref = _graph(), _Rec()
        ref_net.listeners.append(ref)
        ref_net.fit(x, y, epochs=2, batch_size=64)
        assert ref_net._loss_tracker.host_syncs <= 2

        net_b, rec_b = _graph(), _Rec()
        net_b.listeners.append(rec_b)
        ck = ShardedCheckpointer(str(tmp_path / "ck"))
        net_b.fit(x, y, epochs=2, batch_size=64, checkpointer=ck,
                  stop_fn=lambda: len(rec_b.losses) >= 3)
        assert net_b.stopped_early and len(rec_b.losses) == 3
        assert net_b._loss_tracker.host_syncs <= 2

        net_c, rec_c = _graph(seed=99), _Rec()
        net_c.listeners.append(rec_c)
        ck2 = ShardedCheckpointer(str(tmp_path / "ck"))
        net_c.fit(x, y, epochs=2, batch_size=64,
                  checkpointer=ck2, resume="auto")
        assert len(rec_c.losses) == 5
        assert net_c._loss_tracker.host_syncs <= 2
        np.testing.assert_allclose(
            _curve(rec_b.losses) + _curve(rec_c.losses),
            _curve(ref.losses), rtol=1e-6, atol=1e-7)

    def test_parallel_wrapper_fused_partial_window_resume(
            self, tmp_path, devices8):
        """steps_per_dispatch=4 with a stop landing MID-window: the
        executor drains the partial window per-step, the checkpoint
        records the exact cursor, and the resumed run (which replays the
        window tail per-step too) continues the rng chain bit-identically."""
        mesh = Mesh(np.array(devices8), (AXIS_DATA,))
        x, y = _data(n=512)                              # 8 batches/epoch

        net_a, rec_a = _net(), _Rec()
        net_a.listeners.append(rec_a)
        wa = ParallelWrapper(net_a, mesh=mesh)
        wa.fit(x, y, epochs=2, batch_size=64, steps_per_dispatch=4)
        assert len(rec_a.losses) == 16
        assert net_a._loss_tracker.host_syncs <= 2

        # stop at the 7th batch boundary → batches 4,5 are a buffered
        # partial window at stop time
        net_b, rec_b = _net(), _Rec()
        net_b.listeners.append(rec_b)
        wb = ParallelWrapper(net_b, mesh=mesh)
        ck = ShardedCheckpointer(str(tmp_path / "ck"))
        calls = [0]

        def stop_fn():
            calls[0] += 1
            return calls[0] > 6

        wb.fit(x, y, epochs=2, batch_size=64, steps_per_dispatch=4,
               checkpointer=ck, stop_fn=stop_fn)
        assert wb.stopped_early and len(rec_b.losses) == 6
        assert net_b._loss_tracker.host_syncs <= 2
        ck.wait()
        assert ck.latest_step() == 6

        net_c, rec_c = _net(seed=99), _Rec()
        net_c.listeners.append(rec_c)
        wc = ParallelWrapper(net_c, mesh=mesh)
        ck2 = ShardedCheckpointer(str(tmp_path / "ck"))
        wc.fit(x, y, epochs=2, batch_size=64, steps_per_dispatch=4,
               checkpointer=ck2, resume="auto")
        assert len(rec_c.losses) == 10
        assert net_c._loss_tracker.host_syncs <= 2
        np.testing.assert_allclose(
            _curve(rec_b.losses) + _curve(rec_c.losses),
            _curve(rec_a.losses), rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------- COMMIT protocol
class TestCommitProtocol:
    def test_half_written_step_invisible_and_latch_drains(self, tmp_path):
        """Writer dies after the FIRST shard file: the step never gets a
        COMMIT so it is invisible to steps(); wait() surfaces the error
        exactly once (the latch drains)."""
        net = _net()
        ck = ShardedCheckpointer(str(tmp_path / "ck"), async_save=True)
        ck.fault_hook = fault = CheckpointIOFault(fail_after=1,
                                                  kind="shard", times=1)
        ck.save(net, step=1)            # dies after one shard file
        ck.save(net, step=2)            # fault budget spent → commits
        with pytest.raises(InjectedFault):
            ck.wait()
        ck.wait()                       # latch drained: second wait clean
        assert fault.raised == 1
        assert ck.steps() == [2]        # half-written step 1 is invisible
        half = tmp_path / "ck" / "step-0000000001" / "process-0"
        assert half.is_dir() and not (half / "COMMIT").exists()
        # and the half-written step is not restorable
        with pytest.raises(FileNotFoundError):
            ck._read_step(1)

    @pytest.mark.slow
    def test_resume_picks_previous_committed_step(self, tmp_path):
        """Kill the writer mid-write of the LAST checkpoint: resume lands
        on the previous committed step and retrains the lost batch to a
        bit-identical curve."""
        x, y = _data()

        ref_net, ref = _net(), _Rec()
        ref_net.listeners.append(ref)
        ref_net.fit(x, y, epochs=1, batch_size=64)       # 4 losses

        net_b, rec_b = _net(), _Rec()
        net_b.listeners.append(rec_b)
        ck = ShardedCheckpointer(str(tmp_path / "ck"))
        inner = CheckpointIOFault(fail_after=1, kind="shard", times=1)

        def hook(kind, path):            # kill only step 4's write
            if f"step-{4:010d}" in path:
                inner(kind, path)

        ck.fault_hook = hook
        # training itself succeeds; finalize's wait() surfaces the
        # writer death (a silently failed checkpoint is a lost run)
        with pytest.raises(InjectedFault):
            net_b.fit(x, y, epochs=1, batch_size=64, checkpointer=ck)
        assert len(rec_b.losses) == 4
        assert ck.steps() == [1, 2, 3]   # step 4 never committed
        assert ck.latest_step() == 3

        net_c, rec_c = _net(seed=99), _Rec()
        net_c.listeners.append(rec_c)
        ck2 = ShardedCheckpointer(str(tmp_path / "ck"))
        net_c.fit(x, y, epochs=1, batch_size=64,
                  checkpointer=ck2, resume="auto")
        assert len(rec_c.losses) == 1    # retrains exactly the lost batch
        np.testing.assert_allclose(
            _curve(rec_b.losses[:3]) + _curve(rec_c.losses),
            _curve(ref.losses), rtol=1e-6, atol=1e-7)


# ------------------------------------------------- input-pipeline faults
@pytest.mark.slow
class TestDataPipelineFaults:
    def test_iterator_crash_dumps_flight_and_resume_breadcrumbs(
            self, tmp_path):
        """A data-pipeline crash flight-dumps the black box; the resumed
        run records a `resume` event pointing at the prior dump."""
        prev = set_flight(FlightRecorder(dump_dir=str(tmp_path)))
        try:
            x, y = _data()
            batches = _batches(x, y)

            ref_net, ref = _net(), _Rec()
            ref_net.listeners.append(ref)
            ref_net.fit(batches, epochs=1)

            net_b, rec_b = _net(), _Rec()
            net_b.listeners.append(rec_b)
            ck = ShardedCheckpointer(str(tmp_path / "ck"))
            with pytest.raises(InjectedFault):
                net_b.fit(FailingIterator(batches, fail_at=2),
                          epochs=1, checkpointer=ck)
            assert len(rec_b.losses) == 2
            dumps = [n for n in os.listdir(tmp_path)
                     if n.startswith("flight_") and n.endswith(".json")]
            assert len(dumps) == 1 and "training_exception" in dumps[0]

            net_c, rec_c = _net(seed=99), _Rec()
            net_c.listeners.append(rec_c)
            ck2 = ShardedCheckpointer(str(tmp_path / "ck"))
            net_c.fit(batches, epochs=1, checkpointer=ck2, resume="auto")
            assert len(rec_c.losses) == 2
            np.testing.assert_allclose(
                _curve(rec_b.losses) + _curve(rec_c.losses),
                _curve(ref.losses), rtol=1e-6, atol=1e-7)
            # the restart carries its predecessor's black box
            resumes = [e for e in get_flight().events()
                       if e["kind"] == "resume"]
            assert resumes and resumes[-1]["data"]["prior_dump"] == \
                os.path.join(str(tmp_path), dumps[0])
        finally:
            set_flight(prev)

    def test_stalling_iterator_is_ordinary_etl_time(self, tmp_path):
        """A slow pipeline must not trip any recovery machinery."""
        x, y = _data()
        batches = _batches(x, y)

        ref_net, ref = _net(), _Rec()
        ref_net.listeners.append(ref)
        ref_net.fit(batches, epochs=1)

        net, rec = _net(), _Rec()
        net.listeners.append(rec)
        stalling = StallingIterator(batches, stall_at=1, stall_s=0.2)
        net.fit(stalling, epochs=1,
                checkpointer=ShardedCheckpointer(str(tmp_path / "ck")))
        assert stalling.stalled == 1 and not net.stopped_early
        np.testing.assert_allclose(_curve(rec.losses), _curve(ref.losses),
                                   rtol=1e-6, atol=1e-7)


# ----------------------------------------------------------- elastic shrink
@pytest.mark.slow
class TestElasticShrink:
    def test_restore_8_device_snapshot_onto_4_devices(
            self, tmp_path, devices8):
        """A snapshot taken on 8 devices restores onto a 4-device mesh
        (global arrays re-assembled from shards, re-sharded onto the
        smaller mesh) and training continues."""
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel.sharding import ShardingRules

        rules = ShardingRules(rules=[("*dense*", "W", P(None, AXIS_DATA)),
                                     ("*dense*", "b", P(AXIS_DATA))])
        x, y = _data()

        mesh8 = Mesh(np.array(devices8), (AXIS_DATA,))
        net_a = _net()
        wa = ParallelWrapper(net_a, mesh=mesh8, param_rules=rules)
        ck = ShardedCheckpointer(str(tmp_path / "ck"))
        wa.fit(x, y, epochs=1, batch_size=64, checkpointer=ck)
        ck.wait()

        mesh4 = Mesh(np.array(devices8[:4]), (AXIS_DATA,))
        net_c = _net(seed=99)
        wc = ParallelWrapper(net_c, mesh=mesh4, param_rules=rules)
        pos = ck.restore_into_wrapper(wc)
        assert pos["batch_in_epoch"] == 4
        assert net_c.iteration == net_a.iteration
        for lname, sub in net_a.params_tree.items():
            for k, v in sub.items():
                np.testing.assert_array_equal(
                    np.asarray(v), np.asarray(net_c.params_tree[lname][k]))
        # the restored FSDP leaf lives on the SMALLER mesh now
        leaf = net_c.params_tree["layer0_denselayer"]["W"]
        idxs = {tuple((sl.start, sl.stop) for sl in s.index)
                for s in leaf.addressable_shards}
        assert len(idxs) == 4
        rec = _Rec()
        net_c.listeners.append(rec)
        wc.fit(x, y, epochs=2, batch_size=64, resume=pos)
        assert len(rec.losses) == 4      # epoch 0 replayed, epoch 1 trained
        assert all(np.isfinite(v) for v in _curve(rec.losses))

    def test_sharded_moments_survive_8_to_4_shrink(
            self, tmp_path, devices8):
        """ISSUE 9 satellite: replica-sharded Adam moments snapshot on an
        8-device FSDP mesh, re-assemble, and re-partition onto a 4-device
        spine — then training continues. Moment bytes must land sharded
        on the SMALLER mesh too, not silently re-replicated."""
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.optim.updaters import MOMENT_STATE_KEYS
        from deeplearning4j_tpu.parallel.sharding import ShardingRules

        def moment_leaves(net):
            for lname, state in net.updater_state.items():
                for skey, sub in state.items():
                    if skey in MOMENT_STATE_KEYS:
                        for pname, leaf in sub.items():
                            yield lname, skey, pname, leaf

        rules = ShardingRules(rules=[("*dense*", "W", P(None, AXIS_DATA)),
                                     ("*dense*", "b", P(AXIS_DATA))])
        x, y = _data()

        mesh8 = Mesh(np.array(devices8), (AXIS_DATA,))
        net_a = _net()
        wa = ParallelWrapper(net_a, mesh=mesh8, param_rules=rules)
        ck = ShardedCheckpointer(str(tmp_path / "ck"))
        wa.fit(x, y, epochs=1, batch_size=64, checkpointer=ck)
        ck.wait()
        # the source run really exercised the contract: at least the
        # unruled OutputLayer W-moments are sharded on the replica axis
        src_sharded = {(ln, sk, pn)
                       for ln, sk, pn, leaf in moment_leaves(net_a)
                       if any(a is not None for a in leaf.sharding.spec)}
        assert src_sharded

        mesh4 = Mesh(np.array(devices8[:4]), (AXIS_DATA,))
        net_c = _net(seed=99)
        wc = ParallelWrapper(net_c, mesh=mesh4, param_rules=rules)
        pos = ck.restore_into_wrapper(wc)
        assert net_c.iteration == net_a.iteration
        for ln, sk, pn, leaf in moment_leaves(net_a):
            restored = net_c.updater_state[ln][sk][pn]
            np.testing.assert_array_equal(np.asarray(leaf),
                                          np.asarray(restored))
        # every source-sharded moment is sharded on the 4-device spine
        # as well: 4 distinct shard slices, not 4 full copies
        for ln, sk, pn in src_sharded:
            leaf = net_c.updater_state[ln][sk][pn]
            assert any(a is not None for a in leaf.sharding.spec), \
                f"{ln}/{sk}/{pn} re-replicated after shrink"
            idxs = {tuple((sl.start, sl.stop) for sl in s.index)
                    for s in leaf.addressable_shards}
            assert len(idxs) == 4
        rec = _Rec()
        net_c.listeners.append(rec)
        wc.fit(x, y, epochs=2, batch_size=64, resume=pos)
        assert all(np.isfinite(v) for v in _curve(rec.losses))


# ------------------------------------------------ preemption degrade path
class TestPreemptionDegrade:
    def test_install_off_main_thread_degrades_gracefully(self):
        res = {}

        def worker():
            h = PreemptionHandler()
            try:
                h.install()              # signal.signal → ValueError here
                res["degraded"] = h.degraded
                h.request_stop()         # programmatic path still works
                res["preempted"] = h.preempted
            finally:
                h.uninstall()

        t = threading.Thread(target=worker)
        t.start()
        t.join(10)
        assert res == {"degraded": True, "preempted": True}

    @pytest.mark.slow
    def test_fit_with_degraded_handler_stops_via_request_stop(self):
        """The whole fit runs on a worker thread (threaded serving/test
        runners): install() degrades instead of crashing the fit, and
        SigtermAtStep's request_stop() delivery still preempts."""
        x, y = _data()
        res = {}

        def worker():
            handler = PreemptionHandler().install()
            net, rec = _net(), _Rec()
            sig = SigtermAtStep(2, handler=handler)
            net.listeners += [rec, sig]
            net.fit(x, y, epochs=2, batch_size=64, preemption=handler)
            res.update(degraded=handler.degraded, fired=sig.fired,
                       stopped=net.stopped_early, losses=len(rec.losses))

        t = threading.Thread(target=worker)
        t.start()
        t.join(120)
        assert not t.is_alive()
        assert res == {"degraded": True, "fired": True,
                       "stopped": True, "losses": 2}


class TestResumeValidation:
    def test_resume_auto_without_checkpointer_raises(self):
        """`resume="auto"` with no checkpointer can never restore
        anything — silently training from scratch would masquerade as a
        resume, so it must fail loudly at the fit() call."""
        x, y = _data(n=64)
        net = _net()
        with pytest.raises(ValueError, match="nothing to restore"):
            net.fit(x, y, epochs=1, batch_size=64, resume="auto")
