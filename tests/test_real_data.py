"""Real-data end-to-end tests (round-1 verdict weak item 6: 'no end-to-end
accuracy demonstration on real data anywhere').

- Iris (embedded, real measurements): train → evaluate() accuracy.
- MNIST cache layout: genuine IDX-format files written into the cache
  directory exercise the native IDX decoder + loader path (synthetic
  fallback must NOT trigger), mirroring the reference's
  `datasets/mnist/` binary readers.
"""

import gzip
import os
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.data.datasets import (
    IrisDataSetIterator, MnistDataSetIterator, load_iris, load_mnist,
)
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optim.updaters import Adam


class TestIrisRealData:
    def test_train_and_evaluate_accuracy(self):
        """The reference's integration style: fit on Iris, assert
        accuracy via the Evaluation pipeline (not raw argmax)."""
        x, y = load_iris()
        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(7).updater(Adam(0.05))
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss="mcxent"))
            .build()).init()
        net.fit(x, y, epochs=60, batch_size=50)
        ev = net.evaluate(IrisDataSetIterator(batch_size=50))
        assert ev.accuracy() >= 0.95
        assert ev.f1() >= 0.9
        # the stats render includes the confusion matrix
        assert "Confusion" in ev.stats()


def _write_idx_images(path, images: np.ndarray):
    """Genuine IDX3 layout: magic 0x00000803, dims, raw uint8."""
    n, h, w = images.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">IIII", 0x00000803, n, h, w))
        f.write(images.astype(np.uint8).tobytes())


def _write_idx_labels(path, labels: np.ndarray):
    with open(path, "wb") as f:
        f.write(struct.pack(">II", 0x00000801, len(labels)))
        f.write(labels.astype(np.uint8).tobytes())


class TestMnistCacheLayout:
    def test_idx_files_load_not_synthetic(self, tmp_path, monkeypatch):
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (32, 28, 28)).astype(np.uint8)
        labels = rng.integers(0, 10, 32).astype(np.uint8)
        mdir = tmp_path / "mnist"
        mdir.mkdir()
        _write_idx_images(str(mdir / "train-images-idx3-ubyte"), imgs)
        _write_idx_labels(str(mdir / "train-labels-idx1-ubyte"), labels)
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))

        x, y, synthetic = load_mnist(train=True)
        assert not synthetic, "real IDX files must not hit the fallback"
        assert x.shape == (32, 784) and y.shape == (32, 10)
        # pixel values decoded and scaled to [0,1]
        np.testing.assert_allclose(
            x[0], imgs[0].reshape(784).astype(np.float32) / 255.0,
            rtol=1e-6)
        np.testing.assert_array_equal(y.argmax(-1), labels)

        it = MnistDataSetIterator(batch_size=16, train=True, shuffle=False)
        assert not it.synthetic
        ds = next(it)
        assert ds.features.shape == (16, 784)

    def test_gzipped_idx_files_load(self, tmp_path, monkeypatch):
        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, (8, 28, 28)).astype(np.uint8)
        labels = rng.integers(0, 10, 8).astype(np.uint8)
        mdir = tmp_path / "mnist"
        mdir.mkdir()
        raw_i = struct.pack(">IIII", 0x00000803, 8, 28, 28) + imgs.tobytes()
        raw_l = struct.pack(">II", 0x00000801, 8) + labels.tobytes()
        with gzip.open(str(mdir / "t10k-images-idx3-ubyte.gz"), "wb") as f:
            f.write(raw_i)
        with gzip.open(str(mdir / "t10k-labels-idx1-ubyte.gz"), "wb") as f:
            f.write(raw_l)
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        x, y, synthetic = load_mnist(train=False)
        assert not synthetic
        assert x.shape == (8, 784)
        np.testing.assert_array_equal(y.argmax(-1), labels)

    def test_trains_on_real_idx_digits(self, tmp_path, monkeypatch):
        """End-to-end: separable 'digit' images through the real IDX
        pipeline train to high accuracy."""
        rng = np.random.default_rng(2)
        n, classes = 256, 4
        labels = rng.integers(0, classes, n).astype(np.uint8)
        imgs = np.zeros((n, 28, 28), np.uint8)
        for i, c in enumerate(labels):   # bright quadrant per class
            r, col = divmod(int(c), 2)
            imgs[i, r * 14:(r + 1) * 14, col * 14:(col + 1) * 14] = \
                200 + rng.integers(0, 56)
        mdir = tmp_path / "mnist"
        mdir.mkdir()
        _write_idx_images(str(mdir / "train-images-idx3-ubyte"), imgs)
        _write_idx_labels(str(mdir / "train-labels-idx1-ubyte"),
                          labels)
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        x, y, synthetic = load_mnist(train=True)
        assert not synthetic
        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
            .list(DenseLayer(n_in=784, n_out=32, activation="relu"),
                  OutputLayer(n_in=32, n_out=10, activation="softmax",
                              loss="mcxent"))
            .build()).init()
        net.fit(x, y, epochs=15, batch_size=64)
        acc = float(np.mean(net.predict(x) == y.argmax(-1)))
        assert acc >= 0.95


class TestConfigTimeShapeErrors:
    def test_incompatible_vertex_fails_at_build_with_name(self):
        """Round-1 weak item 2: a misconfigured vertex must fail at
        build() with its name, not as an opaque trace error later."""
        from deeplearning4j_tpu.nn.graph import ElementWiseVertex
        from deeplearning4j_tpu.nn.inputs import InputType

        g = NeuralNetConfiguration.builder().seed(0).graph_builder()
        g.add_inputs("a", "b")
        g.set_input_types(InputType.feed_forward(4),
                          InputType.feed_forward(6))  # mismatched widths
        g.add_vertex("sum", ElementWiseVertex(op="add"), "a", "b")
        g.add_layer("out", OutputLayer(n_in=4, n_out=2,
                                       activation="softmax", loss="mcxent"),
                    "sum")
        g.set_outputs("out")
        with pytest.raises(ValueError, match="sum"):
            g.build()


class TestLFW:
    """LFW canned dataset (reference:
    datasets/iterator/impl/LFWDataSetIterator.java — the one SURVEY §2.2
    dataset missing through round 2)."""

    def test_synthetic_fallback_shapes(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data.datasets import (
            LFWDataSetIterator, load_lfw,
        )

        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))  # no lfw/
        x, y, names, synthetic = load_lfw(num_labels=4, num_examples=60)
        assert synthetic
        assert x.shape == (60, 64, 64, 3) and y.shape == (60, 4)
        assert len(names) == 4
        it = LFWDataSetIterator(batch_size=16, num_labels=4,
                                num_examples=60)
        assert it.synthetic
        ds = next(it)
        assert ds.features.shape == (16, 64, 64, 3)
        # deterministic surrogate: same call -> same data
        x2, _, _, _ = load_lfw(num_labels=4, num_examples=60)
        np.testing.assert_array_equal(x, x2)

    def _fake_lfw(self, tmp_path, people):
        from PIL import Image
        base = tmp_path / "lfw"
        for name, count, color in people:
            (base / name).mkdir(parents=True)
            for i in range(count):
                Image.new("RGB", (250, 250), color=color).save(
                    base / name / f"{name}_{i:04d}.jpg")
        return base

    def test_reads_directory_per_person_layout(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data.datasets import (
            LFWDataSetIterator, load_lfw,
        )

        self._fake_lfw(tmp_path, [("Aaron_Alpha", 4, (200, 30, 30)),
                                  ("Betty_Beta", 6, (30, 200, 30)),
                                  ("Carl_Gamma", 2, (30, 30, 200))])
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        x, y, names, synthetic = load_lfw(height=32, width=32)
        assert not synthetic
        assert x.shape == (12, 32, 32, 3) and y.shape == (12, 3)
        # identity with the most images is label 0 (useSubset ordering)
        assert names[0] == "Betty_Beta"
        # pixel content decoded: Betty's images are green-dominant
        betty = x[y.argmax(-1) == 0]
        assert betty[:, :, :, 1].mean() > betty[:, :, :, 0].mean()

        # num_labels keeps the most-photographed people only
        x2, y2, names2, _ = load_lfw(height=32, width=32, num_labels=2)
        assert names2 == ["Betty_Beta", "Aaron_Alpha"]
        assert x2.shape[0] == 10 and y2.shape[1] == 2

    def test_train_test_split_partitions(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data.datasets import LFWDataSetIterator

        self._fake_lfw(tmp_path, [("A_A", 5, (9, 9, 9)),
                                  ("B_B", 5, (99, 99, 99))])
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        tr = LFWDataSetIterator(batch_size=8, image_shape=(16, 16, 3),
                                train=True, shuffle=False)
        te = LFWDataSetIterator(batch_size=8, image_shape=(16, 16, 3),
                                train=False, shuffle=False)
        assert not tr.synthetic
        n_tr = sum(b.features.shape[0] for b in tr)
        n_te = sum(b.features.shape[0] for b in te)
        assert n_tr == 8 and n_te == 2     # 80/20 of 10

    def test_empty_lfw_dir_falls_back_to_synthetic(self, tmp_path,
                                                   monkeypatch):
        from deeplearning4j_tpu.data.datasets import load_lfw

        (tmp_path / "lfw").mkdir()          # exists but empty
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        x, y, names, synthetic = load_lfw(num_labels=3, num_examples=12)
        assert synthetic
        assert x.shape == (12, 64, 64, 3) and y.shape == (12, 3)
