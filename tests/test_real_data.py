"""Real-data end-to-end tests (round-1 verdict weak item 6: 'no end-to-end
accuracy demonstration on real data anywhere').

- Iris (embedded, real measurements): train → evaluate() accuracy.
- MNIST cache layout: genuine IDX-format files written into the cache
  directory exercise the native IDX decoder + loader path (synthetic
  fallback must NOT trigger), mirroring the reference's
  `datasets/mnist/` binary readers.
"""

import gzip
import os
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.data.datasets import (
    IrisDataSetIterator, MnistDataSetIterator, load_iris, load_mnist,
)
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optim.updaters import Adam


class TestIrisRealData:
    def test_train_and_evaluate_accuracy(self):
        """The reference's integration style: fit on Iris, assert
        accuracy via the Evaluation pipeline (not raw argmax)."""
        x, y = load_iris()
        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(7).updater(Adam(0.05))
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss="mcxent"))
            .build()).init()
        net.fit(x, y, epochs=60, batch_size=50)
        ev = net.evaluate(IrisDataSetIterator(batch_size=50))
        assert ev.accuracy() >= 0.95
        assert ev.f1() >= 0.9
        # the stats render includes the confusion matrix
        assert "Confusion" in ev.stats()


def _write_idx_images(path, images: np.ndarray):
    """Genuine IDX3 layout: magic 0x00000803, dims, raw uint8."""
    n, h, w = images.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">IIII", 0x00000803, n, h, w))
        f.write(images.astype(np.uint8).tobytes())


def _write_idx_labels(path, labels: np.ndarray):
    with open(path, "wb") as f:
        f.write(struct.pack(">II", 0x00000801, len(labels)))
        f.write(labels.astype(np.uint8).tobytes())


class TestMnistCacheLayout:
    def test_idx_files_load_not_synthetic(self, tmp_path, monkeypatch):
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (32, 28, 28)).astype(np.uint8)
        labels = rng.integers(0, 10, 32).astype(np.uint8)
        mdir = tmp_path / "mnist"
        mdir.mkdir()
        _write_idx_images(str(mdir / "train-images-idx3-ubyte"), imgs)
        _write_idx_labels(str(mdir / "train-labels-idx1-ubyte"), labels)
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))

        x, y, synthetic = load_mnist(train=True)
        assert not synthetic, "real IDX files must not hit the fallback"
        assert x.shape == (32, 784) and y.shape == (32, 10)
        # pixel values decoded and scaled to [0,1]
        np.testing.assert_allclose(
            x[0], imgs[0].reshape(784).astype(np.float32) / 255.0,
            rtol=1e-6)
        np.testing.assert_array_equal(y.argmax(-1), labels)

        it = MnistDataSetIterator(batch_size=16, train=True, shuffle=False)
        assert not it.synthetic
        ds = next(it)
        assert ds.features.shape == (16, 784)

    def test_gzipped_idx_files_load(self, tmp_path, monkeypatch):
        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, (8, 28, 28)).astype(np.uint8)
        labels = rng.integers(0, 10, 8).astype(np.uint8)
        mdir = tmp_path / "mnist"
        mdir.mkdir()
        raw_i = struct.pack(">IIII", 0x00000803, 8, 28, 28) + imgs.tobytes()
        raw_l = struct.pack(">II", 0x00000801, 8) + labels.tobytes()
        with gzip.open(str(mdir / "t10k-images-idx3-ubyte.gz"), "wb") as f:
            f.write(raw_i)
        with gzip.open(str(mdir / "t10k-labels-idx1-ubyte.gz"), "wb") as f:
            f.write(raw_l)
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        x, y, synthetic = load_mnist(train=False)
        assert not synthetic
        assert x.shape == (8, 784)
        np.testing.assert_array_equal(y.argmax(-1), labels)

    def test_trains_on_real_idx_digits(self, tmp_path, monkeypatch):
        """End-to-end: separable 'digit' images through the real IDX
        pipeline train to high accuracy."""
        rng = np.random.default_rng(2)
        n, classes = 256, 4
        labels = rng.integers(0, classes, n).astype(np.uint8)
        imgs = np.zeros((n, 28, 28), np.uint8)
        for i, c in enumerate(labels):   # bright quadrant per class
            r, col = divmod(int(c), 2)
            imgs[i, r * 14:(r + 1) * 14, col * 14:(col + 1) * 14] = \
                200 + rng.integers(0, 56)
        mdir = tmp_path / "mnist"
        mdir.mkdir()
        _write_idx_images(str(mdir / "train-images-idx3-ubyte"), imgs)
        _write_idx_labels(str(mdir / "train-labels-idx1-ubyte"),
                          labels)
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        x, y, synthetic = load_mnist(train=True)
        assert not synthetic
        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
            .list(DenseLayer(n_in=784, n_out=32, activation="relu"),
                  OutputLayer(n_in=32, n_out=10, activation="softmax",
                              loss="mcxent"))
            .build()).init()
        net.fit(x, y, epochs=15, batch_size=64)
        acc = float(np.mean(net.predict(x) == y.argmax(-1)))
        assert acc >= 0.95


class TestConfigTimeShapeErrors:
    def test_incompatible_vertex_fails_at_build_with_name(self):
        """Round-1 weak item 2: a misconfigured vertex must fail at
        build() with its name, not as an opaque trace error later."""
        from deeplearning4j_tpu.nn.graph import ElementWiseVertex
        from deeplearning4j_tpu.nn.inputs import InputType

        g = NeuralNetConfiguration.builder().seed(0).graph_builder()
        g.add_inputs("a", "b")
        g.set_input_types(InputType.feed_forward(4),
                          InputType.feed_forward(6))  # mismatched widths
        g.add_vertex("sum", ElementWiseVertex(op="add"), "a", "b")
        g.add_layer("out", OutputLayer(n_in=4, n_out=2,
                                       activation="softmax", loss="mcxent"),
                    "sum")
        g.set_outputs("out")
        with pytest.raises(ValueError, match="sum"):
            g.build()
