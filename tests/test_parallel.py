"""Parallelism tests on the 8-device virtual CPU mesh.

Mirrors the reference's multi-device tests (ParallelWrapper/ParallelInference
suites run on CPU threads — SURVEY §4 'Multi-device parallel tests'), with
the TPU twist: correctness is asserted against single-device training
(sharded training must match unsharded numerics).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu import InputType
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, MultiHeadAttention, OutputLayer,
)
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.optim.updaters import Sgd, Adam
from deeplearning4j_tpu.parallel import (
    ParallelInference, ParallelWrapper, make_mesh,
)
from deeplearning4j_tpu.parallel.sharding import (
    ShardingRules, fsdp_rules, shard_params, tensor_parallel_rules,
)
from deeplearning4j_tpu.parallel.ring_attention import (
    attention, ring_self_attention,
)


def _toy(n=256, d=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal((d, classes))
    y = np.eye(classes, dtype=np.float32)[(x @ w).argmax(-1)]
    return x, y


def _net(seed=7, d=8, classes=3, updater=None):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(seed).updater(updater or Sgd(0.1)).activation("tanh")
         .list(DenseLayer(n_out=16),
               OutputLayer(n_out=classes, activation="softmax"))
         .set_input_type(InputType.feed_forward(d))
         .build())).init()


class TestMesh:
    def test_make_mesh_default(self, devices8):
        mesh = make_mesh()
        assert mesh.shape["data"] == 8

    def test_make_mesh_2d_with_wildcard(self, devices8):
        mesh = make_mesh({"data": -1, "model": 2})
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    def test_mesh_size_mismatch(self, devices8):
        with pytest.raises(ValueError):
            make_mesh({"data": 3})


class TestParallelWrapper:
    def test_dp_matches_single_device(self, devices8):
        """Sharded DP step == single-device step (allreduce is exact mean)."""
        x, y = _toy(n=64)
        a = _net(seed=7)
        b = _net(seed=7)
        np.testing.assert_allclose(a.params(), b.params())

        a.fit(x, y, epochs=3, batch_size=64)

        pw = ParallelWrapper(b, mesh=make_mesh({"data": 8}), prefetch_buffer=0)
        pw.fit(x, y, epochs=3, batch_size=64)
        np.testing.assert_allclose(a.params(), b.params(), rtol=2e-4, atol=1e-6)

    def test_dp_loss_decreases_with_adam(self, devices8):
        x, y = _toy(n=256)
        net = _net(updater=Adam(1e-2))
        pw = ParallelWrapper(net, mesh=make_mesh({"data": 8}))
        s0 = net.score(x, y)
        pw.fit(x, y, epochs=10, batch_size=64)
        assert net.score(x, y) < s0 * 0.7

    def test_partial_batch_padding(self, devices8):
        x, y = _toy(n=100)  # not divisible by 8
        net = _net()
        pw = ParallelWrapper(net, mesh=make_mesh({"data": 8}), prefetch_buffer=0)
        pw.fit(x, y, epochs=1, batch_size=64)  # batches: 64 + 36→40
        assert np.isfinite(net.score_)

    def test_fsdp_param_sharding(self, devices8):
        x, y = _toy(n=64, d=8)
        net = _net()
        rules = fsdp_rules([l.name for l in net.layers])
        pw = ParallelWrapper(net, mesh=make_mesh({"data": 8}),
                             param_rules=rules, prefetch_buffer=0)
        w = net.params_tree[net.layers[0].name]["W"]
        assert len(w.sharding.spec) >= 1 and w.sharding.spec[0] == "data"
        pw.fit(x, y, epochs=2, batch_size=64)
        assert np.isfinite(net.score_)


class TestTensorParallel:
    def test_tp_output_matches_replicated(self, devices8):
        """TP-sharded forward == replicated forward (GSPMD exactness)."""
        mesh = make_mesh({"data": 4, "model": 2})
        net = _net(d=8, classes=3)
        x, _ = _toy(n=32)
        expected = np.asarray(net.output(x))

        rules = tensor_parallel_rules([l.name for l in net.layers])
        sharded = shard_params(net.params_tree, mesh, rules)

        def fwd(params, feats):
            y, _, _, _ = net._forward(params, {}, feats, train=False, rng=None)
            return y

        out = jax.jit(fwd)(sharded, jnp.asarray(x))
        np.testing.assert_allclose(expected, np.asarray(out), rtol=1e-5)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_attention(self, devices8, causal):
        mesh = make_mesh({"seq": 8})
        rng = np.random.default_rng(0)
        B, T, H, D = 2, 32, 4, 16
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        dense = attention(q, k, v, causal=causal)
        ring = ring_self_attention(q, k, v, mesh, axis="seq", causal=causal)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(ring), rtol=2e-4, atol=2e-5)

    def test_gradients_flow_through_ring(self, devices8):
        mesh = make_mesh({"seq": 8})
        rng = np.random.default_rng(1)
        B, T, H, D = 1, 16, 2, 8
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)

        def loss_ring(q, k, v):
            return jnp.sum(ring_self_attention(q, k, v, mesh, causal=True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(attention(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gd), rtol=5e-4, atol=5e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_path_matches_dense(self, devices8, causal):
        """Pallas-kernel per-shard path (use_flash) — same answer as the
        XLA online-softmax path and the dense oracle."""
        mesh = make_mesh({"seq": 8})
        rng = np.random.default_rng(2)
        B, T, H, D = 2, 32, 4, 16
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        dense = attention(q, k, v, causal=causal)
        ring = ring_self_attention(q, k, v, mesh, axis="seq", causal=causal,
                                   use_flash=True, interpret=True)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(ring), rtol=2e-4, atol=2e-5)

    def test_flash_path_gradients(self, devices8):
        """dq/dk/dv through ppermute + lse merge + Pallas backward."""
        mesh = make_mesh({"seq": 8})
        rng = np.random.default_rng(3)
        B, T, H, D = 1, 16, 2, 8
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)

        def loss_flash(q, k, v):
            return jnp.sum(ring_self_attention(
                q, k, v, mesh, causal=True, use_flash=True,
                interpret=True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(attention(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gd), rtol=5e-4, atol=5e-5)


class TestSequenceParallelContext:
    """sequence_parallel(mesh): model-level sequence parallelism — the
    attention layers swap their core to ring attention at trace time."""

    def test_layer_swaps_to_ring_and_matches(self, devices8):
        import jax as _jax
        from deeplearning4j_tpu.parallel.ring_attention import (
            sequence_parallel,
        )
        mesh = make_mesh({"seq": 8})
        layer = MultiHeadAttention(num_heads=2, n_in=8, n_out=8,
                                   causal=True)
        layer = layer.infer_n_in(InputType.recurrent(8))
        params, _ = layer.init_params(_jax.random.PRNGKey(0),
                                      InputType.recurrent(8))
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 16, 8)), jnp.float32)
        base, _ = layer.apply(params, x)
        with sequence_parallel(mesh):
            sp, _ = layer.apply(params, x)
        np.testing.assert_allclose(np.asarray(sp), np.asarray(base),
                                   rtol=2e-4, atol=2e-5)

    def test_net_jit_cache_partitioned_by_context(self, devices8):
        """A dense-compiled output() must not be reused inside the
        context (and vice versa) — the caches are per-context."""
        from deeplearning4j_tpu.parallel.ring_attention import (
            sequence_parallel,
        )
        from deeplearning4j_tpu.zoo.transformer import (
            TextGenerationTransformer,
        )
        mesh = make_mesh({"seq": 8})
        net = TextGenerationTransformer(num_classes=9, input_shape=(16, 1),
                                        d_model=16, num_heads=2,
                                        num_blocks=1).init()
        x = np.random.default_rng(1).integers(
            0, 9, (2, 16, 1)).astype(np.float32)
        dense = np.asarray(net.output(x))
        with sequence_parallel(mesh):
            sp = np.asarray(net.output(x))
        again = np.asarray(net.output(x))
        np.testing.assert_allclose(sp, dense, rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(again, dense, rtol=1e-6, atol=1e-7)
        assert len(net._jit_caches) == 2   # one per context

    def test_mask_bypasses_ring_with_warning(self, devices8):
        """Inside sequence_parallel, a padding mask forces the dense
        path — that degradation must be loud (warning), not silent."""
        import warnings as _warnings

        import jax as _jax
        from deeplearning4j_tpu.parallel.ring_attention import (
            sequence_parallel,
        )
        mesh = make_mesh({"seq": 8})
        layer = MultiHeadAttention(num_heads=2, n_in=8, n_out=8,
                                   causal=True)
        layer = layer.infer_n_in(InputType.recurrent(8))
        params, _ = layer.init_params(_jax.random.PRNGKey(0),
                                      InputType.recurrent(8))
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 16, 8)), jnp.float32)
        fmask = jnp.ones((2, 16), jnp.float32)
        with sequence_parallel(mesh):
            with _warnings.catch_warnings(record=True) as caught:
                _warnings.simplefilter("always")
                layer.apply(params, x, mask=fmask)
        assert any("ring is bypassed" in str(w.message) for w in caught)

    def test_moe_block_composes_with_context(self, devices8):
        """A MoE transformer block under sequence_parallel: the attention
        core swaps to the ring while expert routing is untouched — the
        output must equal the dense-context forward."""
        import jax as _jax
        from deeplearning4j_tpu.nn.layers.attention import (
            TransformerEncoderBlock,
        )
        from deeplearning4j_tpu.parallel.ring_attention import (
            sequence_parallel,
        )
        mesh = make_mesh({"seq": 8})
        blk = TransformerEncoderBlock(n_in=8, num_heads=2, causal=True,
                                      n_experts=2)
        params, _ = blk.init_params(_jax.random.PRNGKey(0),
                                    InputType.recurrent(8))
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 16, 8)), jnp.float32)
        dense, _ = blk.apply(params, x)
        with sequence_parallel(mesh):
            sp, _ = blk.apply(params, x)
        np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                                   rtol=2e-4, atol=2e-5)

    def test_batched_inference_worker_sees_context(self, devices8):
        """BATCHED-mode ParallelInference traces in a worker thread,
        which starts from an empty contextvars Context — the caller's
        sequence_parallel context must be captured per request and the
        forward run under it (observable via the per-context cache key)."""
        from deeplearning4j_tpu.parallel import ParallelInference
        from deeplearning4j_tpu.parallel.ring_attention import (
            sequence_parallel,
        )
        net = MultiLayerNetwork(
            (NeuralNetConfiguration.builder()
             .seed(0).updater(Sgd(0.1)).activation("relu")
             .list(DenseLayer(n_out=8),
                   OutputLayer(n_out=3, activation="softmax"))
             .set_input_type(InputType.feed_forward(6))
             .build())).init()
        pi = ParallelInference(net, mode="batched", max_batch_size=8)
        try:
            seq_mesh = make_mesh({"seq": 8})
            with sequence_parallel(seq_mesh):
                y = pi.output(np.zeros((2, 6), np.float32))
            assert y.shape == (2, 3)
            assert any(k is not None for k in pi._jit_caches), \
                "worker thread traced outside the caller's context"
        finally:
            pi.shutdown()

    def test_fit_under_context(self, devices8):
        from deeplearning4j_tpu.parallel.ring_attention import (
            sequence_parallel,
        )
        from deeplearning4j_tpu.zoo.transformer import (
            TextGenerationTransformer,
        )
        mesh = make_mesh({"seq": 8})
        net = TextGenerationTransformer(num_classes=9, input_shape=(16, 1),
                                        d_model=16, num_heads=2,
                                        num_blocks=1).init()
        rng = np.random.default_rng(2)
        x = rng.integers(0, 9, (4, 16, 1)).astype(np.float32)
        y = np.eye(9, dtype=np.float32)[rng.integers(0, 9, (4, 16))]
        with sequence_parallel(mesh):
            net.fit(x, y, epochs=2, batch_size=4)
        assert net.score_ is not None and np.isfinite(net.score_)


class TestAttentionLayer:
    def test_mha_in_network(self):
        from deeplearning4j_tpu.nn.layers import GlobalPoolingLayer
        conf = (NeuralNetConfiguration.builder()
                .seed(0).updater(Adam(1e-2)).activation("identity")
                .list(MultiHeadAttention(num_heads=2),
                      GlobalPoolingLayer(pooling="avg"),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.recurrent(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 10, 8)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        net.fit(x, y, epochs=3, batch_size=8)
        assert np.asarray(net.output(x)).shape == (16, 2)

    def test_attn_dropout_perturbs_training_only(self):
        """attn_dropout must actually drop attention weights in training
        (it was once accepted-but-ignored config) and leave inference
        deterministic."""
        import jax as _jax
        import jax.numpy as _jnp
        layer = MultiHeadAttention(num_heads=2, n_in=8, n_out=8,
                                   attn_dropout=0.5)
        layer = layer.infer_n_in(InputType.recurrent(8))
        params, _ = layer.init_params(_jax.random.PRNGKey(0),
                                      InputType.recurrent(8))
        x = _jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 6, 8)), _jnp.float32)
        eval_out, _ = layer.apply(params, x, train=False)
        train1, _ = layer.apply(params, x, train=True,
                                rng=_jax.random.PRNGKey(1))
        train2, _ = layer.apply(params, x, train=True,
                                rng=_jax.random.PRNGKey(2))
        assert not np.allclose(np.asarray(train1), np.asarray(eval_out))
        assert not np.allclose(np.asarray(train1), np.asarray(train2))
        # rate 0 (or no rng): deterministic and equal to eval
        nodrop = MultiHeadAttention(num_heads=2, n_in=8, n_out=8)
        same, _ = nodrop.apply(params, x, train=True,
                               rng=_jax.random.PRNGKey(1))
        np.testing.assert_allclose(np.asarray(same),
                                   np.asarray(eval_out), rtol=1e-5,
                                   atol=1e-6)

    def test_mha_gradcheck(self):
        from deeplearning4j_tpu.nn.layers import GlobalPoolingLayer
        from deeplearning4j_tpu.gradientcheck import check_gradients
        conf = (NeuralNetConfiguration.builder()
                .seed(0).updater(Sgd(0.1)).activation("identity")
                .list(MultiHeadAttention(num_heads=2, n_out=4),
                      GlobalPoolingLayer(pooling="avg"),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.recurrent(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(2)
        x = rng.standard_normal((2, 5, 4))
        y = np.eye(2)[rng.integers(0, 2, 2)]
        assert check_gradients(net, x, y, subset=40)


class TestParallelInference:
    def test_batched_inference_matches_direct(self, devices8):
        net = _net()
        x, _ = _toy(n=40)
        direct = np.asarray(net.output(x))
        pi = ParallelInference(net, mesh=make_mesh({"data": 8}),
                               max_batch_size=64)
        try:
            got = pi.output(x)
            np.testing.assert_allclose(direct, got, rtol=1e-5)
            # concurrent requests
            import concurrent.futures as cf
            with cf.ThreadPoolExecutor(4) as ex:
                futs = [ex.submit(pi.output, x[i:i + 10])
                        for i in range(0, 40, 10)]
                outs = [f.result() for f in futs]
            np.testing.assert_allclose(
                direct, np.concatenate(outs, axis=0), rtol=1e-5)
        finally:
            pi.shutdown()

    def test_batched_inference_groups_by_context(self, devices8):
        """Coalescing must never mix requests from different
        sequence_parallel contexts into one batch (ADVICE r4): the whole
        batch is traced under the first arrival's context, and another
        context's mesh can impose incompatible sharding divisibility.
        Observable: each context gets its own trace-cache partition."""
        from deeplearning4j_tpu.parallel.ring_attention import (
            sequence_parallel,
        )
        net = _net()
        x, _ = _toy(n=8)
        direct = np.asarray(net.output(x))
        pi = ParallelInference(net, mesh=make_mesh({"data": 8}),
                               max_batch_size=64, max_wait_ms=300)
        seq_mesh = make_mesh({"seq": 8})
        try:
            import concurrent.futures as cf

            def in_ctx():
                with sequence_parallel(seq_mesh):
                    return pi.output(x)

            with cf.ThreadPoolExecutor(4) as ex:
                futs = [ex.submit(in_ctx), ex.submit(pi.output, x),
                        ex.submit(in_ctx), ex.submit(pi.output, x)]
                outs = [f.result(timeout=120) for f in futs]
            for o in outs:
                np.testing.assert_allclose(o, direct, rtol=1e-5)
            keys = set(pi._jit_caches)
            assert len(keys) == 2 and None in keys, (
                f"expected separate trace partitions per context, got "
                f"{keys}")
        finally:
            pi.shutdown()
