"""Per-example evaluation metadata + phase timing / NTP time source.

Reference: eval/meta/Prediction.java + RecordMetaData plumbing;
spark/stats/StatsUtils.java (HTML timeline export); spark/time/
NTPTimeSource.java + TimeSourceProvider.
"""

import os
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.eval import Evaluation
from deeplearning4j_tpu.eval.meta import Prediction, RecordMetaData


class TestEvalMetadata:
    def test_predictions_recorded_with_meta(self):
        ev = Evaluation(num_classes=3)
        labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 1]]
        preds = np.eye(3, dtype=np.float32)[[0, 2, 2, 1]]  # one error at i=1
        meta = [RecordMetaData("test.csv", i) for i in range(4)]
        ev.eval(labels, preds, record_meta=meta)
        errs = ev.get_prediction_errors()
        assert len(errs) == 1
        assert errs[0].actual == 1 and errs[0].predicted == 2
        assert errs[0].record_meta.location == 1
        assert "test.csv[1]" in str(errs[0])

    def test_by_class_accessors(self):
        ev = Evaluation(num_classes=2)
        labels = np.eye(2, dtype=np.float32)[[0, 0, 1, 1]]
        preds = np.eye(2, dtype=np.float32)[[0, 1, 1, 1]]
        meta = [RecordMetaData("m", i) for i in range(4)]
        ev.eval(labels, preds, record_meta=meta)
        assert len(ev.get_predictions_by_actual_class(0)) == 2
        assert len(ev.get_predictions_by_predicted_class(1)) == 3

    def test_meta_length_mismatch_raises(self):
        ev = Evaluation(num_classes=2)
        with pytest.raises(ValueError, match="record_meta"):
            ev.eval(np.eye(2, dtype=np.float32)[[0, 1]],
                    np.eye(2, dtype=np.float32)[[0, 1]],
                    record_meta=[RecordMetaData("m", 0)])

    def test_merge_carries_predictions(self):
        a, b = Evaluation(2), Evaluation(2)
        one = np.eye(2, dtype=np.float32)
        a.eval(one[[0]], one[[1]], record_meta=[RecordMetaData("a", 0)])
        b.eval(one[[1]], one[[1]], record_meta=[RecordMetaData("b", 0)])
        a.merge(b)
        assert len(a.predictions) == 2
        assert len(a.get_prediction_errors()) == 1

    def test_record_reader_collect_meta(self, tmp_path):
        from deeplearning4j_tpu.data.records import (
            CSVRecordReader, RecordReaderDataSetIterator,
        )

        p = tmp_path / "data.csv"
        p.write_text("1.0,2.0,0\n3.0,4.0,1\n5.0,6.0,0\n")
        rr = CSVRecordReader(str(p))
        it = RecordReaderDataSetIterator(rr, batch_size=2, num_classes=2,
                                         collect_meta=True)
        ds = next(it)
        assert it.last_meta is not None and len(it.last_meta) == 2
        assert it.last_meta[0].location == 0
        ds2 = next(it)
        assert it.last_meta[0].location == 2  # index continues across batches


class TestTimeSource:
    def test_system_clock(self):
        import time as _t
        from deeplearning4j_tpu.utils.timesource import SystemClockTimeSource

        ts = SystemClockTimeSource()
        assert abs(ts.current_time_millis() - _t.time() * 1000) < 2000

    def test_ntp_falls_back_gracefully_offline(self):
        from deeplearning4j_tpu.utils.timesource import NTPTimeSource

        ts = NTPTimeSource(server="127.0.0.1", timeout=0.2)
        # no NTP server there: unsynchronized but still serving time
        assert not ts.synchronized_
        assert ts.current_time_millis() > 0

    def test_ntp_refresh_thread_exits_when_source_dropped(self):
        """The refresh thread must hold only a weakref — a bound-method
        target would pin the instance and leak the thread forever."""
        import gc
        from deeplearning4j_tpu.utils.timesource import NTPTimeSource

        ts = NTPTimeSource(server="127.0.0.1", timeout=0.1,
                           update_freq_ms=0)  # clamped to 1s internally
        th = ts._thread
        del ts
        gc.collect()
        th.join(timeout=5)
        assert not th.is_alive()

    def test_provider_singleton_and_override(self):
        from deeplearning4j_tpu.utils.timesource import (
            SystemClockTimeSource, TimeSourceProvider,
        )

        TimeSourceProvider.set_instance(None)
        a = TimeSourceProvider.get_instance()
        assert isinstance(a, SystemClockTimeSource)
        assert TimeSourceProvider.get_instance() is a
        TimeSourceProvider.set_instance(None)

    def test_sntp_packet_parsing(self, monkeypatch):
        """Feed a canned RFC4330 response through the socket seam."""
        import deeplearning4j_tpu.utils.timesource as tsm

        class FakeSocket:
            def __init__(self, *a, **k):
                pass

            def __enter__(self):
                return self

            def __exit__(self, *a):
                pass

            def settimeout(self, t):
                pass

            def sendto(self, data, addr):
                assert data[0] == 0x1B and len(data) == 48

            def recvfrom(self, n):
                import time as _t
                now = _t.time() + tsm._NTP_EPOCH_DELTA + 1.5  # +1.5s offset
                sec = int(now)
                frac = int((now - sec) * 2**32)
                resp = bytearray(48)
                struct.pack_into("!II", resp, 32, sec, frac)
                struct.pack_into("!II", resp, 40, sec, frac)
                return bytes(resp), ("server", 123)

        monkeypatch.setattr(tsm.socket, "socket",
                            lambda *a, **k: FakeSocket())
        off = tsm.sntp_offset_ms("fake")
        assert 1000 < off < 2000  # ~1.5s offset recovered


class TestTimelineExport:
    def test_export_html(self, tmp_path):
        from deeplearning4j_tpu.parallel import (
            PhaseStats, export_timeline_html,
        )

        stats = [
            PhaseStats(0, 64, 120.0, 30.0, 5.0, 1.2, start_ms=1000.0),
            PhaseStats(1, 64, 110.0, 28.0, 4.0, 1.1, start_ms=1200.0),
        ]
        p = str(tmp_path / "timeline.html")
        html = export_timeline_html(stats, p)
        assert os.path.exists(p)
        assert "<svg" in html and "fit" in html and "aggregate" in html
        assert "Per-split phase timings" in html
        assert "1.20000" in html  # score in the table

    def test_training_master_stats_have_timestamps(self):
        from deeplearning4j_tpu.models import MultiLayerNetwork
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.parallel import (
            ParameterAveragingTrainingMaster, export_timeline_html,
        )

        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(0)
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_in=8, n_out=2, activation="softmax",
                              loss="mcxent"))
            .build()).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((128, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 128)]
        tm = ParameterAveragingTrainingMaster(
            num_workers=2, batch_size=16, averaging_frequency=2,
            collect_training_stats=True)
        tm.execute_training(net, x, y)
        stats = tm.training_stats()
        assert stats and all(s.start_ms > 0 for s in stats)
        assert stats == sorted(stats, key=lambda s: s.start_ms)


class TestEvalRobustness:
    def test_confusion_matrix_grows_across_batches(self):
        ev = Evaluation()
        ev.eval_indices([0, 1], [0, 1])      # first batch: classes 0-1
        ev.eval_indices([3], [3])            # later batch: class 3
        assert ev.num_classes == 4
        assert ev.accuracy() == 1.0

    def test_empty_batches_keep_metrics_defined(self):
        ev = Evaluation()
        ev.eval(np.empty((0, 3), np.float32), np.empty((0, 3), np.float32))
        assert ev.accuracy() == 0.0
        assert ev.precision() == 0.0 and ev.recall() == 0.0
        assert "Accuracy" in ev.stats()  # matrix sized from softmax width
        # a never-fed Evaluation stays well-defined too
        fresh = Evaluation()
        assert fresh.accuracy() == 0.0
        assert "no examples" in fresh.stats()

    def test_evaluate_requires_init(self):
        from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
        from deeplearning4j_tpu.models import MultiLayerNetwork
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(0)
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_in=8, n_out=2, activation="softmax",
                              loss="mcxent"))
            .build())  # no init()
        x = np.zeros((4, 4), np.float32)
        y = np.eye(2, dtype=np.float32)[[0, 1, 0, 1]]
        with pytest.raises(RuntimeError, match="not initialized"):
            net.evaluate(ArrayDataSetIterator(x, y, 2))

    def test_per_example_mask_on_2d_labels(self):
        """Padded batches: a per-example labels_mask must exclude padding
        rows from the confusion matrix."""
        ev = Evaluation()
        labels = np.eye(2, dtype=np.float32)[[0, 1, 0, 0]]
        preds = np.eye(2, dtype=np.float32)[[0, 1, 1, 1]]  # rows 2-3 'wrong'
        ev.eval(labels, preds, mask=np.array([1, 1, 0, 0]))
        assert ev.accuracy() == 1.0            # masked rows not counted
        assert int(ev.confusion.matrix.sum()) == 2


class TestRecordReaderMultiDataSetIterator:
    """Reference: datasets/datavec/RecordReaderMultiDataSetIterator.java —
    multi-input/output column mappings feeding ComputationGraph training."""

    def _csv(self, tmp_path):
        p = tmp_path / "multi.csv"
        rows = ["%d,%d,%d,%d,%d" % (i, i + 1, i + 2, i + 3, i % 3)
                for i in range(10)]
        p.write_text("\n".join(rows) + "\n")
        return str(p)

    def test_builder_mappings(self, tmp_path):
        from deeplearning4j_tpu.data.records import (
            CSVRecordReader, RecordReaderMultiDataSetIterator,
        )

        it = (RecordReaderMultiDataSetIterator.builder(4)
              .add_reader("csv", CSVRecordReader(self._csv(tmp_path)))
              .add_input("csv", 0, 1)
              .add_input("csv", 2, 3)
              .add_output_one_hot("csv", 4, 3)
              .build())
        mds = next(it)
        assert len(mds.features) == 2 and len(mds.labels) == 1
        assert mds.features[0].shape == (4, 2)
        assert mds.features[1].shape == (4, 2)
        assert mds.labels[0].shape == (4, 3)
        np.testing.assert_array_equal(mds.features[0][0], [0, 1])
        np.testing.assert_array_equal(mds.labels[0][0],
                                      [1, 0, 0])  # class 0

    def test_feeds_computation_graph(self, tmp_path):
        from deeplearning4j_tpu.data.records import (
            CSVRecordReader, RecordReaderMultiDataSetIterator,
        )
        from deeplearning4j_tpu.models import ComputationGraph
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.graph import MergeVertex
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

        g = NeuralNetConfiguration.builder().seed(0).graph_builder()
        g.add_inputs("a", "b")
        g.set_input_types(InputType.feed_forward(2),
                          InputType.feed_forward(2))
        g.add_layer("da", DenseLayer(n_in=2, n_out=4, activation="tanh"),
                    "a")
        g.add_layer("db", DenseLayer(n_in=2, n_out=4, activation="tanh"),
                    "b")
        g.add_vertex("m", MergeVertex(), "da", "db")
        g.add_layer("out", OutputLayer(n_in=8, n_out=3,
                                       activation="softmax", loss="mcxent"),
                    "m")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        it = (RecordReaderMultiDataSetIterator.builder(5)
              .add_reader("csv", CSVRecordReader(self._csv(tmp_path)))
              .add_input("csv", 0, 1)
              .add_input("csv", 2, 3)
              .add_output_one_hot("csv", 4, 3)
              .build())
        for mds in it:
            net.fit(mds)
        assert np.isfinite(net.score_)

    def test_validation(self, tmp_path):
        from deeplearning4j_tpu.data.records import (
            RecordReaderMultiDataSetIterator,
        )

        with pytest.raises(ValueError, match="unknown reader"):
            (RecordReaderMultiDataSetIterator.builder(2)
             .add_reader("a", None)
             .add_input("missing", 0, 1).build())
        with pytest.raises(ValueError, match="at least one"):
            RecordReaderMultiDataSetIterator.builder(2).build()

    def test_unmapped_string_columns_ok_and_ranges_validated(self, tmp_path):
        from deeplearning4j_tpu.data.records import (
            CollectionRecordReader, RecordReaderMultiDataSetIterator,
        )

        recs = [["1.0", "2.0", "some_id", "0"],
                ["3.0", "4.0", "other_id", "2"]]
        it = (RecordReaderMultiDataSetIterator.builder(2)
              .add_reader("r", CollectionRecordReader(recs))
              .add_input("r", 0, 1)
              .add_output_one_hot("r", 3, 3)
              .build())
        mds = next(it)   # the string column is unmapped → no crash
        np.testing.assert_array_equal(mds.labels[0].argmax(-1), [0, 2])

        bad = (RecordReaderMultiDataSetIterator.builder(2)
               .add_reader("r", CollectionRecordReader(recs))
               .add_input("r", 0, 10)
               .add_output_one_hot("r", 3, 3)
               .build())
        with pytest.raises(ValueError, match="out of bounds"):
            next(bad)

        neg = (RecordReaderMultiDataSetIterator.builder(1)
               .add_reader("r", CollectionRecordReader([["1", "-1"]]))
               .add_input("r", 0, 0)
               .add_output_one_hot("r", 1, 3)
               .build())
        with pytest.raises(ValueError, match="outside"):
            next(neg)


class TestModelLevelEvaluators:
    """Reference: MultiLayerNetwork.evaluateRegression:2668 /
    evaluateROC:2679 / evaluateROCMultiClass:2690 (+ the CG twins)."""

    def _class_net(self, n_out=2):
        from deeplearning4j_tpu.models import MultiLayerNetwork
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.optim.updaters import Adam

        return MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(0).updater(Adam(0.05))
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  OutputLayer(n_in=16, n_out=n_out, activation="softmax",
                              loss="mcxent"))
            .build()).init()

    def test_evaluate_roc(self):
        from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator

        rng = np.random.default_rng(0)
        x = rng.standard_normal((200, 4)).astype(np.float32)
        yi = (x[:, 0] > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[yi]
        net = self._class_net()
        net.fit(x, y, epochs=30, batch_size=64)
        roc = net.evaluate_roc(ArrayDataSetIterator(x, y, 64))
        assert roc.calculate_auc() > 0.9

    def test_evaluate_roc_multi_class(self):
        from deeplearning4j_tpu.data.datasets import load_iris
        from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator

        x, y = load_iris()
        net = self._class_net(n_out=3)
        net.fit(x, y, epochs=40, batch_size=50)
        roc = net.evaluate_roc_multi_class(ArrayDataSetIterator(x, y, 50))
        assert roc.average_auc() > 0.9

    def test_evaluate_regression(self):
        from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
        from deeplearning4j_tpu.models import MultiLayerNetwork
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.optim.updaters import Adam

        rng = np.random.default_rng(1)
        x = rng.standard_normal((256, 3)).astype(np.float32)
        y = (x @ np.array([[1.0], [2.0], [-1.0]], np.float32)
             + 0.05 * rng.standard_normal((256, 1)).astype(np.float32))
        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(0).updater(Adam(0.02))
            .list(DenseLayer(n_in=3, n_out=16, activation="tanh"),
                  OutputLayer(n_in=16, n_out=1, activation="identity",
                              loss="mse"))
            .build()).init()
        net.fit(x, y, epochs=60, batch_size=64)
        re = net.evaluate_regression(ArrayDataSetIterator(x, y, 64))
        assert re.correlation_r2(0) > 0.8

    def test_graph_twins_exist(self):
        from deeplearning4j_tpu.models import ComputationGraph

        for m in ("evaluate_regression", "evaluate_roc",
                  "evaluate_roc_multi_class"):
            assert hasattr(ComputationGraph, m)

    def test_roc_honors_labels_mask(self):
        """ROC doesn't understand masks; run_evaluation must drop masked
        rows before feeding it."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator

        rng = np.random.default_rng(2)
        x = rng.standard_normal((128, 4)).astype(np.float32)
        yi = (x[:, 0] > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[yi]
        net = self._class_net()
        net.fit(x, y, epochs=30, batch_size=64)

        # corrupt the last 64 labels, then mask them out — AUC must stay
        # high because those rows are excluded
        y_bad = y.copy()
        y_bad[64:] = y_bad[64:][:, ::-1]
        mask = np.ones(128, np.float32)
        mask[64:] = 0

        class It:
            def __iter__(self):
                yield DataSet(x, y_bad, None, mask)
            def reset(self):
                pass

        roc = net.evaluate_roc(It())
        assert roc.calculate_auc() > 0.9

    def test_roc_multidataset_iterator(self, tmp_path):
        """CG evaluators accept MultiDataSet iterators (first output)."""
        from deeplearning4j_tpu.data.dataset import MultiDataSet
        from deeplearning4j_tpu.models import ComputationGraph
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.optim.updaters import Adam

        g = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.05))
             .graph_builder())
        g.add_inputs("in")
        g.set_input_types(InputType.feed_forward(4))
        g.add_layer("h", DenseLayer(n_in=4, n_out=8, activation="tanh"),
                    "in")
        g.add_layer("out", OutputLayer(n_in=8, n_out=2,
                                       activation="softmax", loss="mcxent"),
                    "h")
        g.set_outputs("out")
        net = ComputationGraph(g.build()).init()
        rng = np.random.default_rng(3)
        x = rng.standard_normal((96, 4)).astype(np.float32)
        yi = (x[:, 0] > 0).astype(int)
        y = np.eye(2, dtype=np.float32)[yi]
        net.fit(x, y, epochs=30, batch_size=32)

        class It:
            def __iter__(self):
                yield MultiDataSet([x], [y])
            def reset(self):
                pass

        roc = net.evaluate_roc(It())
        assert roc.calculate_auc() > 0.9


class TestPredictionErrorWorkflow:
    """The full 'which examples were misclassified' loop: meta-collecting
    iterator -> model.evaluate -> get_prediction_errors -> top confusions
    -> load the original records back. Reference: eval/meta/Prediction.java
    getRecord + Evaluation.getPredictions* + RecordReaderDataSetIterator
    .loadFromMetaData."""

    def _csv(self, tmp_path, n=48):
        rng = np.random.default_rng(9)
        x = rng.standard_normal((n, 4))
        y = (x @ np.asarray([1.0, -1.0, 0.5, 0.0]) > 0).astype(int)
        p = tmp_path / "data.csv"
        with open(p, "w") as f:
            for xi, yi in zip(x, y):
                f.write(",".join(f"{v:.6f}" for v in xi) + f",{yi}\n")
        return str(p)

    def _fit_net(self, path):
        from deeplearning4j_tpu import InputType
        from deeplearning4j_tpu.data.records import (
            CSVRecordReader, RecordReaderDataSetIterator,
        )
        from deeplearning4j_tpu.models import MultiLayerNetwork
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.optim.updaters import Sgd

        net = MultiLayerNetwork(
            (NeuralNetConfiguration.builder()
             .seed(0).updater(Sgd(0.3)).activation("tanh")
             .list(DenseLayer(n_out=8),
                   OutputLayer(n_out=2, activation="softmax"))
             .set_input_type(InputType.feed_forward(4))
             .build())).init()
        it = RecordReaderDataSetIterator(
            CSVRecordReader(path), batch_size=16, num_classes=2)
        for _ in range(10):
            for ds in it:
                net.fit(ds.features, ds.labels, epochs=1,
                        batch_size=ds.features.shape[0])
        return net

    def test_evaluate_collects_meta_and_loads_records(self, tmp_path):
        from deeplearning4j_tpu.data.records import (
            CSVRecordReader, RecordReaderDataSetIterator,
        )

        path = self._csv(tmp_path)
        net = self._fit_net(path)
        it = RecordReaderDataSetIterator(
            CSVRecordReader(path), batch_size=16, num_classes=2,
            collect_meta=True)
        ev = net.evaluate(it)
        assert len(ev.predictions) == 48      # every example got a record
        errs = ev.get_prediction_errors()
        assert len(errs) == 48 - int(
            ev.confusion.matrix.trace())      # errors == off-diagonal count
        # confusion-cell accessors agree with the matrix
        for a in range(2):
            for p in range(2):
                assert len(ev.get_predictions(a, p)) == \
                    int(ev.confusion.matrix[a, p])
        # load the original CSV rows behind the first few errors
        it2 = RecordReaderDataSetIterator(
            CSVRecordReader(path), batch_size=16, num_classes=2)
        if errs:
            ds = it2.load_from_meta_data([e.record_meta for e in errs[:3]])
            assert ds.features.shape == (min(3, len(errs)), 4)
            # label in the reloaded record matches the actual class
            assert ds.labels.argmax(-1).tolist() == \
                [e.actual for e in errs[:3]]

    def test_top_n_confusions(self):
        ev = Evaluation(num_classes=3)
        actual = np.array([0] * 5 + [1] * 5 + [2] * 5)
        pred = np.array([0, 0, 1, 1, 1,   1, 1, 1, 1, 2,   2, 2, 2, 0, 0])
        ev.eval_indices(actual, pred)
        top = ev.get_top_n_confusions(2)
        assert top[0] == (0, 1, 3)      # most confused cell first
        assert top[1] == (2, 0, 2) or top[1] == (1, 2, 1)
        assert ev.get_top_n_confusions(10)[-1][2] >= 1

    def test_reader_load_missing_record_raises(self, tmp_path):
        from deeplearning4j_tpu.data.records import CSVRecordReader
        from deeplearning4j_tpu.eval.meta import RecordMetaData

        path = self._csv(tmp_path, n=5)
        with pytest.raises(KeyError):
            CSVRecordReader(path).load_from_meta_data(
                [RecordMetaData(path, 99)])

    def test_reader_rejects_foreign_meta_source(self, tmp_path):
        """Metas from a different file must not silently return unrelated
        rows (DataVec matches by URI)."""
        from deeplearning4j_tpu.data.records import CSVRecordReader
        from deeplearning4j_tpu.eval.meta import RecordMetaData

        path = self._csv(tmp_path, n=5)
        with pytest.raises(ValueError, match="source"):
            CSVRecordReader(path).load_from_meta_data(
                [RecordMetaData("somewhere/else.csv", 0)])

    def test_sticky_one_hot_width(self, tmp_path):
        """A loaded subset one-hots to the width the iterator has already
        seen, not the subset's own max class."""
        from deeplearning4j_tpu.data.records import (
            CSVRecordReader, RecordReaderDataSetIterator,
        )
        from deeplearning4j_tpu.eval.meta import RecordMetaData

        p = tmp_path / "w.csv"
        with open(p, "w") as f:
            for i, c in enumerate([0, 1, 2, 0]):
                f.write(f"{i}.0,{c}\n")
        it = RecordReaderDataSetIterator(CSVRecordReader(str(p)), 4)
        ds = next(it)
        assert ds.labels.shape == (4, 3)
        sub = it.load_from_meta_data([RecordMetaData(str(p), 0)])
        assert sub.labels.shape == (1, 3)   # class-0-only subset keeps width


class TestAveragingAndFMeasures:
    """Micro/macro averaging + fBeta/gMeasure (reference:
    eval/EvaluationAveraging.java, eval/EvaluationUtils.java)."""

    def _ev(self):
        ev = Evaluation(num_classes=3)
        actual = np.array([0] * 6 + [1] * 3 + [2] * 1)
        pred = np.array([0, 0, 0, 0, 1, 2,  1, 1, 0,  2])
        ev.eval_indices(actual, pred)
        return ev

    def test_micro_equals_accuracy(self):
        ev = self._ev()
        assert ev.precision(averaging="micro") == pytest.approx(
            ev.accuracy())
        assert ev.recall(averaging="micro") == pytest.approx(ev.accuracy())

    def test_macro_is_classwise_mean(self):
        ev = self._ev()
        per_class = [ev.precision(c) for c in range(3)]
        assert ev.precision() == pytest.approx(np.mean(per_class))

    def test_f_beta_limits(self):
        ev = self._ev()
        # beta=1 == f1; large beta -> recall; small beta -> precision
        assert ev.f_beta(1.0) == pytest.approx(ev.f1())
        assert abs(ev.f_beta(10.0) - ev.recall()) < \
            abs(ev.f_beta(10.0) - ev.precision()) or \
            ev.recall() == ev.precision()
        p, r = ev.precision(2), ev.recall(2)
        assert ev.f_beta(0.5, 2) == pytest.approx(
            1.25 * p * r / (0.25 * p + r))

    def test_g_measure(self):
        ev = self._ev()
        assert ev.g_measure(0) == pytest.approx(
            np.sqrt(ev.precision(0) * ev.recall(0)))

    def test_unknown_averaging_rejected(self):
        ev = self._ev()
        with pytest.raises(ValueError, match="averaging"):
            ev.precision(averaging="weighted")
        with pytest.raises(ValueError, match="averaging"):
            ev.recall(averaging="Micro")


def test_pinned_num_classes_rejects_out_of_range_label():
    """An explicitly configured num_classes must VALIDATE labels: a
    corrupt label raises instead of silently widening the one-hot width
    (advisor r3). Inferred widths (num_classes=None) stay sticky."""
    import pytest

    from deeplearning4j_tpu.data.records import (
        CollectionRecordReader, RecordReaderDataSetIterator,
    )

    recs = [["0.1", "0.2", "0"], ["0.3", "0.4", "5"]]
    it = RecordReaderDataSetIterator(
        CollectionRecordReader(recs), batch_size=4, num_classes=2)
    with pytest.raises(ValueError, match="out of range"):
        next(it)
    # inferred width: same data is accepted and widens to 6
    it2 = RecordReaderDataSetIterator(
        CollectionRecordReader(recs), batch_size=4)
    ds = next(it2)
    assert ds.labels.shape[1] == 6


def test_host_local_shard_balanced_covers_all(monkeypatch):
    """balanced=True round-robins the n % nproc remainder instead of
    dropping it: shard union == range(n), sizes differ by <= 1."""
    import jax

    from deeplearning4j_tpu.parallel import distributed as dist

    monkeypatch.setattr(jax, "process_count", lambda: 3)
    for n in (7, 9, 10, 2):
        seen = []
        sizes = []
        for pi in range(3):
            monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
            sl = dist.host_local_shard(n, balanced=True)
            seen.extend(range(n)[sl])
            sizes.append(len(range(n)[sl]))
        assert sorted(seen) == list(range(n))
        assert max(sizes) - min(sizes) <= 1
        # default (SPMD) mode still gives equal sizes, dropping the tail
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        per = len(range(n)[dist.host_local_shard(n)])
        assert per == n // 3
