"""Parity suite for the one-pass fused optimizer update
(ops/fused_update.py) and its seam into optim/updaters.py.

The fused kernels must reproduce the unfused updater math bit-for-bit
in float64-free f32 terms across the leaf shapes real nets produce:
scalars, odd sizes that don't tile, and low-precision dtypes. The
updater seam is then checked end-to-end: forcing the env hatch routes
the REAL Adam/Nesterovs updaters through the kernel (interpret mode on
CPU) and the trajectory matches the default XLA path.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.fused_update import (
    adam_update,
    nesterov_update,
)

TOL = dict(rtol=2e-6, atol=2e-6)


def _leaves(seed=0):
    # scalar, odd (doesn't divide block_rows), tile-ish, matrix
    shapes = [(), (7,), (513,), (16, 24)]
    ks = jax.random.split(jax.random.PRNGKey(seed), len(shapes))
    return [jax.random.normal(k, s, jnp.float32)
            for k, s in zip(ks, shapes)]


def _adam_ref(p, g, m, v, lrbc, b1, b2, eps):
    m2 = b1 * m + (1.0 - b1) * g
    v2 = b2 * v + (1.0 - b2) * g * g
    return p - lrbc * m2 / (jnp.sqrt(v2) + eps), m2, v2


def _nesterov_ref(p, g, vel, lr, mu):
    v2 = mu * vel - lr * g
    return p + mu * v2 - lr * g, v2


class TestKernelParity:
    @pytest.mark.parametrize("i", range(4))
    def test_adam_leaf_shapes(self, i):
        p = _leaves(1)[i]
        g, m = _leaves(2)[i], _leaves(3)[i] * 0.1
        v = jnp.abs(_leaves(4)[i]) * 0.01
        lrbc = 3e-3
        got = adam_update(p, g, m, v, lrbc, block_rows=8,
                          interpret=True)
        want = _adam_ref(p, g, m, v, lrbc, 0.9, 0.999, 1e-8)
        for a, b in zip(got, want):
            assert a.shape == p.shape and a.dtype == p.dtype
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **TOL)

    @pytest.mark.parametrize("i", range(4))
    def test_nesterov_leaf_shapes(self, i):
        p, g = _leaves(5)[i], _leaves(6)[i]
        vel = _leaves(7)[i] * 0.1
        got = nesterov_update(p, g, vel, 0.05, block_rows=8,
                              interpret=True)
        want = _nesterov_ref(p, g, vel, 0.05, 0.9)
        for a, b in zip(got, want):
            assert a.shape == p.shape and a.dtype == p.dtype
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **TOL)

    def test_bf16_params_stay_bf16(self):
        # mixed-precision nets carry bf16 leaves; the kernel must not
        # silently promote them (that would double optimizer-state HBM)
        p = jnp.ones((64,), jnp.bfloat16) * 0.5
        g = jnp.ones((64,), jnp.bfloat16) * 0.25
        m = jnp.zeros((64,), jnp.bfloat16)
        v = jnp.zeros((64,), jnp.bfloat16)
        p2, m2, v2 = adam_update(p, g, m, v, 1e-2, interpret=True)
        assert p2.dtype == jnp.bfloat16
        assert m2.dtype == jnp.bfloat16 and v2.dtype == jnp.bfloat16
        ref = _adam_ref(p.astype(jnp.float32), g.astype(jnp.float32),
                        m.astype(jnp.float32), v.astype(jnp.float32),
                        1e-2, 0.9, 0.999, 1e-8)
        np.testing.assert_allclose(
            np.asarray(p2, np.float32), np.asarray(ref[0], np.float32),
            rtol=2e-2, atol=2e-2)

    def test_non_default_hyperparams(self):
        p, g = _leaves(8)[2], _leaves(9)[2]
        m, v = _leaves(10)[2] * 0.1, jnp.abs(_leaves(11)[2]) * 0.01
        got = adam_update(p, g, m, v, 1e-2, beta1=0.5, beta2=0.9,
                          eps=1e-4, block_rows=64, interpret=True)
        want = _adam_ref(p, g, m, v, 1e-2, 0.5, 0.9, 1e-4)
        for a, b in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **TOL)


class TestUpdaterSeam:
    """End-to-end through optim/updaters.py: the env hatch flips the
    real updaters onto the kernel and the parameter trajectory matches
    the default path."""

    def _params(self):
        return {"w": jax.random.normal(jax.random.PRNGKey(0), (13, 5)),
                "b": jnp.zeros((5,)),
                "s": jnp.asarray(0.3)}

    def _grads(self, step):
        ks = jax.random.split(jax.random.PRNGKey(100 + step), 3)
        return {"w": jax.random.normal(ks[0], (13, 5)) * 0.1,
                "b": jax.random.normal(ks[1], (5,)) * 0.1,
                "s": jax.random.normal(ks[2], ()) * 0.1}

    def _run(self, updater, steps=4):
        params = self._params()
        state = updater.init(params)
        for i in range(steps):
            params, state = updater.update_with_params(
                self._grads(i), state, params, i)
        return params

    @pytest.mark.parametrize("name", ["adam", "nesterov"])
    def test_forced_fused_matches_xla(self, monkeypatch, name):
        from deeplearning4j_tpu.optim.updaters import Adam, Nesterovs
        mk = ((lambda: Adam(3e-3)) if name == "adam"
              else (lambda: Nesterovs(0.05, momentum=0.9)))
        monkeypatch.setenv("DL4J_TPU_FUSED_UPDATE", "xla")
        base = self._run(mk())
        monkeypatch.setenv("DL4J_TPU_FUSED_UPDATE", "fused")
        fused = self._run(mk())
        for key in base:
            np.testing.assert_allclose(
                np.asarray(fused[key]), np.asarray(base[key]),
                rtol=1e-5, atol=1e-5, err_msg=f"{name} leaf {key}")

    def test_default_cpu_policy_is_xla(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_FUSED_UPDATE", raising=False)
        from deeplearning4j_tpu.ops.kernel_defaults import (
            fused_update_policy,
        )
        assert fused_update_policy("adam") == "xla"

    def test_forced_policy_is_fused(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FUSED_UPDATE", "fused")
        from deeplearning4j_tpu.ops.kernel_defaults import (
            fused_update_policy,
        )
        assert fused_update_policy("adam") == "fused"
        assert fused_update_policy("nesterov") == "fused"
