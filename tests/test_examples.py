"""Every example script must actually run end-to-end (small settings) —
the 'switching user' smoke tests."""

import os
import runpy
import sys

import pytest

_EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run(name):
    return runpy.run_path(os.path.join(_EXAMPLES, name))


@pytest.fixture(autouse=True)
def _examples_path(monkeypatch):
    # runpy.run_path does NOT add the script's directory to sys.path, so
    # the examples' `import _bootstrap` needs it prepended here
    monkeypatch.syspath_prepend(_EXAMPLES)


def test_lenet_mnist():
    mod = _run("lenet_mnist.py")
    acc = mod["main"](epochs=1, batch_size=128, examples=1024)
    assert 0.0 <= acc <= 1.0


def test_transformer_text_generation(capsys):
    mod = _run("transformer_text_generation.py")
    loss, text = mod["main"](epochs=6, T=32, n_gen=16)
    # untrained uniform is ~log(28) ≈ 3.33; 6 epochs reach ~0.47, so 1.5
    # separates "learned the corpus" from "learned nothing"
    assert loss < 1.5
    assert len(text) == 16


def test_modern_llm_decode(capsys):
    mod = _run("modern_llm_decode.py")
    loss, outs = mod["main"](epochs=6, T=32, n_gen=12)
    assert loss < 2.0          # RMS/SwiGLU/GQA stack learns the corpus
    assert set(outs) == {"greedy", "nucleus", "beam"}
    assert all(len(v) == 12 for v in outs.values())


def test_seq2seq_cross_attention(capsys):
    mod = _run("seq2seq_cross_attention.py")
    acc = mod["main"](epochs=120, n=64)
    assert acc > 0.8, acc


def test_word2vec_similarity(capsys):
    mod = _run("word2vec_similarity.py")
    mod["main"]()
    out = capsys.readouterr().out
    assert "apple ~ pear" in out and "binary round-trip" in out


def test_elastic_training(tmp_path):
    mod = _run("elastic_training.py")
    mod["main"](ckpt_dir=str(tmp_path / "ck"))


def test_transformer_pipeline(devices8, capsys):
    mod = _run("transformer_pipeline_1f1b.py")
    mod["main"](stages=4, steps=2)
    assert "params synced back" in capsys.readouterr().out


@pytest.mark.slow       # ~29s; DP training is covered by test_parallel
def test_resnet_data_parallel(devices8, capsys):
    mod = _run("resnet50_data_parallel.py")
    mod["main"](steps=1, image=32, classes=8)
    assert "data-parallel over" in capsys.readouterr().out


def test_training_dashboard(capsys):
    mod = _run("training_dashboard.py")
    mod["main"](epochs=5, serve_forever=False)
    out = capsys.readouterr().out
    assert "dashboard:" in out and "t-SNE view:" in out


def test_nlp_annotation_pipeline(capsys):
    mod = _run("nlp_annotation_pipeline.py")
    mod["main"]()
    out = capsys.readouterr().out
    assert "noun stems only:" in out
    assert "similarity(dog, cat)" in out
    assert "する" in out          # Japanese de-inflection shown


def test_long_context_ring_attention(devices8, capsys):
    mod = _run("long_context_ring_attention.py")
    mod["ring_attention_demo"](T=512, block_check=128)
    mod["remat_training_demo"](T=128)
    out = capsys.readouterr().out
    assert "ring attention" in out and "gradient checkpointing" in out


def test_multiprocess_pod(tmp_path, capsys, multiprocess_env):
    mod = _run("multiprocess_pod.py")
    mod["main"](nproc=2, devs=2, ckpt_dir=str(tmp_path / "ck"))
    out = capsys.readouterr().out
    assert "pod run complete" in out
    # BOTH processes wrote their per-process checkpoint shard dirs
    shard_dirs = {p.name for p in (tmp_path / "ck").rglob("process-*")}
    assert {"process-0", "process-1"} <= shard_dirs, shard_dirs
