"""Unified observability core tests: MetricsRegistry correctness under
threads, Prometheus exposition grammar, span JSONL round-trip,
RecompileWatchdog warn-once, HostSyncMonitor, serving /metrics content
negotiation over the shared registry, and the acceptance contract —
a full fit() with spans + watchdog enabled stays ≤1 host sync/epoch.
"""

import json
import logging
import re
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import observe
from deeplearning4j_tpu.observe import (
    HostSyncMonitor, MetricsRegistry, RecompileWatchdog, SpanLog,
    WatchedJitCache, get_registry, get_watchdog, read_spans, set_registry,
    set_watchdog, span,
)
from deeplearning4j_tpu.observe.registry import PROMETHEUS_CONTENT_TYPE


@pytest.fixture
def fresh_registry():
    """Swap in an isolated process-wide registry; restore afterwards."""
    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


@pytest.fixture
def fresh_watchdog(fresh_registry):
    wd = RecompileWatchdog(threshold=3, metrics=fresh_registry)
    prev = set_watchdog(wd)
    try:
        yield wd
    finally:
        set_watchdog(prev)


def _net(n_in=16, hidden=8, n_out=3, seed=0):
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder().seed(seed)
         .list(DenseLayer(n_out=hidden, activation="relu"),
               OutputLayer(n_out=n_out, activation="softmax",
                           loss="mcxent"))
         .set_input_type(InputType.feed_forward(n_in))
         .build())).init()


def _data(n=64, n_in=16, n_out=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
    return x, y


# ------------------------------------------------------------- registry
class TestMetricsRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs", model="a")
        c.inc()
        c.inc(4)
        assert c.value == 5
        with pytest.raises(ValueError):
            c.inc(-1)
        g = reg.gauge("depth")
        g.set(7)
        g.dec(2)
        assert g.value == 5
        h = reg.histogram("lat")
        for v in range(100):
            h.observe(v)
        assert h.count == 100 and h.sum == sum(range(100))
        p = h.percentiles()
        assert p["p50"] == 50 and p["p99"] == 99

    def test_same_handle_on_re_ask_and_kind_mismatch(self):
        reg = MetricsRegistry()
        assert reg.counter("x", a="1") is reg.counter("x", a="1")
        # label order does not split the series
        assert reg.counter("y", a="1", b="2") is reg.counter(
            "y", b="2", a="1")
        with pytest.raises(TypeError):
            reg.gauge("x", a="1")

    def test_histogram_reservoir_bounded(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", reservoir=16)
        for v in range(1000):
            h.observe(v)
        assert h.count == 1000          # exact running count survives
        assert len(h.values()) == 16    # memory stays bounded
        # sliding window: quantiles come from the most recent values
        assert min(h.values()) == 984

    def test_concurrent_increments_exact(self):
        reg = MetricsRegistry()
        c = reg.counter("hammer")
        h = reg.histogram("hammer_h", reservoir=64)
        n_threads, per = 8, 1000

        def work():
            for _ in range(per):
                c.inc()
                h.observe(1.0)

        ts = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == n_threads * per
        assert h.count == n_threads * per
        assert h.sum == pytest.approx(n_threads * per)

    def test_snapshot_and_jsonl(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("a", k="v").inc(2)
        reg.histogram("b").observe(1.5)
        snap = reg.snapshot()
        assert snap["series"]["a"][0]["value"] == 2
        assert snap["series"]["a"][0]["labels"] == {"k": "v"}
        assert snap["series"]["b"][0]["count"] == 1
        p = tmp_path / "m.jsonl"
        reg.export_jsonl(str(p))
        lines = [json.loads(ln) for ln in p.read_text().splitlines()]
        assert {ln["name"] for ln in lines} == {"a", "b"}


PROM_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                 # metric name
    r"(\{([a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")"    # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?" # more labels
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|NaN|[+-]Inf)$")

PROM_EXEMPLAR_SUFFIX = re.compile(
    r"^\{trace_id=\"[^\"]*\"\} "                 # exemplar labelset
    r"-?\d+(\.\d+)?([eE][+-]?\d+)? "             # exemplar value
    r"\d+(\.\d+)?$")                             # exemplar timestamp


def _assert_prometheus_grammar(text):
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split()
            assert parts[3] in ("counter", "gauge", "summary"), line
            continue
        # OpenMetrics exemplar suffix: `<sample> # {labels} value ts`
        line, sep, exemplar = line.partition(" # ")
        if sep:
            assert PROM_EXEMPLAR_SUFFIX.match(exemplar), \
                f"bad exemplar suffix: {exemplar!r}"
        assert PROM_METRIC_LINE.match(line), f"bad exposition line: {line!r}"


class TestPrometheusExposition:
    def test_grammar(self):
        reg = MetricsRegistry()
        reg.counter("serving_requests_total", model="m", outcome="ok").inc()
        reg.gauge("queue.depth").set(3)            # dot sanitized to _
        h = reg.histogram("latency_seconds", model="m")
        for v in (0.001, 0.02, 0.5):
            h.observe(v)
        reg.gauge("weird name!").set(float("inf"))
        text = reg.to_prometheus()
        _assert_prometheus_grammar(text)
        assert "# TYPE queue_depth gauge" in text
        assert "# TYPE latency_seconds summary" in text
        assert 'latency_seconds{model="m",quantile="0.5"}' in text
        assert 'latency_seconds_count{model="m"} 3' in text
        assert "weird_name_ +Inf" in text

    def test_empty_histogram_renders_no_quantiles(self):
        reg = MetricsRegistry()
        reg.histogram("empty")
        text = reg.to_prometheus()
        _assert_prometheus_grammar(text)
        assert "quantile" not in text
        assert "empty_count 0" in text

    def test_exemplar_suffixes_only_where_observed(self):
        # the p99 line carries the max-value exemplar, _count the
        # latest; a histogram without exemplars renders plain lines,
        # and a never-observed one renders no quantile to hang an
        # exemplar on at all
        reg = MetricsRegistry()
        h = reg.histogram("lat", model="m")
        h.observe(0.2, exemplar="tr-small")
        h.observe(0.9, exemplar="tr-big")
        h.observe(0.1)
        reg.histogram("plain").observe(1.0)
        reg.histogram("bare")                     # never observed
        text = reg.to_prometheus()
        _assert_prometheus_grammar(text)
        p99 = [l for l in text.splitlines()
               if l.startswith('lat{model="m",quantile="0.99"}')][0]
        assert 'trace_id="tr-big"' in p99          # max value wins p99
        count = [l for l in text.splitlines()
                 if l.startswith('lat_count')][0]
        assert 'trace_id="tr-big"' in count        # latest with exemplar
        for line in text.splitlines():
            if line.startswith(("plain", "bare")):
                assert "trace_id" not in line
        assert "bare_count 0" in text
        assert 'bare{quantile' not in text

    def test_concurrent_observe_during_expose(self):
        # exposition walks live instruments while writers observe; the
        # reservoir copy under the instrument lock must keep every
        # render self-consistent and exception-free
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        c = reg.counter("hits")
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            try:
                while not stop.is_set():
                    h.observe(i % 100 / 10.0, exemplar=f"t{i}")
                    c.inc()
                    i += 1
            except Exception as e:       # pragma: no cover - failure path
                errors.append(e)

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(50):
                text = reg.to_prometheus()
                _assert_prometheus_grammar(text)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errors
        final = reg.to_prometheus()
        _assert_prometheus_grammar(final)
        assert f"hits {int(c.value)}" in final
        assert h.count == int(c.value)


# ----------------------------------------------------------------- spans
class TestSpans:
    def test_disabled_is_noop(self):
        assert not observe.tracing_enabled()
        with span("x", a=1) as attrs:
            assert attrs is None

    def test_jsonl_round_trip_with_parent_linkage(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        observe.install_span_log(path)
        try:
            with span("outer", phase="warm") as oa:
                with span("inner", idx=3):
                    pass
                oa["result"] = "ok"      # host value added inside the span
        finally:
            observe.uninstall_span_log()
        evs = read_spans(path)
        assert [e["name"] for e in evs] == ["inner", "outer"]
        inner, outer = evs
        assert inner["parent_id"] == outer["span_id"]
        assert outer["parent_id"] is None
        assert outer["attrs"] == {"phase": "warm", "result": "ok"}
        assert inner["dur_ms"] <= outer["dur_ms"]

    def test_attrs_sanitized_never_serialize_arrays(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        observe.install_span_log(path)
        try:
            # "name" as an attr must not collide with the positional arg
            with span("s", arr=np.arange(3), ok=1, name="n"):
                pass
        finally:
            observe.uninstall_span_log()
        (ev,) = read_spans(path)
        # the array degraded to its TYPE NAME — its values (which for a
        # jax array would require a device sync to read) are never touched
        assert ev["attrs"] == {"arr": "ndarray", "ok": 1, "name": "n"}

    def test_emit_manual_span(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        observe.install_span_log(path)
        try:
            observe.emit_manual_span("window", 100.0, 100.25, tag="t")
        finally:
            observe.uninstall_span_log()
        (ev,) = read_spans(path)
        assert ev["ts"] == 100.0 and ev["dur_ms"] == pytest.approx(250.0)

    def test_spanlog_threads_never_interleave(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        log = observe.install_span_log(SpanLog(path))
        try:
            def work(i):
                for j in range(50):
                    with span(f"t{i}", j=j):
                        pass

            ts = [threading.Thread(target=work, args=(i,))
                  for i in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            observe.uninstall_span_log()
        evs = read_spans(path)    # every line parses ⇒ no interleaving
        assert len(evs) == 200 == log.events
        assert len({e["span_id"] for e in evs}) == 200


# -------------------------------------------------------------- watchdog
class TestRecompileWatchdog:
    def test_counts_first_time_insertions_only(self, fresh_watchdog,
                                               fresh_registry):
        cache = WatchedJitCache(owner_tag="net@1", owner_class="Net")
        cache[("b32",)] = "prog1"
        cache[("b32",)] = "prog1b"          # overwrite: not a new compile
        cache.setdefault(("b64",), "prog2")
        cache.setdefault(("b64",), "IGNORED")
        cache.update({("b128",): "prog3"})
        assert fresh_watchdog.compiles("net@1") == 3
        assert fresh_registry.counter("jit_compiles", owner="Net").value == 3
        sigs = fresh_watchdog.snapshot()["per_owner"]["net@1"]["signatures"]
        assert any("b32" in s for s in sigs)

    def test_warns_exactly_once_past_threshold(self, fresh_watchdog,
                                               caplog):
        cache = WatchedJitCache(owner_tag="churny@2", owner_class="Net")
        with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
            for i in range(10):      # threshold is 3
                cache[("shape", i)] = i
        warnings = [r for r in caplog.records
                    if "RecompileWatchdog" in r.getMessage()]
        assert len(warnings) == 1
        assert "churny@2" in warnings[0].getMessage()
        assert fresh_watchdog.compiles() == 10

    def test_jit_cache_seam_installs_watched_cache(self, fresh_watchdog):
        from deeplearning4j_tpu.parallel.ring_attention import SeqCtxJitCache

        class Holder(SeqCtxJitCache):
            pass

        h = Holder()
        cache = h._jit_cache
        assert isinstance(cache, WatchedJitCache)
        assert h._jit_cache is cache          # stable per context
        cache[(32, (16,))] = "compiled"
        assert fresh_watchdog.compiles() == 1
        tag = next(iter(fresh_watchdog.snapshot()["per_owner"]))
        assert tag.startswith("Holder@")


# --------------------------------------------------------- sync monitor
class TestHostSyncMonitor:
    def test_counts_and_take(self):
        import jax.numpy as jnp

        a = jnp.asarray(1.5)
        with HostSyncMonitor() as mon:
            float(a)
            a.block_until_ready()
            assert mon.syncs == 2
            assert mon.take() == 2
            assert mon.take() == 0        # delta semantics
            float(a)
            assert mon.syncs == 1
        # uninstalled: new syncs invisible
        float(a)
        assert mon.syncs == 1
        assert observe.current_monitor() is None

    def test_nested_monitors_share_one_patch(self):
        import jax.numpy as jnp

        a = jnp.asarray(2.0)
        with HostSyncMonitor() as outer:
            with HostSyncMonitor() as inner:
                assert observe.current_monitor() is inner
                float(a)
            assert observe.current_monitor() is outer
        assert outer.syncs == 1 and inner.syncs == 1


# ----------------------------------------------------------- listeners
class _FakeModel:
    iteration = 0
    last_batch_size = 32


class TestTimeIterationListener:
    def test_first_eligible_iteration_reports(self, caplog):
        from deeplearning4j_tpu.optim.listeners import TimeIterationListener

        lst = TimeIterationListener(total_iterations=10, frequency=1)
        m = _FakeModel()
        with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
            lst.on_fit_start(m)
            lst.iteration_done(m, 1, 0, None)   # old code swallowed this
        assert any("iteration 1/10" in r.getMessage()
                   for r in caplog.records)

    def test_total_zero_reports_rate_without_eta(self, caplog):
        from deeplearning4j_tpu.optim.listeners import TimeIterationListener

        lst = TimeIterationListener(total_iterations=0, frequency=1)
        m = _FakeModel()
        with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
            lst.on_fit_start(m)
            lst.iteration_done(m, 1, 0, None)
        msgs = [r.getMessage() for r in caplog.records]
        assert any("ms/iter" in s for s in msgs)
        assert not any("ETA" in s for s in msgs)

    def test_resumed_fit_rates_only_this_run(self, caplog):
        from deeplearning4j_tpu.optim.listeners import TimeIterationListener

        lst = TimeIterationListener(total_iterations=200, frequency=100)
        m = _FakeModel()
        m.iteration = 99          # resuming: 99 already-trained iterations
        with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
            lst.on_fit_start(m)
            lst.iteration_done(m, 100, 0, None)
        # denominator is iterations done THIS run (1), not 100
        assert any("iteration 100/200" in r.getMessage()
                   for r in caplog.records)


class TestPerformanceListenerRegistry:
    def test_gauges_and_mfu_emitted(self, fresh_registry):
        from deeplearning4j_tpu.optim.listeners import PerformanceListener

        lst = PerformanceListener(frequency=1, report=lambda m: None,
                                  flops_per_step=1e9, peak_flops=1e12)
        assert lst.peak_flops == 1e12      # explicit peak is kept as-is
        m = _FakeModel()
        lst.iteration_done(m, 1, 0, None)
        lst.iteration_done(m, 2, 0, None)
        assert fresh_registry.gauge("train_samples_per_sec").value > 0
        assert fresh_registry.gauge("train_step_ms").value > 0
        mfu = fresh_registry.gauge("train_mfu").value
        assert mfu == pytest.approx(lst.last_mfu) and mfu > 0

    def test_syncs_per_step_with_monitor(self, fresh_registry):
        import jax.numpy as jnp

        from deeplearning4j_tpu.optim.listeners import PerformanceListener

        lst = PerformanceListener(frequency=1, report=lambda m: None)
        m = _FakeModel()
        with HostSyncMonitor():
            lst.iteration_done(m, 1, 0, None)
            float(jnp.asarray(1.0))
            float(jnp.asarray(2.0))
            lst.iteration_done(m, 2, 0, None)
        assert lst.last_syncs_per_step == 2.0
        assert fresh_registry.gauge(
            "train_host_syncs_per_step").value == 2.0


# ------------------------------------------------- profiler correlation
class TestProfilerListenerMidCaptureClose:
    def test_end_of_fit_closes_capture_and_emits_span(self, tmp_path):
        from deeplearning4j_tpu.utils.profiling import ProfilerListener

        net = _net()
        x, y = _data()
        # window starts at iteration 1 but is far longer than the fit:
        # on_fit_end must close the capture cleanly
        pl = ProfilerListener(str(tmp_path / "trace"), start_iteration=1,
                              num_iterations=10_000)
        net.add_listener(pl)
        path = str(tmp_path / "spans.jsonl")
        observe.install_span_log(path)
        try:
            net.fit(x, y, epochs=1, batch_size=16)
        finally:
            observe.uninstall_span_log()
        assert pl.captured and not pl._active
        traces = [e for e in read_spans(path)
                  if e["name"] == "jax.profiler.trace"]
        assert len(traces) == 1
        assert traces[0]["attrs"]["start_iteration"] == 1
        assert traces[0]["dur_ms"] > 0


# ------------------------------------------------------------ serving
def _get_raw(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.headers.get("Content-Type"), r.read().decode()


class TestServingMetricsEndpoint:
    def test_content_negotiation_and_grammar(self):
        from deeplearning4j_tpu.serving.inference_server import (
            InferenceServer,
        )

        net = _net(n_in=4, hidden=8, n_out=2)
        srv = InferenceServer(net, batched=False)
        port = srv.start()
        try:
            body = json.dumps(
                {"ndarray": [[0.1, 0.2, 0.3, 0.4]]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/output", data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=30).read()

            # default stays JSON (the pre-existing consumer contract)
            ctype, text = _get_raw(port, "/metrics")
            assert ctype.startswith("application/json")
            snap = json.loads(text)
            assert snap["requests"]["completed"] == 1

            # a scraper negotiates the Prometheus exposition
            ctype, text = _get_raw(port, "/metrics",
                                   {"Accept": "text/plain"})
            assert ctype == PROMETHEUS_CONTENT_TYPE
            _assert_prometheus_grammar(text)
            assert ('serving_requests_total{model="default",'
                    'outcome="completed"} 1') in text

            # ?format=prometheus works without an Accept header
            ctype, text = _get_raw(port, "/metrics?format=prometheus")
            assert ctype == PROMETHEUS_CONTENT_TYPE
            _assert_prometheus_grammar(text)
        finally:
            srv.stop()

    def test_shared_registry_unifies_training_and_serving(
            self, fresh_registry):
        from deeplearning4j_tpu.serving.inference_server import (
            InferenceServer,
        )

        # training side records into the process registry...
        net = _net()
        x, y = _data()
        net.fit(x, y, epochs=1, batch_size=16)
        assert fresh_registry.counter("train_iterations").value == 4

        # ...and a server built on the SAME registry scrapes both
        snet = _net(n_in=4, hidden=8, n_out=2)
        srv = InferenceServer(snet, batched=False,
                              metrics=get_registry())
        port = srv.start()
        try:
            body = json.dumps(
                {"ndarray": [[1.0, 2.0, 3.0, 4.0]]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/output", data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=30).read()
            _, text = _get_raw(port, "/metrics?format=prometheus")
        finally:
            srv.stop()
        _assert_prometheus_grammar(text)
        assert "train_iterations 4" in text          # training series
        assert "serving_requests_total" in text      # serving series


# ----------------------------------------------------------- acceptance
class TestFitSyncBudgetWithObservability:
    """The acceptance contract: enabling the full observability stack
    (span log + watchdog + registry instrumentation) must not add host
    syncs — the fit loop stays ≤1 materialization per epoch."""

    def _counting_patches(self, monkeypatch, counts):
        from jax._src import array as _jarray

        orig_float = _jarray.ArrayImpl.__float__
        orig_block = _jarray.ArrayImpl.block_until_ready

        def counting_float(a):
            counts["float"] += 1
            return orig_float(a)

        def counting_block(a):
            counts["block"] += 1
            return orig_block(a)

        monkeypatch.setattr(_jarray.ArrayImpl, "__float__", counting_float)
        monkeypatch.setattr(_jarray.ArrayImpl, "block_until_ready",
                            counting_block)

    def test_fit_with_spans_and_watchdog_one_sync_per_epoch(
            self, monkeypatch, tmp_path, fresh_watchdog):
        net = _net()
        x, y = _data()
        net.fit(x, y, epochs=1, batch_size=16)    # compile outside guard
        warm_compiles = fresh_watchdog.compiles()
        assert warm_compiles >= 1      # the watchdog saw the warm-up trace

        counts = {"float": 0, "block": 0}
        self._counting_patches(monkeypatch, counts)
        observe.install_span_log(str(tmp_path / "spans.jsonl"))
        try:
            epochs = 3
            net.fit(x, y, epochs=epochs, batch_size=16)
        finally:
            observe.uninstall_span_log()
        assert counts["float"] + counts["block"] <= epochs, counts
        evs = read_spans(str(tmp_path / "spans.jsonl"))
        assert sum(e["name"] == "fit.epoch" for e in evs) == epochs
        # the warm second fit added no compiles
        assert fresh_watchdog.compiles() == warm_compiles


# -------------------------------------------------------------- dump tool
class TestDumpTool:
    def test_snapshot_and_jsonl_render(self, tmp_path, capsys):
        from deeplearning4j_tpu.observe import dump

        reg = MetricsRegistry()
        reg.counter("reqs", model="m").inc(5)
        reg.histogram("lat").observe(0.25)
        snap_path = tmp_path / "snap.json"
        snap_path.write_text(json.dumps(reg.snapshot()))
        out = dump.dump_file(str(snap_path))
        assert "reqs" in out and "model=m" in out and "5" in out
        assert "count=1" in out

        # BENCH blobs embed the snapshot under "registry"
        bench_path = tmp_path / "BENCH_x.json"
        bench_path.write_text(json.dumps(
            {"metric": "ips", "registry": reg.snapshot()}))
        assert "reqs" in dump.dump_file(str(bench_path))

        # span JSONL path + --tail via main()
        jsonl = tmp_path / "spans.jsonl"
        observe.install_span_log(str(jsonl))
        try:
            for i in range(5):
                with span("step", i=i):
                    pass
        finally:
            observe.uninstall_span_log()
        assert dump.main([str(jsonl), "--tail", "2"]) == 0
        printed = capsys.readouterr().out
        assert printed.count("step") == 2 and "i=4" in printed
