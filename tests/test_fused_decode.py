"""Fused multi-token decode: the K-steps-per-dispatch window.

What these pin:
  * the jit-safe sampler (utils/sampling.sample_token /
    sample_token_lanes) is semantically identical to the numpy
    implementation: greedy is BIT-EXACT, truncation supports match,
    cold temperatures stay on the mode (no float32 underflow)
  * the hard parity contract: fused-K greedy decode emits the exact
    token sequence of step-by-step (K=1) decode, across prompt lengths
    spanning the prefill chunk buckets, and stochastic streams are
    K-invariant (token i always draws with fold_in(key, i))
  * per-lane early exit: EOS mid-window stops a lane without breaking
    the fixed shape or leaking post-EOS tokens
  * mixed co-batches: a mid-prefill session and a mid-decode session
    share one dispatch and neither perturbs the other's output
  * cancel and deadline land at window boundaries and free the slot
  * session churn at a fixed K causes ZERO recompiles after warmup
  * the decode_loop policy seam: env forces, capability degrade,
    K bucketing, and the kernel_dispatch_total counter
"""

import time

import numpy as np
import pytest

from deeplearning4j_tpu.observe.watchdog import get_watchdog
from deeplearning4j_tpu.utils.sampling import (
    SamplingParams, lane_param_arrays, sample_next, sample_token,
    sample_token_lanes, truncate_probs,
)

from test_decode_sessions import V, _make_net


@pytest.fixture(scope="module")
def net():
    return _make_net()


def _plane(net, *, slots=2, chunk=4, fused_k=None):
    from deeplearning4j_tpu.serving import (
        ContinuousBatchingScheduler, ModelRegistry, ServingStats,
    )
    from deeplearning4j_tpu.serving.sessions import DecodeSessionManager

    registry = ModelRegistry()
    registry.deploy("default", 1, net, warm=False)
    stats = ServingStats()
    sched = ContinuousBatchingScheduler(registry, stats, max_batch_size=8)
    mgr = DecodeSessionManager(registry, sched, "default", slots=slots,
                               prefill_chunk=chunk, fused_k=fused_k,
                               metrics=stats.registry)
    return registry, sched, mgr


# ------------------------------------------------------ device sampler
class TestSampleTokenParity:
    def _probs(self, b=6, v=V, seed=0):
        rng = np.random.default_rng(seed)
        p = rng.random((b, v))
        return p / p.sum(-1, keepdims=True)

    def test_greedy_bit_exact_vs_numpy(self):
        p = self._probs()
        host = sample_next(p, SamplingParams(greedy=True),
                           np.random.default_rng(0))
        dev = np.asarray(sample_token(p, SamplingParams(greedy=True), None))
        assert np.array_equal(host, dev)

    def test_greedy_tie_breaks_first_occurrence(self):
        p = np.zeros((1, V))
        p[0, 3] = p[0, 7] = 0.5
        host = sample_next(p, SamplingParams(greedy=True),
                           np.random.default_rng(0))
        dev = np.asarray(sample_token(p, SamplingParams(greedy=True), None))
        assert host[0] == dev[0] == 3

    def test_top_k_support_matches_numpy(self):
        import jax.numpy as jnp
        p = self._probs()
        for tk in (1, 3, V):
            want = truncate_probs(p.astype(np.float64), tk, None) > 0
            t, k, tp, g = lane_param_arrays(
                [SamplingParams(top_k=tk)] * p.shape[0], V)
            pj = jnp.asarray(p, jnp.float32)
            ranks = jnp.argsort(jnp.argsort(-pj, axis=-1), axis=-1)
            got = np.asarray(ranks < jnp.asarray(k)[:, None])
            assert np.array_equal(want, got), f"top_k={tk}"

    def test_top_p_keeps_crossing_token(self):
        import jax
        # draws from a known nucleus: top_p=0.5 over [0.4, 0.3, 0.2, 0.1]
        # keeps {0, 1} (token 1 crosses the threshold)
        p = np.tile([0.4, 0.3, 0.2, 0.1], (512, 1))
        toks = np.asarray(sample_token(
            p, SamplingParams(top_p=0.5), jax.random.PRNGKey(0)))
        assert set(np.unique(toks)) == {0, 1}

    def test_top_k_stochastic_stays_in_support(self):
        import jax
        p = np.tile(self._probs(b=1), (512, 1))
        toks = np.asarray(sample_token(
            p, SamplingParams(top_k=4, temperature=1.2),
            jax.random.PRNGKey(1)))
        allowed = set(np.argsort(-p[0])[:4].tolist())
        assert set(np.unique(toks)) <= allowed

    def test_cold_temperature_no_underflow(self):
        import jax
        # p^(1/tau) at tau=0.005 underflows float32 by ~1e-170; the
        # log-space tempering must keep the draw on the argmax
        p = self._probs()
        toks = np.asarray(sample_token(
            p, SamplingParams(temperature=0.005), jax.random.PRNGKey(7)))
        assert np.array_equal(toks, p.argmax(-1))

    def test_lanes_mixed_knobs_single_program(self):
        import jax
        import jax.numpy as jnp
        p = self._probs(b=4)
        params = [SamplingParams(greedy=True),
                  SamplingParams(top_k=1),
                  SamplingParams(temperature=0.005),
                  SamplingParams(top_p=1e-6)]
        t, k, tp, g = lane_param_arrays(params, V)
        keys = jax.random.split(jax.random.PRNGKey(0), 4)
        toks = np.asarray(sample_token_lanes(
            jnp.asarray(p, jnp.float32), jnp.asarray(t), jnp.asarray(k),
            jnp.asarray(tp), jnp.asarray(g), keys))
        # every knob above collapses the lane to its argmax
        assert np.array_equal(toks, p.argmax(-1))

    def test_textgen_greedy_uses_shared_sampler(self, net):
        from deeplearning4j_tpu.utils.textgen import generate
        out = generate(net, [[1, 2, 3]], 5, greedy=True)
        net.rnn_clear_previous_state()
        assert out.shape == (1, 5)
        assert out.min() >= 0 and out.max() < V


# -------------------------------------------------- the parity contract
def _run_tokens(net, prompt, *, fused_k, max_tokens=10, chunk=4,
                greedy=True, seed=None, eos_id=None):
    registry, sched, mgr = _plane(net, chunk=chunk, fused_k=fused_k)
    try:
        sess = mgr.open_session(prompt, max_tokens=max_tokens,
                                greedy=greedy, seed=seed, eos_id=eos_id)
        return sess.result(timeout=60), mgr
    finally:
        sched.shutdown()
        registry.close()


class TestFusedGreedyParity:
    @pytest.mark.parametrize("prompt", [[5], [1, 2, 3], [1, 2, 3, 4, 5],
                                        [1, 2, 3, 4, 5, 6, 7, 8, 9]])
    @pytest.mark.parametrize("k", [4, 8])
    def test_bit_exact_vs_stepwise_across_buckets(self, net, prompt, k):
        """Prompts span the prefill buckets (stem 0, <chunk, =chunk,
        >2*chunk); fused-K greedy must emit the exact stepwise stream."""
        step, _ = _run_tokens(net, prompt, fused_k=1)
        fused, _ = _run_tokens(net, prompt, fused_k=k)
        assert fused == step, (prompt, k)

    def test_stochastic_stream_is_k_invariant(self, net):
        """Token i draws with fold_in(base_key, i) regardless of how
        steps share windows, so a seeded stochastic stream is identical
        at every K."""
        kwargs = dict(greedy=False, seed=1234, max_tokens=12)
        one, _ = _run_tokens(net, [1, 2, 3], fused_k=1, **kwargs)
        four, _ = _run_tokens(net, [1, 2, 3], fused_k=4, **kwargs)
        eight, _ = _run_tokens(net, [1, 2, 3], fused_k=8, **kwargs)
        assert one == four == eight

    def test_seed_determinism_and_independence(self, net):
        a, _ = _run_tokens(net, [1, 2], fused_k=4, greedy=False, seed=7,
                           max_tokens=12)
        b, _ = _run_tokens(net, [1, 2], fused_k=4, greedy=False, seed=7,
                          max_tokens=12)
        c, _ = _run_tokens(net, [1, 2], fused_k=4, greedy=False, seed=8,
                          max_tokens=12)
        assert a == b
        assert a != c       # 12 tokens over V=13: collision ~ never


# ------------------------------------------------- early exit / windows
class TestWindowEarlyExit:
    def test_eos_mid_window_stops_lane(self, net):
        """Find the greedy stream first, then replay with its 3rd token
        as EOS and a window that spans it: the session must stop AT the
        EOS token (device early-exit), not at the window edge."""
        free, _ = _run_tokens(net, [1, 2, 3], fused_k=8, max_tokens=8)
        # first token that did not appear earlier in the stream: making
        # it EOS must truncate exactly there (strictly inside the window)
        i = next(j for j in range(1, len(free))
                 if free[j] not in free[:j])
        assert i < len(free) - 1, "stream too repetitive for this net"
        got, _ = _run_tokens(net, [1, 2, 3], fused_k=8, max_tokens=8,
                             eos_id=free[i])
        assert got == free[:i + 1]
        assert got[-1] == free[i]

    def test_budget_mid_window(self, net):
        """max_tokens not a multiple of K: the final short window must
        stop at the budget, not pad the stream to the window edge."""
        got, _ = _run_tokens(net, [1, 2, 3], fused_k=8, max_tokens=5)
        full, _ = _run_tokens(net, [1, 2, 3], fused_k=8, max_tokens=8)
        assert len(got) == 5
        assert got == full[:5]

    def test_round_trips_amortized(self, net):
        """The whole point: max_tokens=8 at K=8 is ONE decode window —
        dispatches/token collapses vs stepwise."""
        registry, sched, mgr = _plane(net, fused_k=8)
        try:
            sess = mgr.open_session([1, 2, 3], max_tokens=8, greedy=True)
            toks = sess.result(timeout=60)
            snap = mgr.snapshot()
            assert len(toks) == 8
            assert snap["dispatches"]["windows"] == 1
            assert snap["dispatches"]["window_tokens"] == 8
            # stem (2 tokens -> 1 chunk) + 1 window = 2 round-trips
            assert snap["dispatches"]["total"] == 2
            assert snap["decode_loop"]["kind"] == "fused"
            assert snap["decode_loop"]["k"] == 8
        finally:
            sched.shutdown()
            registry.close()


# ---------------------------------------------- co-batching and churn
class TestMixedCoBatch:
    def test_prefill_and_window_share_dispatch(self, net):
        """A long-prompt session (mid-prefill) and a short-prompt
        session (mid-decode) coalesce, and neither perturbs the other:
        co-batched outputs equal solo outputs token for token."""
        solo_a, _ = _run_tokens(net, [1, 2, 3, 4, 5, 6, 7, 8, 9],
                                fused_k=4, max_tokens=6)
        solo_b, _ = _run_tokens(net, [5], fused_k=4, max_tokens=6)
        registry, sched, mgr = _plane(net, fused_k=4)
        try:
            sa = mgr.open_session([1, 2, 3, 4, 5, 6, 7, 8, 9],
                                  max_tokens=6, greedy=True)
            sb = mgr.open_session([5], max_tokens=6, greedy=True)
            got_a = sa.result(timeout=60)
            got_b = sb.result(timeout=60)
            assert got_a == solo_a
            assert got_b == solo_b
        finally:
            sched.shutdown()
            registry.close()

    def test_churn_zero_recompiles_after_warmup(self, net):
        """Session churn — different prompts, budgets, knobs, seeds —
        through one warmed manager mints no new programs."""
        registry, sched, mgr = _plane(net, fused_k=4)
        try:
            c0 = get_watchdog().compiles()
            for i in range(4):
                s1 = mgr.open_session([1 + i, 2, 3], max_tokens=3 + i,
                                      greedy=(i % 2 == 0), seed=i,
                                      temperature=0.7 + 0.1 * i)
                s2 = mgr.open_session([2 + i], max_tokens=5,
                                      top_k=3 + i, seed=10 + i)
                s1.result(timeout=60), s2.result(timeout=60)
            assert get_watchdog().compiles() == c0, \
                "session churn caused recompiles at fixed K"
        finally:
            sched.shutdown()
            registry.close()


# ----------------------------------------- cancel/deadline in a window
class TestCancelDeadlineInWindow:
    def test_cancel_between_windows_keeps_partial(self, net):
        registry, sched, mgr = _plane(net, fused_k=4)
        try:
            sess = mgr.open_session([1, 2, 3], max_tokens=40)
            # wait for the first window's tokens, then cancel
            deadline = time.monotonic() + 30
            while not sess.generated and time.monotonic() < deadline:
                time.sleep(0.002)
            assert sess.generated, "no window landed in 30s"
            sess.cancel()
            sess.done.wait(30)
            assert sess.outcome == "cancelled"
            partial = len(sess.generated)
            assert 1 <= partial < 40
            # cancel lands at a window boundary: the slot is free again
            assert mgr.pool.describe()["in_use"] == 0
        finally:
            sched.shutdown()
            registry.close()

    def test_deadline_expires_mid_stream_frees_slot(self, net):
        from deeplearning4j_tpu.serving.scheduler import (
            DeadlineExceededError,
        )
        registry, sched, mgr = _plane(net, fused_k=4)
        try:
            sess = mgr.open_session([1, 2, 3], max_tokens=40,
                                    deadline_ms=60000)
            deadline = time.monotonic() + 30
            while not sess.generated and time.monotonic() < deadline:
                time.sleep(0.002)
            assert sess.generated, "no window landed in 30s"
            # force the deadline into the past: the next window submit
            # must expire the session instead of chaining forever
            sess.deadline = time.monotonic() - 0.001
            with pytest.raises(DeadlineExceededError):
                sess.result(timeout=30)
            assert sess.outcome == "expired"
            assert mgr.pool.describe()["in_use"] == 0
        finally:
            sched.shutdown()
            registry.close()


# ------------------------------------------------------ policy seam
class TestDecodeLoopPolicy:
    def test_lattice_and_bucketing(self, monkeypatch):
        from deeplearning4j_tpu.ops.kernel_defaults import (
            DECODE_K_BUCKETS, decode_loop_policy,
        )
        monkeypatch.delenv("DL4J_TPU_DECODE_LOOP", raising=False)
        monkeypatch.delenv("DL4J_TPU_DECODE_K", raising=False)
        pol = decode_loop_policy(record=False)
        assert pol.kind == "fused" and pol.k in DECODE_K_BUCKETS
        assert decode_loop_policy(3, record=False).k == 4   # bucketed up
        assert decode_loop_policy(99, record=False).k == \
            DECODE_K_BUCKETS[-1]
        assert decode_loop_policy(capable=False,
                                  record=False).kind == "stepwise"
        monkeypatch.setenv("DL4J_TPU_DECODE_LOOP", "stepwise")
        assert decode_loop_policy(8, record=False) \
            .kind == "stepwise"
        monkeypatch.setenv("DL4J_TPU_DECODE_LOOP", "fused")
        monkeypatch.setenv("DL4J_TPU_DECODE_K", "2")
        pol = decode_loop_policy(8, record=False)
        assert pol.kind == "fused" and pol.k == 2

    def test_dispatch_counter_and_stepwise_manager(self, net,
                                                   monkeypatch):
        from deeplearning4j_tpu.observe import get_registry
        monkeypatch.setenv("DL4J_TPU_DECODE_LOOP", "stepwise")
        registry, sched, mgr = _plane(net)
        try:
            assert mgr.loop_kind == "stepwise" and mgr.fused_k == 1
            # counted on BOTH the global spine and the private registry
            c = get_registry().counter("kernel_dispatch_total",
                                       op="decode_loop", impl="stepwise")
            assert int(c.value) >= 1
            m = mgr.metrics.counter("kernel_dispatch_total",
                                    op="decode_loop", impl="stepwise")
            assert int(m.value) >= 1
            # stepwise is K=1 through the same window program: still
            # samples on-device, still exact
            sess = mgr.open_session([1, 2, 3], max_tokens=4, greedy=True)
            toks = sess.result(timeout=60)
            assert len(toks) == 4
            snap = mgr.snapshot()
            assert snap["decode_loop"] == {
                "kind": "stepwise", "k": 1,
                "reason": "forced by DL4J_TPU_DECODE_LOOP=stepwise"}
        finally:
            sched.shutdown()
            registry.close()
