"""Radix-tree prefix cache: paged copy-on-write KV reuse.

What these pin:
  * the radix index itself (serving/prefix_cache.py): page-granular
    insert/match, mid-page longest-common-prefix tails, split on
    divergence, partial-leaf upgrade when a longer chain lands, and
    refcount-exact adoption/eviction accounting against the pool
  * the admission contract: a warm prefix NEVER re-prefills — matched
    full pages are adopted by reference, a mid-page match forks at most
    ONE copy-on-write page, and the greedy stream is BIT-EXACT against
    a cold prefill of the same prompt (for native and int8 KV — shared
    quantized pages carry their own per-(token, head) scales, so
    sharing is bit-exact by construction)
  * eviction only ever reclaims cache-only pages (pool refcount 1):
    a live session's pages are untouchable, and the free/cached/live
    page accounting reconciles after any open/close sequence
  * hot-swap coherence: a flipped deploy flushes the radix (stale-KV
    matches are impossible) while live sessions finish on the pages
    they hold; incapable candidates roll back
  * session churn against a warm cache causes ZERO recompiles — page
    indices are traced scalars inside the one compiled window
  * the prefix_cache policy seam: env forces, capability degrade, page
    snapping to a divisor of max_cache, and the
    kernel_dispatch_total{op="prefix_cache"} verdict mirror
  * chaos: eviction under page pressure with freed pages poison-filled
    never corrupts a surviving session; a session killed mid-CoW-fork
    reconciles every page refcount
"""

import numpy as np
import pytest

from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.attention import (
    PositionEmbeddingLayer, TransformerEncoderBlock,
)
from deeplearning4j_tpu.nn.layers.feedforward import EmbeddingSequenceLayer
from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.observe.watchdog import get_watchdog
from deeplearning4j_tpu.optim.updaters import Adam

V, T = 13, 6
LP = 4              # page length for every paged plane in this file


def _make_net(seed=0, emb=12, max_len=64, window=8, max_cache=16):
    """Non-rolling decode stack (rolling rings cannot page)."""
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .activation("identity")
            .list(EmbeddingSequenceLayer(n_in=V, n_out=emb),
                  PositionEmbeddingLayer(max_length=max_len),
                  TransformerEncoderBlock(num_heads=2, causal=True,
                                          window=window,
                                          rolling_cache=False,
                                          max_cache=max_cache),
                  RnnOutputLayer(n_out=V, activation="softmax"))
            .set_input_type(InputType.recurrent(1, T)).build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture(scope="module")
def net():
    return _make_net()


def _plane(net, *, slots=2, chunk=4, page_len=LP, kv_dtype=None):
    from deeplearning4j_tpu.serving import (
        ContinuousBatchingScheduler, ModelRegistry, ServingStats,
    )
    from deeplearning4j_tpu.serving.sessions import DecodeSessionManager

    registry = ModelRegistry()
    registry.deploy("default", 1, net, warm=False)
    stats = ServingStats()
    sched = ContinuousBatchingScheduler(registry, stats, max_batch_size=8)
    mgr = DecodeSessionManager(registry, sched, "default", slots=slots,
                               prefill_chunk=chunk, page_len=page_len,
                               kv_dtype=kv_dtype, metrics=stats.registry)
    return registry, sched, mgr


def _run(mgr, prompt, max_tokens=4, **kw):
    sess = mgr.open_session(prompt, max_tokens=max_tokens, greedy=True,
                            **kw)
    return sess.result(timeout=60)


def _cold(net, prompt, max_tokens=4, **plane_kw):
    """Reference stream from a fresh, empty-cache plane."""
    registry, sched, mgr = _plane(net, **plane_kw)
    try:
        return _run(mgr, prompt, max_tokens=max_tokens)
    finally:
        sched.shutdown()
        registry.close()


# --------------------------------------------------- radix semantics
class TestRadixIndex:
    """PrefixCache against a real paged pool, no serving plane: the
    match/insert/split/evict state machine and its pool refcounts."""

    @pytest.fixture()
    def pool(self, net):
        from deeplearning4j_tpu.serving.kv_pool import KVSlotPool
        return KVSlotPool(net, 2, page_len=LP, metrics=MetricsRegistry())

    @pytest.fixture()
    def cache(self, pool):
        from deeplearning4j_tpu.serving import PrefixCache
        return PrefixCache(pool, metrics=MetricsRegistry())

    def _donate(self, pool, cache, tokens):
        """Simulate a donor session's prefill: allocate the chain,
        insert, then drop the session's own references (the cache's
        survive)."""
        n = -(-len(tokens) // LP)
        with pool.lock():
            chain = pool.page_alloc_locked(n)
            cache.insert(tokens, chain)
            for p in chain:
                pool.page_unref_locked(p)
        return chain

    def test_insert_then_match_full_and_partial(self, pool, cache):
        toks = list(range(1, 12))                 # 11 tokens: 2 full + 3
        chain = self._donate(pool, cache, toks)
        with pool.lock():
            cl, full, partial = cache.match(toks)
            assert cl == 11
            assert full == chain[:2]
            assert partial == (chain[2], 3)
            # every cached page carries exactly the cache's reference
            for p in chain:
                assert pool.page_refcount_locked(p) == 1

    def test_match_stops_at_divergence(self, pool, cache):
        chain = self._donate(pool, cache, [1, 2, 3, 4, 5, 6, 7, 8])
        with pool.lock():
            # diverges inside the second page: 1 full page + lcp 2
            cl, full, partial = cache.match([1, 2, 3, 4, 5, 6, 9, 9])
            assert (cl, full) == (6, chain[:1])
            assert partial == (chain[1], 2)
            # diverges inside the FIRST page: partial-only match
            cl, full, partial = cache.match([1, 2, 9])
            assert (cl, full) == (2, [])
            assert partial == (chain[0], 2)
            # nothing shared: a miss
            cl, full, partial = cache.match([9, 9, 9])
            assert (cl, full, partial) == (0, [], None)
        st = cache.stats()
        assert st["hits"] == 2 and st["misses"] == 1

    def test_split_two_chains_share_a_node(self, pool, cache):
        a = self._donate(pool, cache, [1, 2, 3, 4, 5, 6, 7, 8])
        b = self._donate(pool, cache, [1, 2, 3, 4, 9, 9, 9, 9])
        with pool.lock():
            # the shared first chunk was already cached: chain b's first
            # page was NOT adopted (donor kept its private copy)
            assert pool.page_refcount_locked(b[0]) == 0
            cl_a, full_a, _ = cache.match([1, 2, 3, 4, 5, 6, 7, 8])
            cl_b, full_b, _ = cache.match([1, 2, 3, 4, 9, 9, 9, 9])
        assert (cl_a, full_a) == (8, [a[0], a[1]])
        assert (cl_b, full_b) == (8, [a[0], b[1]])

    def test_partial_upgrade_releases_short_leaf(self, pool, cache):
        short = self._donate(pool, cache, [1, 2])           # partial (2)
        longer = self._donate(pool, cache, [1, 2, 3])       # extends it
        with pool.lock():
            assert pool.page_refcount_locked(short[0]) == 0  # upgraded
            assert pool.page_refcount_locked(longer[0]) == 1
            cl, _, partial = cache.match([1, 2, 3])
        assert cl == 3 and partial == (longer[0], 3)

    def test_covered_tail_is_not_readopted(self, pool, cache):
        first = self._donate(pool, cache, [1, 2, 3])
        second = self._donate(pool, cache, [1, 2])   # strictly shorter
        with pool.lock():
            assert pool.page_refcount_locked(second[0]) == 0
            assert pool.page_refcount_locked(first[0]) == 1
        assert cache.cached_pages() == 1

    def test_eviction_lru_and_live_pages_untouchable(self, pool, cache):
        cold = self._donate(pool, cache, [1, 2, 3, 4])
        hot = self._donate(pool, cache, [5, 6, 7, 8])
        with pool.lock():
            # a live session still maps the cold page: pin it
            pool.page_ref_locked(cold[0])
            cache.match([5, 6, 7, 8])        # refresh hot's LRU tick
            freed = cache.evict(2)
            # only hot was cache-only; the pinned page must survive
            assert freed == 1
            assert pool.page_refcount_locked(cold[0]) == 2
            assert pool.page_refcount_locked(hot[0]) == 0
            pool.page_unref_locked(cold[0])
            freed = cache.evict(1)           # now unpinned -> evictable
            assert freed == 1
            assert pool.pages_free_locked() == pool.pages

    def test_match_refreshes_partial_and_tail_child_ticks(self, pool,
                                                          cache):
        """LRU fairness: a match that lands on a partial leaf, or ends
        inside a full child's edge, marks that page hot — eviction must
        take the genuinely colder chain first, not the one whose tick
        match() forgot to refresh."""
        hot = self._donate(pool, cache, [1, 2, 3])    # partial leaf
        cold = self._donate(pool, cache, [5, 6, 7])   # newer partial
        with pool.lock():
            cache.match([1, 2, 9])    # partial hit -> hot refreshed
            assert cache.evict(1) == 1
            assert pool.page_refcount_locked(hot[0]) == 1, \
                "a just-matched partial leaf was evicted as coldest"
            assert pool.page_refcount_locked(cold[0]) == 0
        # same for a match ending inside a full child's edge
        a = self._donate(pool, cache, [1, 2, 3, 4])   # full page
        b = self._donate(pool, cache, [5, 6, 7, 8])   # newer full page
        with pool.lock():
            cache.match([1, 2, 9])    # tail-child hit -> a refreshed
            assert cache.evict(1) == 1
            assert pool.page_refcount_locked(a[0]) == 1, \
                "a just-matched tail child was evicted as coldest"
            assert pool.page_refcount_locked(b[0]) == 0

    def test_evict_partials_in_lru_order_by_identity(self, pool, cache):
        """Several partial leaves under one node: eviction pops
        strictly coldest-first even as earlier pops shift the list —
        candidates re-resolve by (tokens, page) identity, never by a
        stale list index."""
        a = self._donate(pool, cache, [1, 2])
        b = self._donate(pool, cache, [3, 4])
        c = self._donate(pool, cache, [5, 6])
        with pool.lock():
            cache.match([3, 4])       # b is now the hottest
            assert cache.evict(2) == 2
            assert pool.page_refcount_locked(b[0]) == 1, \
                "LRU order violated: the hot partial went first"
            assert pool.page_refcount_locked(a[0]) == 0
            assert pool.page_refcount_locked(c[0]) == 0

    def test_flush_releases_everything(self, pool, cache):
        self._donate(pool, cache, [1, 2, 3, 4, 5, 6, 7, 8])
        self._donate(pool, cache, [1, 2, 3, 4, 9])
        assert cache.cached_pages() == 3
        with pool.lock():
            released = cache.flush()
            assert released == 3
            assert cache.cached_pages() == 0
            assert pool.pages_free_locked() == pool.pages


# ------------------------------------------- warm == cold, bit-exact
class TestWarmParity:
    def test_warm_full_stem_bit_exact_and_skips_prefill(self, net):
        prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]      # stem 8 = 2 pages
        registry, sched, mgr = _plane(net)
        try:
            assert mgr.prefix_enabled
            cold = _run(mgr, prompt, max_tokens=4)
            d_cold = mgr.snapshot()["dispatches"]["total"]
            warm = _run(mgr, prompt, max_tokens=4)
            snap = mgr.snapshot()
            assert warm == cold
            pc = snap["prefix_cache"]
            assert pc["hits"] == 1 and pc["misses"] == 1
            assert pc["hit_tokens"] == 8
            # the warm session's whole prefill vanished: only decode
            # windows dispatched (cold ran prefill chunks + windows)
            d_warm = snap["dispatches"]["total"] - d_cold
            assert d_warm < d_cold
        finally:
            sched.shutdown()
            registry.close()

    def test_cow_fork_parity_vs_cold_prefill(self, net):
        donor = [1, 2, 3, 4, 5, 6, 7, 8, 9]
        follower = [1, 2, 3, 4, 5, 6, 9, 9, 9]    # diverges mid-page-2
        reference = _cold(net, follower, max_tokens=4)
        registry, sched, mgr = _plane(net)
        try:
            _run(mgr, donor, max_tokens=4)
            got = _run(mgr, follower, max_tokens=4)
            assert got == reference
            pc = mgr.snapshot()["prefix_cache"]
            assert pc["cow_forks"] == 1
            assert pc["hit_tokens"] == 6           # 1 full page + lcp 2
        finally:
            sched.shutdown()
            registry.close()

    def test_cache_off_stream_parity(self, net, monkeypatch):
        """The cache is a perf lever, never a correctness lever: the
        paged plane and the monolithic (env-forced off) plane emit the
        same greedy stream."""
        prompt = [2, 4, 6, 8, 1]
        paged = _cold(net, prompt, max_tokens=5)
        monkeypatch.setenv("DL4J_TPU_PREFIX_CACHE", "off")
        registry, sched, mgr = _plane(net)
        try:
            assert not mgr.prefix_enabled
            assert mgr.snapshot()["prefix_cache"]["enabled"] is False
            assert _run(mgr, prompt, max_tokens=5) == paged
        finally:
            sched.shutdown()
            registry.close()

    def test_int8_shared_pages_bit_exact(self, net):
        """Quantized pages carry per-(token, kv-head) scales inside the
        page, so a follower dequantizes with the donor's exact scales:
        warm int8 == cold int8, bit for bit."""
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5]
        registry, sched, mgr = _plane(net, kv_dtype="int8")
        try:
            cold = _run(mgr, prompt, max_tokens=4)
            warm = _run(mgr, prompt, max_tokens=4)
            assert warm == cold
            pc = mgr.snapshot()["prefix_cache"]
            assert pc["hits"] == 1 and pc["hit_tokens"] == 8
        finally:
            sched.shutdown()
            registry.close()


# ------------------------------------------------- accounting / churn
class TestPageAccounting:
    def test_refcounts_reconcile_after_churn(self, net):
        registry, sched, mgr = _plane(net)
        try:
            for i, p in enumerate(([1, 2, 3, 4, 5], [1, 2, 3, 4, 9],
                                   [7, 7, 7], [1, 2, 3, 4, 5])):
                _run(mgr, p, max_tokens=3)
            pc = mgr.snapshot()["prefix_cache"]
            # every page is either free or held by the cache — no
            # leaked session references after the sessions finished
            assert pc["pages_free"] + pc["cached_pages"] == pc["pages"]
            assert pc["inserts"] >= 2 and pc["hits"] >= 2
        finally:
            sched.shutdown()
            registry.close()

    def test_admission_failure_releases_pages(self, net):
        from deeplearning4j_tpu.serving import SlotPoolExhaustedError
        registry, sched, mgr = _plane(net, slots=2)
        try:
            _run(mgr, [1, 2, 3, 4, 5, 6, 7, 8, 9], max_tokens=4)
            with mgr.pool.lock():
                free0 = mgr.pool.pages_free_locked()
                # pin every free page so admission cannot be satisfied
                pinned = mgr.pool.page_alloc_locked(free0)
            with pytest.raises(SlotPoolExhaustedError):
                mgr.open_session([9, 8, 7, 6, 5, 4, 3], max_tokens=8,
                                 alloc_timeout_s=0.0)
            with mgr.pool.lock():
                for p in pinned:
                    mgr.pool.page_unref_locked(p)
            # the failed admission leaked nothing: the slot and every
            # adopted/fresh page came back
            assert mgr.pool.describe()["in_use"] == 0
            pc = mgr.snapshot()["prefix_cache"]
            assert pc["pages_free"] + pc["cached_pages"] == pc["pages"]
        finally:
            sched.shutdown()
            registry.close()

    def test_matched_pages_pinned_against_admission_eviction(self, net):
        """Page pressure during admission must never evict the chain
        match() just returned: matched pages (shared full pages AND the
        partial CoW source) are pinned to refcount 2 before the LRU
        sweep runs, so a too-short pool fails with a clean
        SlotPoolExhaustedError — not a page_ref ValueError on a freed
        page — and the cached chain survives intact."""
        from deeplearning4j_tpu.serving import SlotPoolExhaustedError
        prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]      # stem 8 = 2 pages
        registry, sched, mgr = _plane(net, slots=2)
        try:
            reference = _run(mgr, prompt, max_tokens=4)
            with mgr.pool.lock():
                pinned = mgr.pool.page_alloc_locked(
                    mgr.pool.pages_free_locked())
            # full-stem warm admission: both matched pages are
            # cache-only (refcount 1) and would be the LRU sweep's only
            # candidates — the pin must keep them out of its reach
            with pytest.raises(SlotPoolExhaustedError):
                mgr.open_session(prompt, max_tokens=1,
                                 alloc_timeout_s=0.0)
            # mid-page divergence: same pressure, now with the CoW
            # source page pinned transiently too
            with pytest.raises(SlotPoolExhaustedError):
                mgr.open_session([1, 2, 3, 4, 5, 6, 9, 9, 9],
                                 max_tokens=1, alloc_timeout_s=0.0)
            with mgr.pool.lock():
                for p in pinned:
                    mgr.pool.page_unref_locked(p)
            pc = mgr.snapshot()["prefix_cache"]
            assert pc["cached_pages"] == 2, \
                "admission eviction ate the matched chain"
            assert pc["pages_free"] + pc["cached_pages"] == pc["pages"]
            assert mgr.pool.describe()["in_use"] == 0
            # the surviving chain still serves warm, bit-exact
            assert _run(mgr, prompt, max_tokens=4) == reference
        finally:
            sched.shutdown()
            registry.close()

    def test_zero_recompiles_warm_churn(self, net):
        registry, sched, mgr = _plane(net)
        try:
            _run(mgr, [1, 2, 3, 4, 5, 6, 7, 8, 9], max_tokens=4)
            c0 = get_watchdog().compiles()
            for i in range(3):
                _run(mgr, [1, 2, 3, 4, 5, 6, 7, 8, 9], max_tokens=4)
                _run(mgr, [1, 2, 3, 4, 5, 6, 9 - i, 9], max_tokens=3)
            assert get_watchdog().compiles() == c0, \
                "warm prefix admission caused recompiles"
        finally:
            sched.shutdown()
            registry.close()


# ---------------------------------------------- hot-swap / rebind
class TestHotSwapCoherence:
    def test_flipped_deploy_flushes_radix(self, net):
        registry, sched, mgr = _plane(net)
        try:
            prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
            _run(mgr, prompt, max_tokens=4)
            assert mgr.snapshot()["prefix_cache"]["cached_pages"] > 0
            v2 = _make_net(seed=5)
            registry.deploy("default", 2, v2, feat_shape=(T, 1))
            pc = mgr.snapshot()["prefix_cache"]
            assert pc["cached_pages"] == 0, "stale KV survived the flip"
            assert pc["pages_free"] == pc["pages"]
            # the same prompt under v2 must MISS (then re-index) and
            # match v2's own cold stream — never the old weights' KV
            got = _run(mgr, prompt, max_tokens=4)
            assert got == _cold(v2, prompt, max_tokens=4)
            assert mgr.snapshot()["prefix_cache"]["misses"] >= 2
        finally:
            sched.shutdown()
            registry.close()

    def test_straddling_session_never_reindexes_after_flip(self, net):
        """A session admitted under the OLD weights whose first decode
        row lands after the flip must NOT repopulate the flushed radix:
        its pages hold old-weight KV, and a new-weight session matching
        them would silently decode wrong logits. The straddler is
        driven deterministically through the admission internals
        (admission is synchronous; the flip lands before its first
        decode row would have run)."""
        from deeplearning4j_tpu.serving.sessions import DecodeSession
        from deeplearning4j_tpu.utils.sampling import SamplingParams
        prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
        registry, sched, mgr = _plane(net)
        try:
            _run(mgr, prompt, max_tokens=4)   # warm the radix under v1
            slot = mgr.pool.alloc(0.0)
            with mgr.pool.lock():
                gen0 = mgr._prefix_gen
                cl, chain = mgr._admit_pages(
                    slot, np.asarray(prompt, np.int64), 4, 0)
            sess = DecodeSession(
                "straddler", slot, np.asarray(prompt, np.int64),
                max_tokens=4, params=SamplingParams(greedy=True),
                seed=0, deadline_ms=None, eos_id=None)
            sess._pages, sess._cached_len, sess._gen = chain, cl, gen0
            v2 = _make_net(seed=5)
            registry.deploy("default", 2, v2, feat_shape=(T, 1))
            assert mgr.snapshot()["prefix_cache"]["cached_pages"] == 0
            # first decode row after the flip offers the prefix back:
            # the generation stamp must refuse it
            mgr._insert_prefix(sess)
            assert mgr.snapshot()["prefix_cache"]["cached_pages"] == 0, \
                "old-weight KV re-indexed after the flip"
            # teardown exactly as _finish would
            mgr.pool.free(slot)
            with mgr.pool.lock():
                for p in chain:
                    mgr.pool.page_unref_locked(p)
            pc = mgr.snapshot()["prefix_cache"]
            assert pc["pages_free"] == pc["pages"]
            # a fresh session under v2 cold-prefills, re-indexes under
            # the NEW generation, and matches v2's own cold stream
            assert _run(mgr, prompt, max_tokens=4) == _cold(
                v2, prompt, max_tokens=4)
            assert mgr.snapshot()["prefix_cache"]["cached_pages"] > 0
        finally:
            sched.shutdown()
            registry.close()

    def test_unpageable_candidate_rolls_back(self, net):
        from test_decode_sessions import _make_net as _rolling_net
        from deeplearning4j_tpu.serving.registry import (
            DeployRolledBackError,
        )
        registry, sched, mgr = _plane(net)
        try:
            assert mgr.prefix_enabled
            with pytest.raises(DeployRolledBackError):
                registry.deploy("default", 2, _rolling_net(seed=9),
                                feat_shape=(T, 1))
            assert len(_run(mgr, [1, 2], max_tokens=4)) == 4
        finally:
            sched.shutdown()
            registry.close()


# ------------------------------------------------------ policy seam
class TestPrefixCachePolicy:
    def test_lattice_and_page_snapping(self, monkeypatch):
        from deeplearning4j_tpu.ops.kernel_defaults import (
            prefix_cache_policy,
        )
        monkeypatch.delenv("DL4J_TPU_PREFIX_CACHE", raising=False)
        monkeypatch.delenv("DL4J_TPU_KV_PAGE", raising=False)
        pol = prefix_cache_policy(max_cache=1024, record=False)
        assert pol.kind == "paged" and pol.page_len == 128
        # snapped down to the largest divisor of max_cache
        assert prefix_cache_policy(max_cache=48,
                                   record=False).page_len == 48
        assert prefix_cache_policy(6, max_cache=16,
                                   record=False).page_len == 4
        assert prefix_cache_policy(capable=False,
                                   record=False).kind == "off"
        monkeypatch.setenv("DL4J_TPU_PREFIX_CACHE", "off")
        assert prefix_cache_policy(record=False).kind == "off"
        monkeypatch.setenv("DL4J_TPU_PREFIX_CACHE", "on")
        assert prefix_cache_policy(record=False).kind == "paged"
        # forced on but structurally impossible still degrades
        assert prefix_cache_policy(capable=False,
                                   record=False).kind == "off"
        monkeypatch.delenv("DL4J_TPU_PREFIX_CACHE", raising=False)
        monkeypatch.setenv("DL4J_TPU_KV_PAGE", "8")
        assert prefix_cache_policy(max_cache=64,
                                   record=False).page_len == 8

    def test_capability_and_verdict_mirror(self, net):
        from test_decode_sessions import _make_net as _rolling_net
        assert net.prefix_cache_capable()
        assert not _rolling_net().prefix_cache_capable()
        registry, sched, mgr = _plane(net)
        try:
            assert mgr.metrics.counter("kernel_dispatch_total",
                                       op="prefix_cache",
                                       impl="paged").value >= 1
        finally:
            sched.shutdown()
            registry.close()

    def test_draft_model_disables_paging(self, net):
        """Spec decode's lockstep draft pool must prefill every token —
        the two optimizations are mutually exclusive, draft wins."""
        from deeplearning4j_tpu.serving import (
            ContinuousBatchingScheduler, ModelRegistry, ServingStats,
        )
        from deeplearning4j_tpu.serving.sessions import (
            DecodeSessionManager,
        )
        registry = ModelRegistry()
        registry.deploy("default", 1, net, warm=False)
        stats = ServingStats()
        sched = ContinuousBatchingScheduler(registry, stats,
                                            max_batch_size=8)
        mgr = DecodeSessionManager(registry, sched, "default", slots=2,
                                   draft_net=net, spec_k=4,
                                   metrics=stats.registry)
        try:
            assert mgr.spec_enabled and not mgr.prefix_enabled
            assert "draft" in mgr.snapshot()["prefix_cache"]["reason"]
        finally:
            sched.shutdown()
            registry.close()


# ------------------------------------------------------------- chaos
POISON = 7777.0      # finite: NaNs would mask "never read" as "read"


@pytest.mark.chaos
class TestPrefixCacheChaos:
    def test_eviction_under_pressure_with_poisoned_free_pages(self, net):
        """Fill the radix, open a live session, then poison every FREED
        page and force eviction-driven churn: the survivor's stream
        must be bit-exact — eviction may only ever touch pages no live
        session maps, and a freed page's stale bytes must be invisible
        to subsequent tenants."""
        survivor_prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
        reference = _cold(net, survivor_prompt, max_tokens=6)
        registry, sched, mgr = _plane(net, slots=2)
        try:
            # warm the radix with the survivor's prefix, then start the
            # survivor but DON'T drain it yet
            _run(mgr, survivor_prompt, max_tokens=2)
            survivor = mgr.open_session(survivor_prompt, max_tokens=6,
                                        greedy=True)
            # churn disjoint prompts through the other slot: each needs
            # fresh pages, forcing LRU eviction of cache-only chains
            for i in range(3):
                _run(mgr, [10 + i % 3, 9 - i, 8, 7, 6, 5], max_tokens=3)
                with mgr.pool.lock():
                    free = [p for p in range(mgr.pool.pages)
                            if mgr.pool.page_refcount_locked(p) == 0]
                    mgr.pool.poison_pages_locked(free, POISON)
            assert survivor.result(timeout=60) == reference, \
                "eviction/poison corrupted a live session's pages"
            pc = mgr.snapshot()["prefix_cache"]
            assert pc["evicted_pages"] > 0, "pressure never evicted"
            assert pc["pages_free"] + pc["cached_pages"] == pc["pages"]
        finally:
            sched.shutdown()
            registry.close()

    def test_kill_mid_cow_fork_reconciles_refcounts(self, net):
        """Die between the CoW admission and the first window: the
        forked private page and every adopted shared page must come
        back, and the donor's cached chain must still serve warm hits.
        The kill is driven deterministically through the admission path
        (admission is synchronous; the 'session' dies before its first
        dispatch), then the real cancel path is exercised on top."""
        donor = [1, 2, 3, 4, 5, 6, 7, 8, 9]
        follower = [1, 2, 3, 4, 5, 6, 9, 9, 9]
        registry, sched, mgr = _plane(net, slots=2)
        try:
            _run(mgr, donor, max_tokens=4)
            with mgr.pool.lock():
                ref0 = [mgr.pool.page_refcount_locked(p)
                        for p in range(mgr.pool.pages)]
            slot = mgr.pool.alloc(0.0)
            with mgr.pool.lock():
                cl, chain = mgr._admit_pages(
                    slot, np.asarray(follower, np.int64), 4, 0)
            assert cl == 6            # 1 shared page + lcp 2 into page 2
            assert mgr.snapshot()["prefix_cache"]["cow_forks"] == 1
            # the kill: exactly what _finish does for a dead session
            mgr.pool.free(slot)
            with mgr.pool.lock():
                for p in chain:
                    mgr.pool.page_unref_locked(p)
                ref1 = [mgr.pool.page_refcount_locked(p)
                        for p in range(mgr.pool.pages)]
            assert ref1 == ref0, "mid-CoW kill leaked page references"
            # the real cancel path on a live follower: whatever window
            # count it reached, the global accounting must reconcile
            f = mgr.open_session(follower, max_tokens=7, greedy=True)
            f.cancel()
            assert f.done.wait(30)
            assert mgr.pool.describe()["in_use"] == 0
            pc = mgr.snapshot()["prefix_cache"]
            assert pc["pages_free"] + pc["cached_pages"] == pc["pages"]
            # the donor's chain still serves: warm full-stem hit
            hits0 = pc["hits"]
            got = _run(mgr, donor, max_tokens=4)
            assert got == _cold(net, donor, max_tokens=4)
            assert mgr.snapshot()["prefix_cache"]["hits"] == hits0 + 1
        finally:
            sched.shutdown()
            registry.close()
