"""Kernel-default consistency guard (VERDICT r4 #5).

A hand-written kernel may only be a dispatch default where a recorded
hardware measurement says it beats its XLA alternative — the discipline
the reference applied to its cuDNN helpers
(`deeplearning4j-cuda/.../CudnnConvolutionHelper.java:54`). These tests
fail if:
  - the MEASURED table embedded in ops/kernel_defaults.py has drifted
    from tools/kernel_bench_results.json (updater not re-run), or
  - the policy would pick a kernel configuration that contradicts (or
    lacks) its measured winning row.
"""
import json
import os

import pytest

from deeplearning4j_tpu.ops import kernel_defaults as kd

HERE = os.path.dirname(os.path.abspath(__file__))
RESULTS = os.path.join(HERE, "..", "tools", "kernel_bench_results.json")


def _tpu_shapes(monkeypatch):
    """Simulate the TPU shape gate so policy decisions are testable on
    the CPU suite (same tiling/floor logic, minus the backend check)."""
    monkeypatch.setattr(
        kd, "_shape_eligible",
        lambda tq, tk, min_t=512: (tq % 128 == 0 and tk % 128 == 0
                                   and min(tq, tk) >= min_t))


def test_embedded_table_matches_results_file():
    import sys
    sys.path.insert(0, os.path.join(HERE, "..", "tools"))
    try:
        from update_kernel_defaults import build_table
    finally:
        sys.path.pop(0)
    with open(RESULTS) as fh:
        rows = json.load(fh)
    assert kd.MEASURED == build_table(rows), (
        "ops/kernel_defaults.py MEASURED table is stale — run "
        "python tools/update_kernel_defaults.py after benching")


def test_attention_policy_agrees_with_measured_winners(monkeypatch):
    _tpu_shapes(monkeypatch)
    for mode, by_t in kd.MEASURED["attention"].items():
        train = mode == "train"
        for t, row in by_t.items():
            if t >= kd.dense_max_t():
                continue   # memory necessity overrides the speed verdict
            pol = kd.attention_policy(t, train=train)
            assert pol.kind == row["winner"], (
                f"{mode}@T={t}: policy picks {pol.kind} but measured "
                f"winner is {row['winner']} ({row['flash_ms']} vs "
                f"{row['dense_ms']} ms)")
            if pol.kind == "flash":
                assert (pol.block_q, pol.block_k) == (
                    row["block_q"], row["block_k"]), (
                    f"{mode}@T={t}: policy blocks {pol.block_q}x"
                    f"{pol.block_k} != measured best "
                    f"{row['block_q']}x{row['block_k']}")


def test_flash_default_requires_winning_row(monkeypatch):
    """The sharpest r4 finding: no flash-by-default without a recorded
    win. If the policy would use flash below the memory threshold, a
    winning measured row must exist at the nearest benchmarked T."""
    _tpu_shapes(monkeypatch)
    for t in (512, 1024, 2048, 4096):
        for train in (False, True):
            pol = kd.attention_policy(t, train=train)
            if pol.kind != "flash" or t >= kd.dense_max_t():
                continue
            mode = "train" if train else "fwd"
            table = kd.MEASURED["attention"][mode]
            mt = kd._nearest_measured(table, t)
            assert mt is not None and table[mt]["winner"] == "flash", (
                f"flash default at T={t} ({mode}) has no winning "
                f"measured row backing it")


def test_pallas_backward_requires_winning_row(monkeypatch):
    _tpu_shapes(monkeypatch)
    for t in (512, 1024, 2048, 4096):
        if t >= kd.dense_max_t():
            continue
        if kd.attention_backward(t) == "pallas":
            table = kd.MEASURED["attention"]["train"]
            mt = kd._nearest_measured(table, t)
            assert (mt is not None
                    and table[mt]["winner"] == "flash"
                    and table[mt]["backward"] == "pallas"), (
                f"pallas backward default at T={t} lacks a winning "
                f"measured train row")


def test_memory_necessity_overrides_speed(monkeypatch):
    """Past DENSE_MAX_T the [T, T] dense path is a memory hazard: flash
    with the O(T) Pallas backward is mandatory regardless of verdicts."""
    _tpu_shapes(monkeypatch)
    t = kd.dense_max_t()
    pol = kd.attention_policy(t, train=True)
    assert pol.kind == "flash"
    assert pol.backward == "pallas"
    # the hazard scales with Tq*Tk, not min: a long-context
    # cross-attention with a short query side must also route to flash
    pol = kd.attention_policy(t // 4, t * 4, train=True)
    assert pol.kind == "flash"
    assert pol.backward == "pallas"
    assert kd.attention_backward(t // 4, t * 4) == "pallas"
    # ...even when the query side is below the 512 perf floor — the
    # kernel capability floor (128) governs the memory-necessity path
    pol = kd.attention_policy(256, 2 * t * t // 256, train=True)
    assert pol.kind == "flash", pol
    # but below the perf floor WITHOUT memory pressure, dense wins
    assert kd.attention_policy(256, 256, train=True).kind == "dense"


def test_env_escape_hatches(monkeypatch):
    _tpu_shapes(monkeypatch)
    monkeypatch.setenv("DL4J_TPU_ATTN", "dense")
    assert kd.attention_policy(8192, train=True).kind == "dense"
    monkeypatch.setenv("DL4J_TPU_ATTN", "flash")
    pol = kd.attention_policy(1024, train=True)
    assert pol.kind == "flash"
    monkeypatch.setenv("DL4J_TPU_ATTN_BACKWARD", "pallas")
    assert kd.attention_policy(1024, train=True).backward == "pallas"
    monkeypatch.setenv("DL4J_TPU_ATTN_BLOCK", "256x128")
    pol = kd.attention_policy(1024, train=True)
    assert (pol.block_q, pol.block_k) == (256, 128)
    # shape ineligibility still wins over a flash force
    monkeypatch.setenv("DL4J_TPU_ATTN", "flash")
    assert kd.attention_policy(1000, train=True).kind == "dense"


def test_dense_max_t_env(monkeypatch):
    _tpu_shapes(monkeypatch)
    monkeypatch.setenv("DL4J_TPU_DENSE_MAX_T", "2048")
    assert kd.attention_policy(2048, train=True).kind == "flash"


def test_lstm_policy_agrees_with_measured(monkeypatch):
    table = kd.MEASURED["lstm"]
    assert table, "no LSTM rows measured at all"
    for mode, row in table.items():
        assert kd.lstm_policy(train=(mode == "train")) == row["winner"]
    monkeypatch.setenv("DL4J_TPU_LSTM", "scan")
    assert kd.lstm_policy() == "scan"


def test_flash_attention_backward_resolution_matches_policy():
    """flash_attention(backward=None) resolves through the same
    function the policy uses, so layer dispatch and direct op calls
    can't disagree."""
    from deeplearning4j_tpu.ops.attention import _resolve_backward

    for t in (512, 1024, 2048, 8192):
        assert _resolve_backward(None, t, t) == kd.attention_backward(t)
    assert _resolve_backward("pallas", 1024, 1024) == "pallas"


def _banded_shapes(monkeypatch, value=True):
    """Simulate TPU eligibility for the banded kernel gates (the
    policies import these at call time, so patching the op module's
    attributes reaches them)."""
    # NB: ops/__init__ re-exports a function named banded_attention
    # that shadows the module attribute — go through sys.modules
    import importlib
    ba = importlib.import_module(
        "deeplearning4j_tpu.ops.banded_attention")
    monkeypatch.setattr(
        ba, "banded_eligible",
        lambda t, h, hkv, min_t=256, any_backend=False: value)
    monkeypatch.setattr(ba, "decode_eligible",
                        lambda cache_len, h, hkv: value)


def test_banded_policy_env_hatches(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_ATTN", "dense")
    assert kd.banded_policy(256, 4, 2).kind == "dense"
    # flash cannot band: a flash force on a windowed shape stays dense
    monkeypatch.setenv("DL4J_TPU_ATTN", "flash")
    assert kd.banded_policy(256, 4, 2).kind == "dense"
    monkeypatch.setenv("DL4J_TPU_ATTN", "banded")
    pol = kd.banded_policy(256, 4, 2)
    assert pol.kind == "banded"
    assert (pol.block_q, pol.block_k) == (256, 256)
    # block override flows through the force, like flash's
    monkeypatch.setenv("DL4J_TPU_ATTN_BLOCK", "128x64")
    pol = kd.banded_policy(256, 4, 2)
    assert (pol.block_q, pol.block_k) == (128, 64)
    monkeypatch.delenv("DL4J_TPU_ATTN_BLOCK")
    # ...but tiling ineligibility still wins over the force (backend
    # does NOT: a force runs interpret-mode off-TPU by design)
    assert kd.banded_policy(100, 4, 2).kind == "dense"
    assert kd.banded_policy(256, 4, 3).kind == "dense"   # h % hkv != 0


def test_banded_policy_conservative_without_rows(monkeypatch):
    """Dispatch discipline: even on eligible shapes, banded is not the
    default until a winning MEASURED['banded'] row exists. When a real
    banded bench lands, update this pin together with the table."""
    if kd.MEASURED.get("banded"):
        pytest.skip("banded rows measured; pin no longer applies")
    _banded_shapes(monkeypatch)
    for train in (False, True):
        pol = kd.banded_policy(1024, 8, 2, train=train)
        assert pol.kind == "dense", pol
        assert "no measured rows" in pol.reason


def test_banded_policy_agrees_with_measured_winners(monkeypatch):
    _banded_shapes(monkeypatch)
    for mode, by_t in kd.MEASURED.get("banded", {}).items():
        train = mode == "train"
        for t, row in by_t.items():
            if not train and kd._mem_hazard(t, t):
                continue   # memory necessity overrides the verdict
            pol = kd.banded_policy(t, 8, 2, train=train)
            assert pol.kind == row["winner"], (
                f"banded {mode}@T={t}: policy picks {pol.kind} but "
                f"measured winner is {row['winner']}")
            if pol.kind == "banded":
                assert (pol.block_q, pol.block_k) == (
                    row["block_q"], row["block_k"])


def test_decode_policy_env_and_default(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_DECODE_ATTN", "dense")
    assert kd.decode_attention_policy(512, 8, 2).kind == "dense"
    monkeypatch.setenv("DL4J_TPU_DECODE_ATTN", "banded")
    pol = kd.decode_attention_policy(512, 8, 2)
    assert pol.kind == "banded" and pol.block_l == 512
    monkeypatch.delenv("DL4J_TPU_DECODE_ATTN")
    # eligible shape, no measured rows -> conservative dense
    if kd.MEASURED.get("decode"):
        pytest.skip("decode rows measured; pin no longer applies")
    _banded_shapes(monkeypatch)
    pol = kd.decode_attention_policy(512, 8, 2)
    assert pol.kind == "dense"
    assert "no measured rows" in pol.reason


def test_decode_policy_record_flag_gates_counter(monkeypatch):
    """Observers (serving snapshots) ask what WOULD dispatch with
    record=False; kernel_dispatch_total must count only real dispatch
    sites, or snapshot polling would inflate the metric."""
    from deeplearning4j_tpu.observe import get_registry
    monkeypatch.setenv("DL4J_TPU_DECODE_ATTN", "dense")
    c = get_registry().counter("kernel_dispatch_total",
                               op="decode_attention", impl="dense")
    v0 = c.value
    kd.decode_attention_policy(512, 8, 2, record=False)
    assert c.value == v0
    kd.decode_attention_policy(512, 8, 2)
    assert c.value == v0 + 1


def test_fused_update_policy(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_FUSED_UPDATE", "fused")
    assert kd.fused_update_policy("adam") == "fused"
    monkeypatch.setenv("DL4J_TPU_FUSED_UPDATE", "xla")
    assert kd.fused_update_policy("adam") == "xla"
    monkeypatch.delenv("DL4J_TPU_FUSED_UPDATE")
    for kind in ("adam", "nesterov"):
        row = kd.MEASURED.get("fused_update", {}).get(kind)
        if row is None:
            # no data: XLA is the conservative default (off-TPU the
            # availability gate forces it regardless)
            assert kd.fused_update_policy(kind) == "xla"


def test_current_data_yields_dense_defaults(monkeypatch):
    """Regression pin for the r4 ADVICE finding: with the rows recorded
    today (flash loses everywhere measured), training and inference
    attention below the memory threshold must default to XLA dense.
    When a winning 512-block sweep is persisted, this test must be
    UPDATED alongside the table — that is the point: defaults move only
    together with data."""
    _tpu_shapes(monkeypatch)
    table = kd.MEASURED["attention"]
    if any(r["winner"] == "flash"
           for by_t in table.values() for r in by_t.values()):
        pytest.skip("a winning flash row exists; pin no longer applies")
    assert kd.attention_policy(2048, train=True).kind == "dense"
    assert kd.attention_policy(2048, train=False).kind == "dense"
    assert kd.attention_backward(2048) == "dense"
