"""Sharded/async checkpoint + exact training resume (SURVEY §7 step 4:
"checkpoint zip ↦ sharded async ckpt"; §5 elastic-recovery gap).

The kill-and-resume test is the acceptance criterion from the round-1
verdict: an interrupted FSDP run restored from the sharded snapshot must
reproduce the uninterrupted run's loss curve exactly.
"""

import os

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optim.updaters import Adam
from deeplearning4j_tpu.parallel import ParallelWrapper, ShardedCheckpointer
from deeplearning4j_tpu.parallel.mesh import AXIS_DATA
from deeplearning4j_tpu.parallel.sharding import ShardingRules


def _net(seed=7):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .list(
            DenseLayer(n_in=12, n_out=32, activation="relu"),
            DenseLayer(n_out=32, n_in=32, activation="relu"),
            OutputLayer(n_in=32, n_out=4, activation="softmax",
                        loss="mcxent"),
        )
        .build()
    ).init()


def _data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 12)).astype(np.float32)
    yi = rng.integers(0, 4, n)
    x[np.arange(n), yi % 12] += 2.0
    return x, np.eye(4, dtype=np.float32)[yi]


def _fsdp_rules():
    # scope to the 32-wide dense layers (output layer's 4 cols can't split 8)
    return ShardingRules(rules=[("*dense*", "W", P(None, AXIS_DATA)),
                                ("*dense*", "b", P(AXIS_DATA))])


class _Recorder:
    """Minimal listener capturing the loss curve."""

    def __init__(self):
        self.losses = []

    def __getattr__(self, name):
        if name.startswith("on_") or name in ("iteration_done",):
            if name == "iteration_done":
                return lambda net, it, ep, loss: self.losses.append(loss)
            return lambda *a, **k: None
        raise AttributeError(name)


class TestShardedCheckpointer:
    def test_fsdp_shards_written_per_device_slice(self, tmp_path, devices8):
        """An FSDP-sharded leaf writes N distinct slice files, a replicated
        leaf exactly one — no host-side gather of the global array."""
        mesh = Mesh(np.array(devices8), (AXIS_DATA,))
        net = _net()
        ParallelWrapper(net, mesh=mesh, param_rules=_fsdp_rules())
        ck = ShardedCheckpointer(str(tmp_path / "ck"), async_save=False)
        ck.save(net, step=1)
        import json
        pdir = tmp_path / "ck" / "step-0000000001" / "process-0"
        manifest = json.loads((pdir / "manifest.json").read_text())
        w0 = manifest["leaves"]["params:layer0_denselayer/W"]
        assert len(w0["shards"]) == 8       # one file per mesh slice
        # 32 cols sharded over 8 devices → 4-wide column slices
        assert w0["shards"][0]["index"][1][1] - \
            w0["shards"][0]["index"][1][0] == 4
        st = manifest["leaves"]["state:layer0_denselayer"] \
            if "state:layer0_denselayer" in manifest["leaves"] else None
        # replicated iteration-step scalar in updater state: single shard
        any_rep = [v for k, v in manifest["leaves"].items()
                   if len(v["shards"]) == 1]
        assert any_rep

    def test_roundtrip_restores_sharded_values(self, tmp_path, devices8):
        mesh = Mesh(np.array(devices8), (AXIS_DATA,))
        net = _net()
        w = ParallelWrapper(net, mesh=mesh, param_rules=_fsdp_rules())
        x, y = _data()
        w.fit(x, y, epochs=1, batch_size=64)
        ck = ShardedCheckpointer(str(tmp_path / "ck"), async_save=False)
        ck.save(net, step=net.iteration)

        net2 = _net(seed=99)   # different init
        w2 = ParallelWrapper(net2, mesh=mesh, param_rules=_fsdp_rules())
        ck.restore_into_wrapper(w2)
        for lname, sub in net.params_tree.items():
            for k, v in sub.items():
                np.testing.assert_array_equal(np.asarray(v),
                                              np.asarray(net2.params_tree[lname][k]))
        # restored leaves carry the wrapper's NamedSharding (stay on mesh)
        leaf = net2.params_tree["layer0_denselayer"]["W"]
        # str() because shard.index is a tuple of slices and slice is
        # unhashable before Python 3.12
        assert len({str(s.index) for s in leaf.addressable_shards}) == 8
        assert net2.iteration == net.iteration

    def test_kill_and_resume_reproduces_loss_curve(self, tmp_path, devices8):
        """Train 8 iterations straight vs. train 4 + 'kill' + restore +
        resume 4: the last 4 losses must match to float tolerance."""
        mesh = Mesh(np.array(devices8), (AXIS_DATA,))
        x, y = _data()

        # --- uninterrupted run ---
        net_a = _net()
        wa = ParallelWrapper(net_a, mesh=mesh, param_rules=_fsdp_rules())
        rec_a = _Recorder()
        net_a.listeners.append(rec_a)
        wa.fit(x, y, epochs=2, batch_size=64)       # 4 batches/epoch
        assert len(rec_a.losses) == 8

        # --- interrupted run: checkpoint every step, stop after epoch 1 ---
        net_b = _net()
        wb = ParallelWrapper(net_b, mesh=mesh, param_rules=_fsdp_rules())
        rec_b = _Recorder()
        net_b.listeners.append(rec_b)
        ck = ShardedCheckpointer(str(tmp_path / "ck"), max_to_keep=2)
        wb.fit(x, y, epochs=1, batch_size=64, checkpointer=ck)
        ck.wait()
        assert ck.latest_step() == 4
        del net_b, wb  # "kill"

        # --- resume in a fresh wrapper ---
        net_c = _net(seed=1234)  # init is irrelevant, restore overwrites
        wc = ParallelWrapper(net_c, mesh=mesh, param_rules=_fsdp_rules())
        rec_c = _Recorder()
        net_c.listeners.append(rec_c)
        pos = ck.restore_into_wrapper(wc)
        wc.fit(x, y, epochs=2, batch_size=64, resume=pos)
        assert len(rec_c.losses) == 4
        np.testing.assert_allclose(rec_b.losses + rec_c.losses, rec_a.losses,
                                   rtol=1e-5, atol=1e-6)

    def test_mid_epoch_resume(self, tmp_path, devices8):
        """Kill mid-epoch: resume skips exactly the consumed batches."""
        mesh = Mesh(np.array(devices8), (AXIS_DATA,))
        x, y = _data()
        net_a = _net()
        wa = ParallelWrapper(net_a, mesh=mesh)
        rec_a = _Recorder()
        net_a.listeners.append(rec_a)
        wa.fit(x, y, epochs=1, batch_size=64)

        net_b = _net()
        wb = ParallelWrapper(net_b, mesh=mesh)
        ck = ShardedCheckpointer(str(tmp_path / "ck2"))
        rec_b = _Recorder()
        net_b.listeners.append(rec_b)
        # manually run 2 of the 4 batches, checkpointing
        wb.fit(x[:128], y[:128], epochs=1, batch_size=64, checkpointer=ck)
        ck.wait()
        pos = {"batch_in_epoch": 2}  # as if killed after batch 2 of 4

        net_c = _net(seed=5)
        wc = ParallelWrapper(net_c, mesh=mesh)
        rec_c = _Recorder()
        net_c.listeners.append(rec_c)
        restored = ck.restore_into_wrapper(wc)
        assert restored["batch_in_epoch"] == 2
        net_c.epoch = 0
        wc.fit(x, y, epochs=1, batch_size=64, resume=pos)
        assert len(rec_c.losses) == 2  # only batches 3 and 4
        np.testing.assert_allclose(rec_b.losses + rec_c.losses, rec_a.losses,
                                   rtol=1e-5, atol=1e-6)

    def test_async_save_does_not_block_and_commits(self, tmp_path, devices8):
        mesh = Mesh(np.array(devices8), (AXIS_DATA,))
        net = _net()
        ParallelWrapper(net, mesh=mesh)
        ck = ShardedCheckpointer(str(tmp_path / "ck3"), async_save=True)
        for s in (1, 2, 3, 4, 5):
            ck.save(net, step=s)
        ck.wait()
        assert ck.steps() == [3, 4, 5]  # rotation kept max_to_keep=3
        for s in ck.steps():
            d = tmp_path / "ck3" / f"step-{s:010d}" / "process-0"
            assert (d / "COMMIT").exists()

    def test_restore_without_checkpoint_raises(self, tmp_path):
        ck = ShardedCheckpointer(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError):
            ck.restore_into(_net())

    def test_wait_error_latch_drains(self, tmp_path):
        """One failed write surfaces exactly once; it must not poison
        every later wait() (ISSUE 6 satellite)."""
        from deeplearning4j_tpu.parallel.chaos import (
            CheckpointIOFault, InjectedFault,
        )

        net = _net()
        ck = ShardedCheckpointer(str(tmp_path / "ck"), async_save=True)
        ck.fault_hook = CheckpointIOFault(fail_after=0, kind="manifest",
                                          times=1)
        ck.save(net, step=1)
        with pytest.raises(InjectedFault):
            ck.wait()
        ck.wait()                       # latch drained — no stale error
        ck.save(net, step=2)            # writer thread is still healthy
        ck.wait()
        assert ck.latest_step() == 2

    def test_rotation_never_deletes_pinned_step(self, tmp_path):
        """A step being read by a racing restore is pinned; rotation must
        skip it instead of deleting it under the reader."""
        net = _net()
        ck = ShardedCheckpointer(str(tmp_path / "ck"), max_to_keep=1,
                                 async_save=False)
        ck.save(net, step=1)
        with ck._state_lock:            # a restore holds step 1 open
            ck._pinned.add(1)
        ck.save(net, step=2)
        ck.save(net, step=3)
        assert 1 in ck.steps()          # survived two rotations
        assert 3 in ck.steps()
        ck._read_step(1)                # still fully readable
        with ck._state_lock:            # reader done → next rotate culls
            ck._pinned.discard(1)
        ck.save(net, step=4)
        assert ck.steps() == [4]

    def test_steps_tolerates_stray_and_uncommitted_entries(self, tmp_path):
        """`steps()` runs concurrently with the writer's rotation: stray
        files matching the step prefix and uncommitted/vanishing dirs are
        simply not candidates — never an exception."""
        net = _net()
        ck = ShardedCheckpointer(str(tmp_path / "ck"), async_save=False)
        ck.save(net, step=1)
        (tmp_path / "ck" / "step-stray").write_text("not a dir")
        os.makedirs(tmp_path / "ck" / "step-0000000099")  # no COMMIT
        assert ck.steps() == [1]
        assert ck.latest_step() == 1
