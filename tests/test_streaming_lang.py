"""Streaming ingestion (dl4j-streaming parity) + CJK tokenizer tests."""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.data.streaming import (
    InMemoryBroker, NDArrayConsumer, NDArrayPublisher,
    StreamingDataSetIterator, bytes_to_ndarray, ndarray_to_bytes,
    record_to_ndarray,
)
from deeplearning4j_tpu.nlp.lang import (
    ChineseTokenizerFactory, JapaneseTokenizerFactory, KoreanTokenizerFactory,
)


class TestStreaming:
    def test_codec_roundtrip(self):
        for arr in (np.arange(12, dtype=np.float32).reshape(3, 4),
                    np.asarray([1.5], np.float64),
                    np.zeros((2, 3, 4), np.int32)):
            back = bytes_to_ndarray(ndarray_to_bytes(arr))
            assert back.dtype == arr.dtype
            np.testing.assert_array_equal(back, arr)

    def test_record_conversion(self):
        np.testing.assert_allclose(record_to_ndarray(["1.5", 2, "3"]),
                                   [1.5, 2.0, 3.0])

    def test_pub_sub(self):
        broker = InMemoryBroker()
        pub = NDArrayPublisher(broker, "t")
        sub = NDArrayConsumer(broker, "t", timeout=0.2)
        for i in range(5):
            pub.publish(np.full((2,), i, np.float32))
        got = list(sub)
        assert len(got) == 5
        np.testing.assert_allclose(got[3], [3, 3])

    def test_streaming_iterator_feeds_training(self):
        """Producer thread publishes while fit() consumes — the Camel-route-
        into-training-pipeline scenario."""
        from deeplearning4j_tpu.models import MultiLayerNetwork
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.optim.updaters import Adam

        broker = InMemoryBroker()
        rng = np.random.default_rng(0)
        w = rng.standard_normal((6, 2)).astype(np.float32)

        def produce():
            px = NDArrayPublisher(broker, "x")
            py = NDArrayPublisher(broker, "y")
            for _ in range(96):
                x = rng.standard_normal(6).astype(np.float32)
                y = np.eye(2, dtype=np.float32)[int(np.argmax(x @ w))]
                px.publish(x)
                py.publish(y)

        t = threading.Thread(target=produce)
        t.start()
        it = StreamingDataSetIterator(broker, features_topic="x",
                                      labels_topic="y", batch_size=32,
                                      timeout=1.0)
        net = MultiLayerNetwork(
            (NeuralNetConfiguration.builder()
             .seed(0).updater(Adam(1e-2)).activation("relu")
             .list(DenseLayer(n_out=8),
                   OutputLayer(n_out=2, activation="softmax"))
             .set_input_type(InputType.feed_forward(6))
             .build())).init()
        net.fit(it)
        t.join()
        assert np.isfinite(net.score_)
        assert net.iteration == 3  # 96 examples / batch 32

    def test_timeout_ends_epoch(self):
        it = StreamingDataSetIterator(InMemoryBroker(), features_topic="x",
                                      labels_topic="y", timeout=0.05)
        assert list(it) == []


class TestCJKTokenizers:
    def test_japanese_char_class_runs(self):
        tf = JapaneseTokenizerFactory()
        toks = tf.create("私はTPUで学習します123").tokens()
        assert "TPU" in toks
        assert "123" in toks
        # kanji and kana separated at class boundaries
        assert "私" in toks and "は" in toks

    def test_japanese_user_dictionary(self):
        tf = JapaneseTokenizerFactory(user_dictionary=["機械学習", "学習"])
        toks = tf.create("機械学習を学習する").tokens()
        assert "機械学習" in toks

    def test_chinese_unigram_and_dict(self):
        # without ANY lexicon: pure unigram fallback
        bare = ChineseTokenizerFactory(base_lexicon=())
        assert bare.create("我爱北京").tokens() == ["我", "爱", "北", "京"]
        # the embedded ZH_COMMON core knows 北京
        assert ChineseTokenizerFactory().create("我爱北京").tokens() == [
            "我", "爱", "北京"]
        toks = ChineseTokenizerFactory(["北京", "天安门"]).create(
            "我爱北京天安门").tokens()
        assert toks == ["我", "爱", "北京", "天安门"]

    def test_korean_particle_stripping(self):
        toks = KoreanTokenizerFactory().create("나는 학교에 간다").tokens()
        assert "나" in toks and "학교" in toks
        keep = KoreanTokenizerFactory(strip_particles=False).create(
            "나는 학교에 간다").tokens()
        assert "나는" in keep

    def test_factory_spi_with_word2vec(self):
        """CJK factories slot into the same SPI the embedding stack uses."""
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        sentences = ["我 爱 学习", "我 爱 北京", "学习 北京"] * 10
        w2v = Word2Vec(min_count=1, layer_size=8, epochs=1,
                       seed=1, tokenizer_factory=ChineseTokenizerFactory())
        w2v.fit(["".join(s.split()) for s in sentences])
        assert w2v.word_vector("我") is not None


class TestLatticeSegmentation:
    """kuromoji/ansj-class min-cost lattice segmentation (the round-1
    'far shallower than kuromoji' gap)."""

    def test_viterbi_beats_greedy(self):
        from deeplearning4j_tpu.nlp.lang import LatticeSegmenter

        seg = LatticeSegmenter({"研究": 2.0, "研究生": 3.0, "生命": 2.0})
        # greedy longest-match yields 研究生|命; min-cost finds 研究|生命
        assert seg.segment("研究生命") == ["研究", "生命"]

    def test_chinese_factory_embedded_lexicon(self):
        from deeplearning4j_tpu.nlp.lang import ChineseTokenizerFactory

        toks = ChineseTokenizerFactory().create("我们研究生命的起源").tokens()
        assert "研究" in toks and "生命" in toks and "我们" in toks
        # OOV hanzi degrade to unigrams (ansj fallback)
        assert "起" in toks and "源" in toks

    def test_japanese_factory_embedded_lexicon(self):
        from deeplearning4j_tpu.nlp.lang import JapaneseTokenizerFactory

        ja = JapaneseTokenizerFactory()
        assert ja.create("私は日本の学生です").tokens() == \
            ["私", "は", "日本", "の", "学生", "です"]
        # OOV katakana loanword stays ONE token (unknown-run grouping)
        toks = ja.create("コンピュータを勉強する").tokens()
        assert "コンピュータ" in toks and "を" in toks

    def test_user_dictionary_overrides(self):
        from deeplearning4j_tpu.nlp.lang import ChineseTokenizerFactory

        base = ChineseTokenizerFactory().create("深度学习框架").tokens()
        custom = ChineseTokenizerFactory(
            ["深度学习", "框架"]).create("深度学习框架").tokens()
        assert custom == ["深度学习", "框架"]
        assert custom != base

    def test_word2vec_through_lattice_tokenizer(self):
        import numpy as np
        from deeplearning4j_tpu.nlp import Word2Vec
        from deeplearning4j_tpu.nlp.lang import ChineseTokenizerFactory

        rng = np.random.default_rng(0)
        fruit = "苹果 水果 果汁"
        cars = "汽车 轮子 发动机"
        sents = []
        for _ in range(150):
            words = (fruit if rng.random() < 0.5 else cars).split()
            sents.append("".join(rng.choice(words, 5)))  # no spaces (zh)
        w2v = Word2Vec(layer_size=16, min_count=1, window=3, epochs=4,
                       seed=2, tokenizer_factory=ChineseTokenizerFactory(
                           ["苹果", "水果", "果汁", "汽车", "轮子", "发动机"]))
        w2v.fit(sents)
        assert w2v.similarity("苹果", "水果") > w2v.similarity("苹果", "汽车")

    def test_japanese_mixed_script_dictionary_word(self):
        """Kanji+okurigana words (most verbs) cross script boundaries —
        the lattice must see the whole CJK span to match them."""
        from deeplearning4j_tpu.nlp.lang import JapaneseTokenizerFactory

        ja = JapaneseTokenizerFactory(user_dictionary=["食べる"])
        assert "食べる" in ja.create("パンを食べる").tokens()


class TestKoreanMorphology:
    """Reference: deeplearning4j-nlp-korean KoreanTokenizer.java:34 —
    twitter-korean-text morphology: stem/josa/eomi decomposition, POS
    tags, de-conjugated dictionary forms."""

    def test_noun_josa_decomposition(self):
        from deeplearning4j_tpu.nlp.lang import KoreanMorphologicalAnalyzer

        ms = KoreanMorphologicalAnalyzer().analyze("나는 학교에 갔다")
        got = [(m.surface, m.pos) for m in ms]
        assert got == [("나", "Pronoun"), ("는", "Josa"),
                       ("학교", "Noun"), ("에", "Josa"),
                       ("가", "Verb"), ("았다", "Eomi")]
        # the conjugated 갔다 recovered its dictionary form
        assert ms[4].base == "가다"

    def test_past_tense_contraction_reversal(self):
        """갔/났/했/왔/됐 syllables expand arithmetically via jamo math
        (ㅏ+았, ㅐ→하+았 irregular, ㅚ+었)."""
        from deeplearning4j_tpu.nlp.lang import KoreanMorphologicalAnalyzer

        an = KoreanMorphologicalAnalyzer()
        for text, stem, base in (
                ("만났어요", "만나", "만나다"),
                ("공부했습니다", "공부하", "공부하다"),
                ("왔다", "오", "오다"),
                ("됐어요", "되", "되다"),
                ("봤다", "보", "보다")):
            ms = an.analyze(text)
            assert ms[0].surface == stem and ms[0].base == base, (text, ms)
            assert ms[1].pos == "Eomi", (text, ms)

    def test_adjective_number_foreign_punct(self):
        from deeplearning4j_tpu.nlp.lang import KoreanMorphologicalAnalyzer

        ms = KoreanMorphologicalAnalyzer().analyze("날씨가 좋다! 3 TPU")
        got = {(m.surface, m.pos) for m in ms}
        assert ("좋", "Adjective") in got
        assert ("다", "Eomi") in got
        assert ("!", "Punctuation") in got
        assert ("3", "Number") in got
        assert ("TPU", "Foreign") in got

    def test_morphological_factory_tokens(self):
        from deeplearning4j_tpu.nlp.lang import (
            KoreanMorphologicalTokenizerFactory,
        )

        toks = KoreanMorphologicalTokenizerFactory().create(
            "친구를 만났어요").tokens()
        assert toks == ["친구", "만나"]   # particles/endings dropped
        toks = KoreanMorphologicalTokenizerFactory(
            keep_particles=True).create("친구를 만났어요").tokens()
        assert toks == ["친구", "를", "만나", "았어요"]

    def test_user_nouns_extend_dictionary(self):
        from deeplearning4j_tpu.nlp.lang import KoreanMorphologicalAnalyzer

        an = KoreanMorphologicalAnalyzer(user_nouns=["텐서플로"])
        ms = an.analyze("텐서플로를")
        assert [(m.surface, m.pos) for m in ms] == [
            ("텐서플로", "Noun"), ("를", "Josa")]


class TestChinesePOS:
    """Reference: deeplearning4j-nlp-chinese ChineseTokenizer.java (ansj
    analyzer) — terms carry nature tags; same tag alphabet here."""

    def test_nature_tags(self):
        from deeplearning4j_tpu.nlp.lang import ChineseMorphologicalAnalyzer

        terms = ChineseMorphologicalAnalyzer().analyze("我们在北京学习和工作")
        got = [(t.surface, t.nature) for t in terms]
        assert got == [("我们", "r"), ("在", "p"), ("北京", "n"),
                       ("学习", "v"), ("和", "c"), ("工作", "v")]

    def test_particles_numbers_latin(self):
        from deeplearning4j_tpu.nlp.lang import ChineseMorphologicalAnalyzer

        an = ChineseMorphologicalAnalyzer()
        tags = {t.surface: t.nature for t in an.analyze("我的3个GPU")}
        assert tags["的"] == "u"
        assert tags["3"] == "m"
        assert tags["个"] == "q"
        assert tags["GPU"] == "en"

    def test_user_pos_overrides(self):
        from deeplearning4j_tpu.nlp.lang import ChineseMorphologicalAnalyzer

        an = ChineseMorphologicalAnalyzer(dictionary=["深度学习"],
                                          user_pos={"深度学习": "nz"})
        terms = an.analyze("我喜欢深度学习")
        assert ("深度学习", "nz") in [(t.surface, t.nature) for t in terms]
