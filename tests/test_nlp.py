"""NLP tests: vocab/Huffman, Word2Vec convergence + similarity, doc vectors,
GloVe, serialization round-trips, vectorizers.

Mirrors the reference nlp test strategy (SURVEY §4: 'Word2Vec /
ParagraphVectors convergence + similarity assertions on bundled corpora')
with a synthetic two-topic corpus instead of bundled raw text.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BagOfWordsVectorizer, CollectionSentenceIterator, CommonPreprocessor,
    DefaultTokenizerFactory, Glove, HuffmanTree, ParagraphVectors,
    TfidfVectorizer, Word2Vec, build_vocab, read_binary, read_word_vectors,
    write_binary, write_word_vectors,
)


def _two_topic_corpus(n=400, seed=0):
    """Sentences drawn from two disjoint topic vocabularies — embeddings
    must place same-topic words closer than cross-topic words."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "cow", "sheep", "goat"]
    tech = ["cpu", "gpu", "tpu", "ram", "disk", "cache"]
    out = []
    for _ in range(n):
        topic = animals if rng.random() < 0.5 else tech
        out.append([topic[i] for i in rng.integers(0, len(topic), 8)])
    return out


class TestVocab:
    def test_build_and_prune(self):
        v = build_vocab([["a", "a", "b"], ["a", "c"]], min_count=2)
        assert "a" in v and "b" not in v
        assert v.words[0].word == "a" and v.words[0].count == 3

    def test_huffman_codes_prefix_free(self):
        v = build_vocab(_two_topic_corpus(50), min_count=1)
        HuffmanTree(v)
        codes = ["".join(map(str, w.code)) for w in v.words]
        assert len(set(codes)) == len(codes)
        for a in codes:
            for b in codes:
                if a != b:
                    assert not b.startswith(a) or len(b) == len(a)

    def test_frequent_words_get_short_codes(self):
        v = build_vocab([["x"] * 100, ["y"] * 5, ["z"] * 5, ["w"] * 2],
                        min_count=1)
        HuffmanTree(v)
        assert len(v.words[0].code) <= len(v.words[-1].code)


class TestWord2Vec:
    @pytest.mark.parametrize("hs", [False, True])
    def test_topic_similarity(self, hs):
        w2v = Word2Vec(layer_size=24, window=3, min_count=1, negative=4,
                       hierarchic_softmax=hs, epochs=6, batch_size=1024,
                       subsampling=0, seed=1)
        w2v.fit(_two_topic_corpus())
        same = w2v.similarity("cat", "dog")
        cross = w2v.similarity("cat", "gpu")
        assert same > cross, (same, cross)
        near = w2v.words_nearest("cpu", 3)
        assert set(near) <= {"gpu", "tpu", "ram", "disk", "cache"}, near

    def test_sentence_iterator_and_tokenizer_path(self):
        sents = [" ".join(s) for s in _two_topic_corpus(100)]
        it = CollectionSentenceIterator(sents)
        tf = DefaultTokenizerFactory().set_token_pre_processor(
            CommonPreprocessor())
        w2v = Word2Vec(layer_size=16, min_count=1, epochs=2, seed=0,
                       subsampling=0, tokenizer_factory=tf)
        w2v.fit(it)
        assert w2v.word_vector("cat") is not None

    def test_serialization_round_trips(self, tmp_path):
        w2v = Word2Vec(layer_size=8, min_count=1, epochs=1, subsampling=0)
        w2v.fit(_two_topic_corpus(50))
        ptxt = tmp_path / "vecs.txt"
        write_word_vectors(w2v, str(ptxt))
        vocab, mat = read_word_vectors(str(ptxt))
        assert len(vocab) == len(w2v.vocab)
        i = vocab.index_of("cat")
        np.testing.assert_allclose(mat[i], w2v.word_vector("cat"), atol=1e-5)

        pbin = tmp_path / "vecs.bin"
        write_binary(w2v, str(pbin))
        vocab2, mat2 = read_binary(str(pbin))
        i2 = vocab2.index_of("cat")
        np.testing.assert_allclose(mat2[i2], w2v.word_vector("cat"),
                                   rtol=1e-6)


class TestParagraphVectors:
    def test_doc_similarity_by_topic(self):
        corpus = _two_topic_corpus(200)
        labels = [f"DOC_{i}" for i in range(len(corpus))]
        pv = ParagraphVectors(layer_size=24, window=3, min_count=1,
                              negative=4, epochs=8, seed=3, subsampling=0,
                              dm=False)
        pv.fit(corpus, labels)
        # find two same-topic and two cross-topic docs
        a_docs = [i for i, s in enumerate(corpus) if s[0] in
                  {"cat", "dog", "horse", "cow", "sheep", "goat"}]
        t_docs = [i for i in range(len(corpus)) if i not in a_docs]
        same = pv.similarity_to_label(f"DOC_{a_docs[0]}", f"DOC_{a_docs[1]}")
        cross = pv.similarity_to_label(f"DOC_{a_docs[0]}", f"DOC_{t_docs[0]}")
        assert same > cross, (same, cross)

    def test_infer_vector(self):
        corpus = _two_topic_corpus(100)
        pv = ParagraphVectors(layer_size=16, min_count=1, epochs=4,
                              subsampling=0, seed=0)
        pv.fit(corpus)
        v = pv.infer_vector(["cat", "dog", "cow"])
        assert v.shape == (16,) and np.isfinite(v).all()


class TestGlove:
    def test_glove_topic_similarity(self):
        g = Glove(layer_size=16, window=4, min_count=1, epochs=30,
                  batch_size=4096, seed=0)
        g.fit(_two_topic_corpus(300))
        assert g.similarity("cat", "dog") > g.similarity("cat", "gpu")


class TestVectorizers:
    def test_bow_counts(self):
        bow = BagOfWordsVectorizer()
        m = bow.fit_transform([["a", "b", "a"], ["b", "c"]])
        assert m.shape == (2, 3)
        ia = bow.vocab.index_of("a")
        assert m[0, ia] == 2

    def test_tfidf_downweights_common(self):
        tv = TfidfVectorizer()
        m = tv.fit_transform([["a", "b"], ["a", "c"], ["a", "d"]])
        ia, ib = tv.vocab.index_of("a"), tv.vocab.index_of("b")
        assert m[0, ia] < m[0, ib]


class TestStopWords:
    """Reference: text/stopwords/StopWords.java + stopwords.txt filtering
    in the Word2Vec vocab pipeline."""

    def test_get_stop_words(self):
        from deeplearning4j_tpu.nlp import StopWords
        sw = StopWords.get_stop_words()
        assert "the" in sw and "and" in sw and len(sw) > 100
        assert "zebra" not in sw
        assert "custom" in StopWords.get_stop_words(extra=["custom"])

    def test_preprocessor_filters_through_tokenizer(self):
        from deeplearning4j_tpu.nlp import StopWordsRemovalPreprocessor
        from deeplearning4j_tpu.nlp.tokenization import (
            CommonPreprocessor, DefaultTokenizerFactory,
        )
        f = DefaultTokenizerFactory()
        f.set_token_pre_processor(StopWordsRemovalPreprocessor(
            inner=CommonPreprocessor()))
        toks = f.create("The quick fox and the lazy dog!").tokens()
        assert toks == ["quick", "fox", "lazy", "dog"]

    def test_vocab_excludes_stopwords(self):
        from deeplearning4j_tpu.nlp import (
            StopWordsRemovalPreprocessor, Word2Vec,
        )
        from deeplearning4j_tpu.nlp.tokenization import (
            DefaultTokenizerFactory,
        )
        f = DefaultTokenizerFactory()
        f.set_token_pre_processor(StopWordsRemovalPreprocessor())
        w2v = Word2Vec(tokenizer_factory=f, layer_size=8, min_count=1,
                       epochs=1, seed=0)
        w2v.fit(["the dog and the cat ran", "a dog or a cat sat"] * 5)
        words = {vw.word for vw in w2v.vocab.words}
        assert "dog" in words and "cat" in words
        assert "the" not in words and "and" not in words

    def test_contractions_filtered_through_inner_preprocessor(self):
        from deeplearning4j_tpu.nlp import StopWordsRemovalPreprocessor
        from deeplearning4j_tpu.nlp.tokenization import (
            CommonPreprocessor, DefaultTokenizerFactory,
        )
        f = DefaultTokenizerFactory()
        f.set_token_pre_processor(StopWordsRemovalPreprocessor(
            inner=CommonPreprocessor()))
        toks = f.create("I don't know, he's gone and they're tall").tokens()
        assert toks == ["know", "gone", "tall"]
