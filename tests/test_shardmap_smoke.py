"""Keeps tools/shardmap_smoke.py runnable: the harness must stay green
on the CPU mesh (interpret mode) so a TPU tunnel window is never wasted
on a harness bug. The tool's real purpose is the non-interpret run on
the chip — interpret mode cannot catch Mosaic lowering errors
(VERDICT r4 #4) — so this test is necessary, not sufficient.
"""
import os
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                     "tools")


@pytest.fixture(scope="module")
def smoke():
    os.environ["SMOKE_INTERPRET"] = "1"
    sys.path.insert(0, TOOLS)
    try:
        import shardmap_smoke
        yield shardmap_smoke
    finally:
        sys.path.remove(TOOLS)   # the module itself inserts repo root at 0
        os.environ.pop("SMOKE_INTERPRET", None)


def _check_names():
    # enumerate without importing jax-heavy module at collection: the
    # names mirror CHECKS; the count assertion below keeps them in sync
    return ["flash_fwd_shardmap", "flash_bwd_shardmap",
            "fused_lstm_shardmap", "conv_fused_shardmap", "ring_flash",
            "kv_decode", "kv_decode_gqa_rolling"]


def test_name_list_matches_tool(smoke):
    assert [c.__name__.replace("check_", "") for c in smoke.CHECKS] == \
        _check_names(), "update _check_names() when CHECKS changes"


@pytest.mark.parametrize("name", _check_names())
def test_check_passes_on_cpu_mesh(smoke, name, devices8):
    check = next(c for c in smoke.CHECKS
                 if c.__name__ == f"check_{name}")
    r = check()
    assert r["max_err"] <= r["tol"], (name, r)
