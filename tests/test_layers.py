"""Layer-zoo tests: conv/pool/BN/LSTM/masking gradient checks + behavior.

Mirrors the reference gradient-check suites (CNNGradientCheckTest,
LSTMGradientCheckTests, BNGradientCheckTest, GradientCheckTestsMasking,
VaeGradientCheckTests — SURVEY §4).
"""

import numpy as np
import pytest

from deeplearning4j_tpu import InputType
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, AutoEncoder, BatchNormalization, Bidirectional,
    ConvolutionLayer, DenseLayer, DropoutLayer, EmbeddingLayer,
    GlobalPoolingLayer, GravesLSTM, LSTM, LastTimeStep,
    LocalResponseNormalization, OutputLayer, RBM, RnnOutputLayer,
    SimpleRnn, SubsamplingLayer, VariationalAutoencoder, ZeroPaddingLayer,
)
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.optim.updaters import Adam, Sgd
from deeplearning4j_tpu.gradientcheck import check_gradients


def _onehot(idx, n):
    return np.eye(n, dtype=np.float32)[idx]


class TestCnn:
    def _conf(self, **kw):
        return (NeuralNetConfiguration.builder()
                .seed(9).updater(Sgd(0.1)).activation("tanh")
                .list(
                    ConvolutionLayer(n_out=3, kernel=(3, 3), stride=(1, 1)),
                    SubsamplingLayer(pooling=kw.get("pooling", "max"),
                                     kernel=(2, 2), stride=(2, 2)),
                    OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 2))
                .build())

    def test_shape_inference(self):
        conf = self._conf()
        assert conf.layers[0].n_in == 2
        # conv 8x8 k3 s1 truncate -> 6x6x3; pool 2x2 -> 3x3x3 -> flat 27
        assert conf.layers[2].n_in == 27

    @pytest.mark.parametrize("pooling", ["max", "avg"])
    def test_gradient_check(self, pooling):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 8, 8, 2))
        y = _onehot(rng.integers(0, 2, 4), 2)
        net = MultiLayerNetwork(self._conf(pooling=pooling)).init()
        assert check_gradients(net, x, y, subset=60)

    def test_cnn_flat_input_with_preprocessor(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Adam(1e-2)).activation("relu")
                .list(ConvolutionLayer(n_out=4, kernel=(3, 3)),
                      SubsamplingLayer(kernel=(2, 2), stride=(2, 2)),
                      OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.convolutional_flat(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 64)).astype(np.float32)
        y = _onehot(rng.integers(0, 3, 16), 3)
        net.fit(x, y, epochs=2, batch_size=8)
        assert np.asarray(net.output(x)).shape == (16, 3)

    def test_zero_padding_and_same_mode(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Sgd(0.1))
                .list(ZeroPaddingLayer(pad=(1, 1)),
                      ConvolutionLayer(n_out=2, kernel=(3, 3),
                                       convolution_mode="same",
                                       activation="relu"),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.convolutional(6, 6, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).standard_normal((2, 6, 6, 1))
        out = np.asarray(net.output(x.astype(np.float32)))
        assert out.shape == (2, 2)


class TestBatchNorm:
    def test_running_stats_update_and_freeze_at_eval(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(2).updater(Sgd(0.1)).activation("identity")
                .list(DenseLayer(n_out=6),
                      BatchNormalization(),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = 3 + 2 * rng.standard_normal((64, 4)).astype(np.float32)
        y = _onehot(rng.integers(0, 2, 64), 2)
        bn_name = conf.layers[1].name
        mean0 = np.asarray(net.state_tree[bn_name]["mean"]).copy()
        net.fit(x, y, epochs=3, batch_size=32)
        mean1 = np.asarray(net.state_tree[bn_name]["mean"])
        assert not np.allclose(mean0, mean1), "running mean should move in train"
        out1 = np.asarray(net.output(x))
        out2 = np.asarray(net.output(x))
        np.testing.assert_allclose(out1, out2)  # eval is deterministic

    def test_gradient_check_eval_stats(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(2).updater(Sgd(0.1)).activation("tanh")
                .list(DenseLayer(n_out=5), BatchNormalization(),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((6, 3))
        y = _onehot(rng.integers(0, 2, 6), 2)
        assert check_gradients(net, x, y)


class TestRnn:
    def _lstm_conf(self, cls=LSTM, loss_layer=None, T=None):
        loss_layer = loss_layer or RnnOutputLayer(
            n_out=3, activation="softmax", loss="mcxent")
        return (NeuralNetConfiguration.builder()
                .seed(4).updater(Sgd(0.1)).activation("tanh")
                .list(cls(n_out=5), loss_layer)
                .set_input_type(InputType.recurrent(4, T))
                .build())

    @pytest.mark.parametrize("cls", [LSTM, GravesLSTM, SimpleRnn])
    def test_gradient_check_rnn(self, cls):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((3, 6, 4))
        y = _onehot(rng.integers(0, 3, (3, 6)), 3)
        net = MultiLayerNetwork(self._lstm_conf(cls)).init()
        assert check_gradients(net, x, y, subset=80)

    def test_gradient_check_masked(self):
        """Reference: GradientCheckTestsMasking."""
        rng = np.random.default_rng(0)
        x = rng.standard_normal((3, 6, 4))
        y = _onehot(rng.integers(0, 3, (3, 6)), 3)
        mask = np.ones((3, 6))
        mask[0, 4:] = 0
        mask[2, 2:] = 0
        net = MultiLayerNetwork(self._lstm_conf(LSTM)).init()
        assert check_gradients(net, x, y, features_mask=mask,
                               labels_mask=mask, subset=80)

    def test_masked_timesteps_do_not_affect_carry(self):
        net = MultiLayerNetwork(self._lstm_conf(LSTM)).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 6, 4)).astype(np.float32)
        mask = np.ones((2, 6), np.float32)
        mask[:, 3:] = 0
        x2 = x.copy()
        x2[:, 3:] = 999.0  # junk in masked region
        import jax.numpy as jnp
        l = net.conf.layers[0]
        p = net.params_tree[l.name]
        y1, c1 = l.apply(p, jnp.asarray(x), mask=jnp.asarray(mask))
        y2, c2 = l.apply(p, jnp.asarray(x2), mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(c1["h"]), np.asarray(c2["h"]),
                                   rtol=1e-5)

    def test_rnn_time_step_matches_full_forward(self):
        """Reference: rnnTimeStep consistency tests."""
        conf = self._lstm_conf(LSTM)
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 5, 4)).astype(np.float32)
        full = np.asarray(net.output(x))
        net.rnn_clear_previous_state()
        steps = [np.asarray(net.rnn_time_step(x[:, t])) for t in range(5)]
        stepped = np.concatenate(steps, axis=1)
        np.testing.assert_allclose(full, stepped, rtol=1e-4, atol=1e-5)

    def test_bidirectional_and_last_timestep(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(4).updater(Adam(1e-2)).activation("tanh")
                .list(Bidirectional(layer=LSTM(n_out=4)),
                      LastTimeStep(layer=LSTM(n_out=6)),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.recurrent(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 7, 3)).astype(np.float32)
        y = _onehot(rng.integers(0, 2, 8), 2)
        net.fit(x, y, epochs=3, batch_size=8)
        assert np.asarray(net.output(x)).shape == (8, 2)

    def test_tbptt_fit(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(4).updater(Adam(1e-2)).activation("tanh")
                .list(LSTM(n_out=5),
                      RnnOutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.recurrent(3))
                .tbptt(4)
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 12, 3)).astype(np.float32)
        y = _onehot(rng.integers(0, 2, (4, 12)), 2)
        net.fit(x, y, epochs=3, batch_size=4)
        assert net.score_ is not None and np.isfinite(net.score_)

    def test_tbptt_back_shorter_than_fwd(self):
        """tbptt(6, 3): chunk prefix advances carries gradient-free, train
        step covers the last 3 steps (reference fwd != back truncation,
        `MultiLayerNetwork.java:1102-1104`)."""
        conf = (NeuralNetConfiguration.builder()
                .seed(4).updater(Adam(1e-2)).activation("tanh")
                .list(LSTM(n_out=5),
                      RnnOutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.recurrent(3))
                .tbptt(6, 3)
                .build())
        assert conf.tbptt_back_length == 3
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 12, 3)).astype(np.float32)
        y = _onehot(rng.integers(0, 2, (4, 12)), 2)
        net.fit(x, y, epochs=3, batch_size=4)
        assert net.score_ is not None and np.isfinite(net.score_)

    def test_tbptt_rejects_2d_labels(self):
        conf = (NeuralNetConfiguration.builder()
                .list(LSTM(n_out=4), LastTimeStep(layer=LSTM(n_out=4)),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.recurrent(3)).tbptt(4).build())
        net = MultiLayerNetwork(conf).init()
        x = np.zeros((2, 8, 3), np.float32)
        y = _onehot([0, 1], 2)
        with pytest.raises(ValueError, match="per-timestep"):
            net.fit(x, y, epochs=1)


class TestMiscLayers:
    def test_embedding_layer(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(0).updater(Adam(5e-2))
                .list(EmbeddingLayer(n_in=10, n_out=6, activation="identity"),
                      OutputLayer(n_out=10, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        idx = np.arange(10)
        y = _onehot(idx, 10)  # identity mapping task
        for _ in range(60):
            net.fit(idx[:, None], y, epochs=1, batch_size=10)
        assert (net.predict(idx[:, None]) == idx).mean() > 0.8

    def test_dropout_train_vs_eval(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(0).updater(Sgd(0.1)).dropout(0.5)
                .list(DenseLayer(n_out=32, activation="identity"),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.ones((4, 8), np.float32)
        o1 = np.asarray(net.output(x))
        o2 = np.asarray(net.output(x))
        np.testing.assert_allclose(o1, o2)  # no dropout at inference

    def test_global_pooling_cnn(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(0).updater(Sgd(0.1))
                .list(ConvolutionLayer(n_out=5, kernel=(3, 3),
                                       activation="relu"),
                      GlobalPoolingLayer(pooling="avg"),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.convolutional(6, 6, 1))
                .build())
        assert conf.layers[2].n_in == 5
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).standard_normal((3, 6, 6, 1)).astype(np.float32)
        assert np.asarray(net.output(x)).shape == (3, 2)

    def test_lrn_preserves_shape(self):
        import jax.numpy as jnp
        lrn = LocalResponseNormalization()
        x = jnp.ones((2, 4, 4, 7))
        y, _ = lrn.apply({}, x)
        assert y.shape == x.shape


class TestPretraining:
    def test_autoencoder_pretrain_reduces_reconstruction(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(0).updater(Adam(1e-2)).activation("sigmoid")
                .list(AutoEncoder(n_out=8, corruption_level=0.0),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(16))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.random((64, 16)).astype(np.float32)
        import jax
        ae = conf.layers[0]
        r0 = float(ae.reconstruction_score(
            net.params_tree[ae.name], x, rng=jax.random.PRNGKey(0)))
        net.pretrain(x, epochs=30, batch_size=32)
        r1 = float(ae.reconstruction_score(
            net.params_tree[ae.name], x, rng=jax.random.PRNGKey(0)))
        assert r1 < r0

    def test_vae_pretrain_improves_elbo(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(0).updater(Adam(1e-2)).activation("tanh")
                .list(VariationalAutoencoder(
                          n_out=4, encoder_sizes=(16,), decoder_sizes=(16,),
                          reconstruction_distribution="bernoulli"),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(12))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = (rng.random((64, 12)) > 0.5).astype(np.float32)
        import jax
        vae = conf.layers[0]
        e0 = float(vae.reconstruction_score(
            net.params_tree[vae.name], x, rng=jax.random.PRNGKey(0)))
        net.pretrain(x, epochs=20, batch_size=32)
        e1 = float(vae.reconstruction_score(
            net.params_tree[vae.name], x, rng=jax.random.PRNGKey(0)))
        assert e1 < e0

    def test_rbm_pretrain_runs(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(0).updater(Sgd(0.05))
                .list(RBM(n_out=6),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(10))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = (np.random.default_rng(0).random((32, 10)) > 0.5).astype(np.float32)
        w0 = np.asarray(net.params_tree[conf.layers[0].name]["W"]).copy()
        net.pretrain(x, epochs=5, batch_size=16)
        w1 = np.asarray(net.params_tree[conf.layers[0].name]["W"])
        assert not np.allclose(w0, w1)


class TestFreezing:
    def test_frozen_layer_params_do_not_change(self):
        from deeplearning4j_tpu.nn.layers import FrozenLayer
        conf = (NeuralNetConfiguration.builder()
                .seed(0).updater(Sgd(0.5)).activation("tanh")
                .list(FrozenLayer(layer=DenseLayer(n_out=6)),
                      OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((32, 4)).astype(np.float32)
        y = _onehot(rng.integers(0, 2, 32), 2)
        frozen_name = conf.layers[0].name
        out_name = conf.layers[1].name
        w0 = np.asarray(net.params_tree[frozen_name]["W"]).copy()
        o0 = np.asarray(net.params_tree[out_name]["W"]).copy()
        net.fit(x, y, epochs=5, batch_size=16)
        np.testing.assert_allclose(
            np.asarray(net.params_tree[frozen_name]["W"]), w0)
        assert not np.allclose(np.asarray(net.params_tree[out_name]["W"]), o0)
