"""Character-level text generation with a transformer KV cache.

The reference's text-generation flow trains `TextGenerationLSTM`
(`zoo/model/TextGenerationLSTM.java`) and samples one character at a
time through `MultiLayerNetwork.rnnTimeStep` (the GravesLSTM
char-modelling example pattern). This is the same flow on the
transformer zoo model: train a tiny causal LM on a repeating corpus,
then generate continuations token-by-token through the attention KV
cache (`decode_carry` stepping) — the prompt is consumed once and each
new character costs one cached step, not a full-prefix re-run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402 — repo-root path + CPU re-pin

import numpy as np

from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.utils.textgen import generate
from deeplearning4j_tpu.zoo.transformer import TextGenerationTransformer

CORPUS = "the quick brown fox jumps over the lazy dog. " * 40


def main(epochs: int = 30, T: int = 64, n_gen: int = 40):
    chars = sorted(set(CORPUS))
    vocab = len(chars)
    idx = {c: i for i, c in enumerate(chars)}
    ids = np.array([idx[c] for c in CORPUS], np.int64)

    # sliding windows of T+1 chars -> (input, next-char one-hot) pairs
    n = min(256, len(ids) - T - 1)
    starts = np.arange(n)
    x = np.stack([ids[s:s + T] for s in starts])[..., None].astype(np.float32)
    y = np.eye(vocab, dtype=np.float32)[
        np.stack([ids[s + 1:s + T + 1] for s in starts])]

    net = TextGenerationTransformer(
        num_classes=vocab, input_shape=(T, 1), d_model=64, num_heads=4,
        num_blocks=2).init()
    for epoch in range(epochs):
        net.fit(ArrayDataSetIterator(x, y, batch_size=32))
    from deeplearning4j_tpu.data.dataset import DataSet
    loss = float(net.score(DataSet(x[:32], y[:32])))
    print(f"final loss {loss:.3f}")

    # learned absolute positions bound the total decode length
    prompt = "the quick "
    assert len(prompt) + n_gen <= T, "prompt + generation must fit T"
    prompt_ids = np.array([[idx[c] for c in prompt]])
    out = generate(net, prompt_ids, n_gen, greedy=True)
    text = "".join(chars[i] for i in out[0])
    print(f"prompt: {prompt!r}")
    print(f"generated: {text!r}")
    return loss, text


if __name__ == "__main__":
    main()
