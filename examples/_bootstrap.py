"""Shared example bootstrap: repo-root import path + CPU re-pin.

Each example does `import _bootstrap  # noqa: F401` as its first import.
The image's sitecustomize pins jax_platforms to "axon,cpu" at interpreter
start; an explicit JAX_PLATFORMS=cpu request is honored with the same
re-pin as tests/conftest.py and __graft_entry__.py.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
    import jax

    jax.config.update("jax_platforms", "cpu")
