"""Transformer LM trained with 1F1B pipeline parallelism over the `pipe`
mesh axis — PipelinedNetwork partitions the configured model into
prologue (embeddings) / uniform block trunk / output epilogue
automatically (no reference counterpart: SURVEY §7 step 7 extension).

Run:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/transformer_pipeline_1f1b.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402 — repo-root path + CPU re-pin

import jax
import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel import PipelinedNetwork
from deeplearning4j_tpu.parallel.mesh import AXIS_PIPE
from deeplearning4j_tpu.zoo.transformer import TextGenerationTransformer


def main(stages: int = 4, steps: int = 8):
    devs = jax.devices()[:stages]
    mesh = Mesh(np.array(devs), (AXIS_PIPE,))
    net = TextGenerationTransformer(
        num_classes=64, input_shape=(16, 1), d_model=32, num_heads=4,
        num_blocks=stages).init()
    pp = PipelinedNetwork(net, mesh, n_micro=4)
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 64, (32, 16, 1)).astype(np.float32)
    labels = np.eye(64, dtype=np.float32)[
        np.roll(ids[..., 0], -1, axis=1).astype(int)]
    for i in range(steps):
        loss = pp.fit_batch(ids, labels, it=i)
        print(f"step {i}: loss {loss:.4f}")
    pp.sync_to_net()
    print("params synced back; net.output works:",
          np.asarray(net.output(ids[:2])).shape)


if __name__ == "__main__":
    main()
