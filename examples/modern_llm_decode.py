"""Llama-architecture-shaped char LM + the full decode-control suite.

The reference's generation story is temperature sampling through
`rnnTimeStep` (`zoo/model/TextGenerationLSTM.java`); this example shows
the modern end of the same flow on this framework: a transformer whose
block shape matches the Llama architecture — RoPE positions, grouped-
query attention (2 KV heads under 4 query heads — the KV cache, and so
decode's per-token HBM traffic, is halved), RMSNorm, SwiGLU FFN — then
greedy, nucleus (top-p), and beam-search decoding, all through the same
KV-cache stepping (beam reselection gathers cache rows; no prefix is
ever recomputed).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402 — repo-root path + CPU re-pin

import numpy as np

from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.utils.textgen import beam_search, generate
from deeplearning4j_tpu.zoo.transformer import TextGenerationTransformer

CORPUS = "a wise owl lived in an oak. the more he saw the less he spoke. " * 32


def main(epochs: int = 25, T: int = 48, n_gen: int = 32):
    chars = sorted(set(CORPUS))
    vocab = len(chars)
    idx = {c: i for i, c in enumerate(chars)}
    ids = np.array([idx[c] for c in CORPUS], np.int64)

    n = min(192, len(ids) - T - 1)
    starts = np.arange(n)
    x = np.stack([ids[s:s + T] for s in starts])[..., None].astype(np.float32)
    y = np.eye(vocab, dtype=np.float32)[
        np.stack([ids[s + 1:s + T + 1] for s in starts])]

    net = TextGenerationTransformer(
        num_classes=vocab, input_shape=(T, 1), d_model=64, num_heads=4,
        num_kv_heads=2, num_blocks=2, pos_encoding="rope",
        norm="rms", ffn_activation="swiglu",
        max_decode=T + n_gen).init()
    for _ in range(epochs):
        net.fit(ArrayDataSetIterator(x, y, batch_size=32))
    from deeplearning4j_tpu.data.dataset import DataSet
    loss = float(net.score(DataSet(x[:32], y[:32])))
    print(f"final loss {loss:.3f}")

    prompt_txt = "the more he "
    prompt = np.array([[idx[c] for c in prompt_txt]])

    def detok(row):
        return "".join(chars[t] for t in row)

    outs = {}
    outs["greedy"] = detok(generate(net, prompt, n_gen, greedy=True)[0])
    outs["nucleus"] = detok(generate(
        net, prompt, n_gen, temperature=0.9, top_p=0.9,
        rng=np.random.default_rng(0))[0])
    outs["beam"] = detok(beam_search(
        net, prompt, n_gen, beam_width=4, length_penalty=0.0)[0])
    for k, v in outs.items():
        print(f"{k:>8}: {prompt_txt!r} -> {v!r}")
    return loss, outs


if __name__ == "__main__":
    main()
