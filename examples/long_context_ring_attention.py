"""Long-context training levers, demonstrated together:

1. sequence-parallel RING ATTENTION — the context length is sharded over
   a mesh axis and K/V blocks rotate via ppermute, so no device ever
   materializes the full T x T score matrix (charter: long-context is
   first-class; run on the 8-device virtual CPU mesh or a real slice);
2. GRADIENT CHECKPOINTING — per-layer rematerialization drops stored
   activations from O(depth) to O(1) layers for ~33% extra backward
   FLOPs (builder().gradient_checkpointing()).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python examples/long_context_ring_attention.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from deeplearning4j_tpu import InputType  # noqa: E402
from deeplearning4j_tpu.models import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.layers import (  # noqa: E402
    LSTM, RnnOutputLayer,
)
from deeplearning4j_tpu.optim.updaters import Adam  # noqa: E402
from deeplearning4j_tpu.parallel import make_mesh  # noqa: E402
from deeplearning4j_tpu.parallel.ring_attention import (  # noqa: E402
    attention, ring_self_attention,
)


def ring_attention_demo(T=4096, block_check=256):
    """Attention over a 4k context, sequence-sharded over every device."""
    mesh = make_mesh({"seq": -1})
    n_dev = mesh.shape["seq"]
    B, H, D = 1, 4, 32
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
               for _ in range(3))
    # Pin matmul precision for the parity check: TPU's default rounds
    # f32 matmul inputs to bf16, which would force a ~1000x looser
    # tolerance and hide real ~1% ring-path bugs. At float32 precision
    # the tight bound holds on every platform.
    with jax.default_matmul_precision("float32"):
        out = ring_self_attention(q, k, v, mesh, axis="seq", causal=True)
        # spot-check a block against the dense oracle (dense on 4k is
        # fine on host; on a real long context it would not be)
        ref = attention(q[:, :block_check], k[:, :block_check],
                        v[:, :block_check], causal=True)
    np.testing.assert_allclose(np.asarray(out[:, :block_check]),
                               np.asarray(ref), rtol=2e-4, atol=2e-5)
    print(f"ring attention: T={T} sharded over {n_dev} devices, "
          f"per-device score block {T // n_dev}x{T} "
          f"(dense would be {T}x{T}); first {block_check} steps match "
          "the dense oracle")


def remat_training_demo(T=512):
    """Deep recurrent stack over a long sequence with per-layer remat."""
    def build(ckpt):
        b = (NeuralNetConfiguration.builder().seed(0)
             .updater(Adam(1e-3)).activation("tanh"))
        if ckpt:
            b = b.gradient_checkpointing()
        return MultiLayerNetwork(
            b.list(LSTM(n_out=48), LSTM(n_out=48), LSTM(n_out=48),
                   RnnOutputLayer(n_out=4, activation="softmax"))
            .set_input_type(InputType.recurrent(16)).build()).init()

    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, T, 16)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (2, T))]
    plain, remat = build(False), build(True)
    plain.fit(x, y, epochs=1, batch_size=2)
    remat.fit(x, y, epochs=1, batch_size=2)
    diff = float(np.abs(plain.params() - remat.params()).max())
    print(f"gradient checkpointing: 3-layer LSTM over T={T}, "
          f"remat-vs-plain max param diff {diff:.2e} (identical math, "
          "O(1)-layer activation memory)")


def main():
    print(f"devices: {jax.device_count()}")
    ring_attention_demo()
    remat_training_demo()


if __name__ == "__main__":
    main()
