"""Word2Vec over a sentence iterator with a preprocessor stack + Google-
binary export (reference: Word2Vec.Builder + WordVectorSerializer)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402 — repo-root path + CPU re-pin

import tempfile

import numpy as np

from deeplearning4j_tpu.nlp import (
    CollectionSentenceIterator, CompositePreProcessor, LowCasePreProcessor,
    StripSpecialCharsPreProcessor, Word2Vec, read_binary, write_binary,
)


def main():
    rng = np.random.default_rng(0)
    fruit = ["Apple", "Pear", "Fruit", "Juice"]
    cars = ["Car", "Truck", "Wheel", "Motor"]
    sents = [" ".join(rng.choice(fruit if rng.random() < .5 else cars, 6))
             for _ in range(400)]
    it = CollectionSentenceIterator(sents).set_pre_processor(
        CompositePreProcessor(LowCasePreProcessor(),
                              StripSpecialCharsPreProcessor()))
    w2v = Word2Vec(layer_size=32, min_count=1, window=3, epochs=5, seed=1)
    w2v.fit(it)
    print("apple ~ pear:", round(w2v.similarity("apple", "pear"), 3))
    print("apple ~ car: ", round(w2v.similarity("apple", "car"), 3))
    print("nearest(apple):", w2v.words_nearest("apple", 3))
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "vectors.bin")
        write_binary(w2v, p)   # original word2vec/gensim-compatible layout
        vocab, mat = read_binary(p)
        print(f"binary round-trip: {len(vocab)} words x {mat.shape[1]} dims")


if __name__ == "__main__":
    main()
