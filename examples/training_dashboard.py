"""Live training dashboard: attach a StatsListener + UIServer and watch
param/update norms, histograms, activation stats and a t-SNE view at
http://127.0.0.1:<port>/train/overview.html (reference: PlayUIServer +
TrainModule)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402 — repo-root path + CPU re-pin

import numpy as np

from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne
from deeplearning4j_tpu.data.datasets import load_iris
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optim.updaters import Adam
from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener, UIServer


def main(epochs: int = 30, serve_forever: bool = False):
    server = UIServer.get_instance()
    storage = InMemoryStatsStorage()
    server.attach(storage)
    print(f"dashboard: http://127.0.0.1:{server.port}/train/overview.html")
    x, y = load_iris()
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder().seed(1).updater(Adam(0.05))
        .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
              OutputLayer(n_in=16, n_out=3, activation="softmax",
                          loss="mcxent"))
        .build()).init()
    net.listeners.append(StatsListener(
        storage, 1, collect_histograms=True, collect_activations=True))
    net.fit(x, y, epochs=epochs, batch_size=50)
    emb = BarnesHutTsne(n_components=2, n_iter=150, seed=3).fit_transform(
        np.asarray(net.feed_forward(x)[0]))
    server.upload_tsne(emb, [str(int(c)) for c in np.argmax(y, -1)])
    print("t-SNE view:", f"http://127.0.0.1:{server.port}/tsne.html")
    if serve_forever:
        import threading
        threading.Event().wait()


if __name__ == "__main__":
    main(serve_forever=True)
