"""Multi-process (multi-host) distributed training — the pod launcher flow.

What the reference does with `spark-submit` + `SparkDl4jMultiLayer`
(driver broadcasts the model, executors train shards, the master
averages; `SparkDl4jMultiLayer.java:215`), a TPU pod does with one
controller PROCESS per host wired by `jax.distributed.initialize`:
every process runs THIS script, feeds its `host_local_shard` of the
data, and the collectives inside the jitted step do the rest.

Run it single-machine (the Spark `local[N]` analogue — N real OS
processes with 2 virtual CPU devices each):

    JAX_PLATFORMS=cpu python examples/multiprocess_pod.py --nproc 2

On a real pod each host would instead set JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID (or rely on TPU metadata) and run the
worker path directly.

The flow each process runs: DistributedTrainingMaster (per-step exact DP
over all hosts' devices) -> distributed_evaluate (per-shard confusion
matrices merged in one gather) -> ShardedCheckpointer (each host writes
its process-<k>/ shard directory).
"""

import _bootstrap  # noqa: F401

import os
import socket
import subprocess
import sys

import numpy as np

N, D, CLASSES, BATCH = 128, 16, 4, 32


def make_data():
    rng = np.random.default_rng(42)
    x = rng.standard_normal((N, D)).astype(np.float32)
    w = rng.standard_normal((D, CLASSES))
    y = np.eye(CLASSES, dtype=np.float32)[(x @ w).argmax(-1)]
    return x, y


def make_net():
    from deeplearning4j_tpu import InputType
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optim.updaters import Adam

    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(3).updater(Adam(1e-2)).activation("tanh")
         .list(DenseLayer(n_out=32),
               OutputLayer(n_out=CLASSES, activation="softmax"))
         .set_input_type(InputType.feed_forward(D))
         .build())).init()


def worker(ckpt_dir: str) -> float:
    """One controller process of the pod (every host runs this)."""
    import jax

    from deeplearning4j_tpu.parallel import make_mesh
    from deeplearning4j_tpu.parallel.checkpoint import ShardedCheckpointer
    from deeplearning4j_tpu.parallel.distributed import (
        initialize_distributed, process_index,
    )
    from deeplearning4j_tpu.parallel.training_master import (
        DistributedTrainingMaster, distributed_evaluate,
    )

    initialize_distributed()   # env-var wiring (coordinator, N, pid)
    x, y = make_data()
    net = make_net()
    DistributedTrainingMaster(mesh=make_mesh({"data": -1})).execute_training(
        net, x, y, batch_size=BATCH, epochs=3)
    ev = distributed_evaluate(net, x, y, batch_size=BATCH)
    if ckpt_dir:
        ShardedCheckpointer(ckpt_dir, async_save=False).save(
            net, step=net.iteration)
    if process_index() == 0:
        print(f"pod of {jax.process_count()} processes x "
              f"{len(jax.local_devices())} devices: "
              f"accuracy={ev.accuracy():.3f} score={net.score_:.4f}")
    return float(ev.accuracy())


def launch(nproc: int, devs: int, ckpt_dir: str) -> None:
    """Local launcher: spawn nproc copies of this script as pod workers
    (the `local[N]` fixture; a cluster scheduler does this across hosts)."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    procs = []
    try:
        for pid in range(nproc):
            env = dict(
                os.environ,
                JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
                JAX_NUM_PROCESSES=str(nproc),
                JAX_PROCESS_ID=str(pid),
                POD_WORKER="1", POD_CKPT=ckpt_dir,
                JAX_PLATFORMS="cpu",
                XLA_FLAGS=f"--xla_force_host_platform_device_count={devs}",
            )
            procs.append(subprocess.Popen([sys.executable, __file__],
                                          env=env))
        rc = [p.wait(timeout=600) for p in procs]
    finally:
        for p in procs:   # a hung worker must not leak past the launcher
            if p.poll() is None:
                p.kill()
    if any(rc):
        raise SystemExit(f"pod worker(s) failed: rc={rc}")


def main(nproc: int = 2, devs: int = 2, ckpt_dir: str = "") -> None:
    if os.environ.get("POD_WORKER"):
        worker(os.environ.get("POD_CKPT", ""))
        return
    launch(nproc, devs, ckpt_dir)
    print(f"pod run complete ({nproc} processes x {devs} devices)")


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument("--devs", type=int, default=2)
    ap.add_argument("--ckpt", default="")
    a = ap.parse_args()
    main(a.nproc, a.devs, a.ckpt)
