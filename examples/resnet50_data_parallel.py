"""ResNet-50 data-parallel training — the north-star config #5
(ParallelWrapper/Spark-averaging equivalent: one sharded-jit step with an
ICI allreduce; `parallelism/ParallelWrapper.java:409`).

Run multi-(virtual-)device with:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/resnet50_data_parallel.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402 — repo-root path + CPU re-pin

import jax
import numpy as np

from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
from deeplearning4j_tpu.zoo import ResNet50


def main(steps: int = 4, image: int = 64, classes: int = 16):
    n = jax.device_count()
    per_device = 8
    net = ResNet50(num_classes=classes, input_shape=(image, image, 3)).init()
    pw = ParallelWrapper(net, mesh=make_mesh({"data": n}))
    rng = np.random.default_rng(0)
    b = per_device * n
    x = rng.standard_normal((b * steps, image, image, 3)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, b * steps)]
    pw.fit(x, y, epochs=1, batch_size=b)
    print(f"trained {steps} steps data-parallel over {n} device(s); "
          f"final loss {net.score_:.4f}")


if __name__ == "__main__":
    main()
