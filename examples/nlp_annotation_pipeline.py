"""Annotation pipeline: sentences -> tokens -> POS -> stems, then feed
POS-filtered, stemmed tokens into Word2Vec (the UIMA-module workflow:
UimaSentenceIterator + PosUimaTokenizerFactory + StemmingPreprocessor).
Also shows kuromoji-style Japanese morphology (POS/readings/base forms).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402

from deeplearning4j_tpu.nlp import Word2Vec  # noqa: E402
from deeplearning4j_tpu.nlp.annotation import (  # noqa: E402
    AnnotationPipeline, AnnotationSentenceIterator,
    PosFilteredTokenizerFactory, StemmingPreprocessor, TYPE_TOKEN,
)
from deeplearning4j_tpu.nlp.lang import (  # noqa: E402
    JapaneseMorphologicalAnalyzer,
)
from deeplearning4j_tpu.nlp.tokenization import (  # noqa: E402
    DefaultTokenizerFactory,
)


def main():
    text = ("Dr. Smith was running experiments quickly. "
            "The experiments produced surprising results!")
    doc = AnnotationPipeline.default().process(text)
    print("tokens / POS / stems:")
    for t in doc.select(TYPE_TOKEN)[:10]:
        print(f"  {t.covered_text(doc.text):<12} "
              f"{t.features.get('pos', '?'):<5} "
              f"{t.features.get('stem', '')}")

    nouns = PosFilteredTokenizerFactory({"NN", "NNS"}, strip_nones=True)
    print("noun stems only:", nouns.create(text).tokens())

    docs = ["Dogs chase cats. Cats chase mice.",
            "The running dogs were chasing the sleeping cats."] * 20
    factory = DefaultTokenizerFactory()
    factory.set_token_pre_processor(StemmingPreprocessor())
    w2v = Word2Vec(tokenizer_factory=factory, layer_size=16, min_count=1,
                   epochs=3, seed=0)
    w2v.fit(AnnotationSentenceIterator(docs))
    print("similarity(dog, cat) on stemmed corpus:",
          round(float(w2v.similarity("dog", "cat")), 3))

    print("\nJapanese morphology:")
    for m in JapaneseMorphologicalAnalyzer().analyze(
            "私は昨日東京で日本語を勉強しました"):
        print(f"  {m.surface:<8} {m.pos:<4} reading={m.reading} "
              f"base={m.base}")


if __name__ == "__main__":
    main()
