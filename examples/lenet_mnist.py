"""LeNet on MNIST — BASELINE config #1 (the reference's
`MnistClassifier`-style quickstart, `zoo/model/LeNet.java`).

Uses real MNIST IDX files when present under the cache dir
(`~/.deeplearning4j_tpu/mnist/`), else the deterministic synthetic
surrogate (flagged). One jitted XLA train step per batch.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402 — repo-root path + CPU re-pin

import numpy as np

from deeplearning4j_tpu.data.datasets import MnistDataSetIterator
from deeplearning4j_tpu.optim.listeners import (
    PerformanceListener, ScoreIterationListener,
)
from deeplearning4j_tpu.zoo import LeNet


def main(epochs: int = 1, batch_size: int = 128, examples: int = 6400):
    net = LeNet(num_classes=10, input_shape=(28, 28, 1)).init()
    net.listeners += [ScoreIterationListener(10, print),
                      PerformanceListener(10, print)]
    train = MnistDataSetIterator(batch_size, train=True,
                                 num_examples=examples)
    if train.synthetic:
        print("NOTE: no MNIST files cached — training on the synthetic "
              "surrogate (accuracy still demonstrates the pipeline)")
    net.fit(train, epochs=epochs)
    test = MnistDataSetIterator(256, train=False, num_examples=1024)
    ev = net.evaluate(test)
    print(ev.stats())
    return ev.accuracy()


if __name__ == "__main__":
    main()
