"""Encoder-decoder sequence transduction with cross-attention.

The graph API composes an encoder branch and a decoder branch joined by
`CrossAttentionVertex` (queries from the decoder, keys/values from the
encoder) — the classic seq2seq-with-attention pattern. The task here is
sequence reversal: the decoder must emit the encoder's tokens backwards,
which is unlearnable without content routing through the attention (the
decoder input carries positions only).

No reference counterpart (DL4J is RNN-era, SURVEY §5 notes it has no
attention); this is the modern-transduction extension on top of the
reference's ComputationGraph multi-input machinery.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402 — repo-root path + CPU re-pin

import numpy as np

from deeplearning4j_tpu import InputType
from deeplearning4j_tpu.data.dataset import MultiDataSet
from deeplearning4j_tpu.models import ComputationGraph
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import CrossAttentionVertex
from deeplearning4j_tpu.nn.layers import DenseLayer
from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
from deeplearning4j_tpu.optim.updaters import Adam


def main(epochs: int = 200, V: int = 8, T: int = 7, n: int = 128):
    rng = np.random.default_rng(0)
    conf = (NeuralNetConfiguration.builder()
            .seed(0).updater(Adam(1e-2)).activation("relu")
            .graph_builder()
            .add_inputs("dec", "enc")
            .add_layer("enc_ff", DenseLayer(n_out=32), "enc")
            .add_layer("dec_ff", DenseLayer(n_out=32), "dec")
            .add_vertex("xattn", CrossAttentionVertex(num_heads=4, n_out=32),
                        "dec_ff", "enc_ff")
            .add_layer("out", RnnOutputLayer(n_out=V, activation="softmax"),
                       "xattn")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(T, T),
                             InputType.recurrent(V + T, T))
            .build())
    net = ComputationGraph(conf).init()

    # encoder sees (token one-hot, position one-hot); decoder sees
    # positions only — every bit of content must flow through xattn
    tokens = rng.integers(0, V, (n, T))
    pos = np.tile(np.eye(T, dtype=np.float32)[None], (n, 1, 1))
    enc = np.concatenate([np.eye(V, dtype=np.float32)[tokens], pos], -1)
    dec = pos
    y = np.eye(V, dtype=np.float32)[tokens[:, ::-1]]   # reversed targets

    mds = MultiDataSet([dec, enc], [y])
    for _ in range(epochs):
        net.fit(mds)

    pred = np.asarray(net.output(dec[:16], enc[:16])).argmax(-1)
    acc = float((pred == tokens[:16, ::-1]).mean())
    print(f"sequence-reversal accuracy through cross-attention: {acc:.2f} "
          f"(final loss {net.score_:.4f})")
    return acc


if __name__ == "__main__":
    main()
