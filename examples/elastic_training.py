"""Preemption-safe training: ElasticTrainer checkpoints shards async and
auto-resumes — rerun this script mid-training (or SIGTERM it) and the
loss curve continues exactly where it stopped (SURVEY §5 elastic gap)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _bootstrap  # noqa: F401,E402 — repo-root path + CPU re-pin

import numpy as np

from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optim.updaters import Adam
from deeplearning4j_tpu.parallel import ElasticTrainer


def main(ckpt_dir: str = "/tmp/dl4j_tpu_elastic_demo"):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((512, 12)).astype(np.float32)
    yi = rng.integers(0, 4, 512)
    x[np.arange(512), yi % 12] += 2.0
    y = np.eye(4, dtype=np.float32)[yi]
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
        .list(DenseLayer(n_in=12, n_out=32, activation="relu"),
              OutputLayer(n_in=32, n_out=4, activation="softmax",
                          loss="mcxent"))
        .build()).init()
    trainer = ElasticTrainer(net, ckpt_dir, checkpoint_every=2)
    result = trainer.fit(x, y, epochs=4, batch_size=64)
    print(result)
    if result["preempted"]:
        print("preempted — rerun to resume from", ckpt_dir)


if __name__ == "__main__":
    main()
