"""Classification evaluation: confusion matrix, accuracy, precision/recall/F1.

Reference parity: `eval/Evaluation.java:50` (`eval():218`, `stats():414`,
precision/recall/F1, confusion matrix) and `eval/ConfusionMatrix.java`.
Accumulates batch-wise; mask-aware for per-timestep RNN labels (the
reference's time-series eval path).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class ConfusionMatrix:
    """Reference: `eval/ConfusionMatrix.java`."""

    def __init__(self, num_classes: int):
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def actual_total(self, cls: int) -> int:
        return int(self.matrix[cls, :].sum())

    def predicted_total(self, cls: int) -> int:
        return int(self.matrix[:, cls].sum())


class Evaluation:
    """Streaming classification metrics. Reference: `eval/Evaluation.java`."""

    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[Sequence[str]] = None):
        self.num_classes = num_classes
        self.label_names = list(labels) if labels else None
        self.confusion: Optional[ConfusionMatrix] = None
        # per-example Prediction records, populated only when record_meta
        # is passed to eval() (reference: eval/meta/, stored when
        # RecordMetaData flows through eval(labels, out, meta))
        self.predictions: list = []

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = ConfusionMatrix(self.num_classes)
        if n > self.num_classes:
            # grow: a first batch that happened to miss high class indices
            # must not pin the matrix size for the rest of the evaluation
            old = self.confusion.matrix
            grown = np.zeros((n, n), old.dtype)
            grown[:old.shape[0], :old.shape[1]] = old
            self.num_classes = n
            self.confusion.matrix = grown

    def eval(self, labels, predictions, mask=None, record_meta=None):
        """Accumulate a batch. labels/predictions: one-hot or prob arrays
        [batch, n] or [batch, time, n]; integer class labels [batch] also
        accepted. `record_meta`: optional per-example RecordMetaData list
        — enables the per-example accessors (get_prediction_errors, ...).
        Reference: `eval():218` + evalTimeSeries + eval(..., meta)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # time series → flatten (with mask)
            if record_meta is not None:
                raise ValueError(
                    "record_meta is not supported with per-timestep (3-D) "
                    "labels — the reference's meta path is per-example")
            B, T, C = labels.shape
            labels = labels.reshape(B * T, C)
            predictions = predictions.reshape(B * T, -1)
            if mask is not None:
                m = np.asarray(mask).reshape(B * T) > 0
                labels, predictions = labels[m], predictions[m]
        elif mask is not None:
            # per-example mask for 2-D/1-D labels (padded batches)
            m = np.asarray(mask).reshape(len(labels)) > 0
            labels, predictions = labels[m], predictions[m]
            if record_meta is not None:
                record_meta = [r for r, keep in zip(record_meta, m) if keep]
        if labels.ndim == 2:
            actual = labels.argmax(axis=-1)
            n = labels.shape[-1]
        else:
            actual = labels.astype(np.int64)
            n = int(predictions.shape[-1])
        pred = predictions.argmax(axis=-1)
        # eval_indices validates record_meta BEFORE mutating, so a caught
        # error leaves the metrics un-double-countable on retry
        self.eval_indices(actual, pred, num_classes=n,
                          record_meta=record_meta)

    def eval_indices(self, actual, predicted,
                     num_classes: Optional[int] = None,
                     record_meta=None) -> None:
        """Accumulate pre-argmaxed class indices — the device-side fast
        path (model.evaluate computes argmax on device and ships only int
        vectors to host)."""
        actual = np.asarray(actual).astype(np.int64)
        predicted = np.asarray(predicted).astype(np.int64)
        if len(actual) == 0:
            if num_classes:  # keep metrics well-defined on empty input
                self._ensure(num_classes)
            return
        n = (num_classes if num_classes is not None
             else int(max(actual.max(), predicted.max())) + 1)
        n = max(n, int(max(actual.max(), predicted.max())) + 1)
        if record_meta is not None and len(record_meta) != len(actual):
            raise ValueError(
                f"record_meta has {len(record_meta)} entries for "
                f"{len(actual)} examples")
        self._ensure(n)
        np.add.at(self.confusion.matrix, (actual, predicted), 1)
        if record_meta is not None:
            from deeplearning4j_tpu.eval.meta import Prediction

            self.predictions.extend(
                Prediction(int(a), int(p), m)
                for a, p, m in zip(actual, predicted, record_meta))

    @staticmethod
    def run_evaluation(evaluator, iterator, output_fn):
        """Feed every batch's outputs into any batch-wise evaluator with an
        `eval(labels, predictions)` method — backs the model-level
        evaluate_regression / evaluate_roc / evaluate_roc_multi_class
        (reference: MultiLayerNetwork.java:2668-2699).

        Masking/time-series normalization happens HERE (flatten [B,T,C] to
        [B·T, C] and drop masked rows, the reference's evalTimeSeries
        path), so evaluators that don't understand masks (ROC family)
        still get only valid examples. MultiDataSet batches evaluate the
        FIRST output (the reference's single-output contract)."""
        for ds in iterator:
            if hasattr(ds, "labels_masks"):   # MultiDataSet
                out = output_fn(*ds.features)
                if isinstance(out, (list, tuple)):
                    out = out[0]
                labels = np.asarray(ds.labels[0])
                mask = (ds.labels_masks[0]
                        if ds.labels_masks else None)
            else:
                out = output_fn(ds.features)
                labels = np.asarray(ds.labels)
                mask = ds.labels_mask
            preds = np.asarray(out)
            if labels.ndim == 3:
                B, T, C = labels.shape
                labels = labels.reshape(B * T, C)
                preds = preds.reshape(B * T, -1)
                if mask is not None:
                    keep = np.asarray(mask).reshape(B * T) > 0
                    labels, preds = labels[keep], preds[keep]
            elif mask is not None:
                keep = np.asarray(mask).reshape(len(labels)) > 0
                labels, preds = labels[keep], preds[keep]
            evaluator.eval(labels, preds)
        return evaluator

    def evaluate_iterator(self, iterator, *, output_fn, predict_indices_fn):
        """Shared batch loop for model.evaluate (MultiLayerNetwork and
        ComputationGraph): device-side argmax fast path for plain
        per-example labels (only int32 indices cross to host via
        `predict_indices_fn(features) -> (indices, head_width)`), full
        softmax through `output_fn` for masked/time-series labels.

        If the iterator collects RecordMetaData (`set_collect_meta_data` —
        `last_meta` set per batch), it flows into per-example Prediction
        records, so `get_prediction_errors()` works straight off
        `model.evaluate(it)` (reference: MultiLayerNetwork.doEvaluation
        passing meta into eval(labels, out, meta))."""
        for ds in iterator:
            labels = np.asarray(ds.labels)
            meta = getattr(iterator, "last_meta", None)
            if labels.ndim == 3 or ds.labels_mask is not None:
                self.eval(labels, np.asarray(output_fn(ds.features)),
                          mask=ds.labels_mask,
                          record_meta=None if labels.ndim == 3 else meta)
                continue
            pred, width = predict_indices_fn(ds.features)
            actual = (labels.argmax(-1) if labels.ndim == 2
                      else labels.astype(np.int64))
            # class count from the one-hot width, else the model head —
            # a batch missing high classes must not shrink the matrix
            n = labels.shape[-1] if labels.ndim == 2 else width
            self.eval_indices(actual, np.asarray(pred), num_classes=n,
                              record_meta=meta)
        return self

    # ---- per-example accessors (reference: eval/meta + Evaluation
    #      getPredictionErrors/getPredictionsByActualClass/...) ----
    def get_prediction_errors(self) -> list:
        """All misclassified examples' Prediction records."""
        return [p for p in self.predictions if p.actual != p.predicted]

    def get_predictions_by_actual_class(self, cls: int) -> list:
        return [p for p in self.predictions if p.actual == cls]

    def get_predictions_by_predicted_class(self, cls: int) -> list:
        return [p for p in self.predictions if p.predicted == cls]

    def get_predictions(self, actual: int, predicted: int) -> list:
        """Prediction records in one confusion cell. Reference:
        `Evaluation.getPredictions(actualClass, predictedClass)`."""
        return [p for p in self.predictions
                if p.actual == actual and p.predicted == predicted]

    def get_top_n_confusions(self, n: int = 5) -> list:
        """Most frequent OFF-diagonal (actual, predicted, count) cells,
        descending — 'what does the model confuse most'. Works off the
        confusion matrix, so it needs no RecordMetaData collection."""
        if self.confusion is None:
            return []
        m = self.confusion.matrix.copy()
        np.fill_diagonal(m, 0)
        pairs = np.argwhere(m > 0)
        order = sorted(pairs.tolist(), key=lambda ij: -m[ij[0], ij[1]])
        return [(int(a), int(p), int(m[a, p])) for a, p in order[:n]]

    # ---- metrics (reference method names) ----
    def _tp(self, c):
        return self.confusion.matrix[c, c]

    def accuracy(self) -> float:
        if self.confusion is None:   # nothing accumulated yet
            return 0.0
        m = self.confusion.matrix
        total = m.sum()
        return float(np.trace(m) / total) if total else 0.0

    def precision(self, cls: Optional[int] = None,
                  averaging: str = "macro") -> float:
        """Per-class, or averaged: "macro" (mean of per-class values,
        the reference default) or "micro" (global TP/(TP+FP) — equals
        accuracy for single-label multiclass). Reference:
        `eval/EvaluationAveraging.java` + Evaluation.precision."""
        if self.confusion is None:
            return 0.0
        m = self.confusion.matrix
        if cls is not None:
            denom = m[:, cls].sum()
            return float(m[cls, cls] / denom) if denom else 0.0
        if averaging == "micro":   # == accuracy for single-label multiclass
            return self.accuracy()
        if averaging != "macro":
            raise ValueError(f"averaging must be macro|micro, got {averaging!r}")
        vals = [self.precision(c) for c in range(self.num_classes)
                if m[:, c].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None,
               averaging: str = "macro") -> float:
        if self.confusion is None:
            return 0.0
        m = self.confusion.matrix
        if cls is not None:
            denom = m[cls, :].sum()
            return float(m[cls, cls] / denom) if denom else 0.0
        if averaging == "micro":   # == accuracy for single-label multiclass
            return self.accuracy()
        if averaging != "macro":
            raise ValueError(f"averaging must be macro|micro, got {averaging!r}")
        vals = [self.recall(c) for c in range(self.num_classes)
                if m[c, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None,
           averaging: str = "macro") -> float:
        return self.f_beta(1.0, cls, averaging)

    def f_beta(self, beta: float, cls: Optional[int] = None,
               averaging: str = "macro") -> float:
        """Reference: `eval/EvaluationUtils.java` fBeta."""
        p = self.precision(cls, averaging)
        r = self.recall(cls, averaging)
        if p == 0.0 or r == 0.0:
            return 0.0
        b2 = beta * beta
        return float((1 + b2) * p * r / (b2 * p + r))

    def g_measure(self, cls: Optional[int] = None,
                  averaging: str = "macro") -> float:
        """Geometric mean of precision and recall. Reference:
        `eval/EvaluationUtils.java` gMeasure."""
        p = self.precision(cls, averaging)
        r = self.recall(cls, averaging)
        return float(np.sqrt(p * r))

    def false_positive_rate(self, cls: int) -> float:
        m = self.confusion.matrix
        fp = m[:, cls].sum() - m[cls, cls]
        tn = m.sum() - m[cls, :].sum() - m[:, cls].sum() + m[cls, cls]
        return float(fp / (fp + tn)) if (fp + tn) else 0.0

    def matthews_correlation(self, cls: int) -> float:
        m = self.confusion.matrix
        tp = m[cls, cls]
        fp = m[:, cls].sum() - tp
        fn = m[cls, :].sum() - tp
        tn = m.sum() - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float((tp * tn - fp * fn) / denom) if denom else 0.0

    def stats(self) -> str:
        """Human-readable summary. Reference: `stats():414`."""
        if self.confusion is None:
            return "Evaluation: no examples accumulated"
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes: {self.num_classes}",
            f" Accuracy:  {self.accuracy():.4f}",
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
        ]
        m = self.confusion.matrix
        names = self.label_names or [str(i) for i in range(self.num_classes)]
        header = "      " + " ".join(f"{n:>6}" for n in names)
        lines.append(header)
        for i in range(self.num_classes):
            row = " ".join(f"{int(m[i, j]):>6}" for j in range(self.num_classes))
            lines.append(f"{names[i]:>5} {row}")
        return "\n".join(lines)

    def merge(self, other: "Evaluation") -> "Evaluation":
        """Combine evaluations from shards (used by distributed eval;
        reference: Spark-side evaluation aggregation)."""
        if other.confusion is None:
            return self
        if self.confusion is None:
            self.num_classes = other.num_classes
            self.confusion = ConfusionMatrix(other.num_classes)
        self.confusion.matrix = self.confusion.matrix + other.confusion.matrix
        self.predictions.extend(other.predictions)
        return self
