"""Evaluation metrics.

Reference parity: `eval/` in deeplearning4j-nn — Evaluation (confusion
matrix / precision / recall / F1), EvaluationBinary, RegressionEvaluation,
ROC family. Metrics accumulate batch-wise on host numpy (tiny data), matching
the reference's streaming eval design.
"""

from deeplearning4j_tpu.eval.evaluation import Evaluation, ConfusionMatrix
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass
from deeplearning4j_tpu.eval.binary import EvaluationBinary
from deeplearning4j_tpu.eval.meta import Prediction, RecordMetaData

__all__ = [
    "Evaluation", "ConfusionMatrix", "RegressionEvaluation", "ROC",
    "ROCBinary", "ROCMultiClass", "EvaluationBinary",
    "Prediction", "RecordMetaData",
]
