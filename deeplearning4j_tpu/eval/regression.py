"""Regression evaluation.

Reference parity: `eval/RegressionEvaluation.java` — per-column MSE, MAE,
RMSE, RSE, correlation, R².
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, num_columns: Optional[int] = None):
        self.n = 0
        self.num_columns = num_columns
        self._sum_sq_err = None
        self._sum_abs_err = None
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_pred_sq = None
        self._sum_label_pred = None

    def _ensure(self, c: int):
        if self._sum_sq_err is None:
            self.num_columns = self.num_columns or c
            z = lambda: np.zeros(self.num_columns)
            self._sum_sq_err = z()
            self._sum_abs_err = z()
            self._sum_label = z()
            self._sum_label_sq = z()
            self._sum_pred = z()
            self._sum_pred_sq = z()
            self._sum_label_pred = z()

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            B, T, C = labels.shape
            labels = labels.reshape(B * T, C)
            predictions = predictions.reshape(B * T, C)
            if mask is not None:
                m = np.asarray(mask).reshape(B * T) > 0
                labels, predictions = labels[m], predictions[m]
        self._ensure(labels.shape[-1])
        err = predictions - labels
        self.n += labels.shape[0]
        self._sum_sq_err += (err**2).sum(0)
        self._sum_abs_err += np.abs(err).sum(0)
        self._sum_label += labels.sum(0)
        self._sum_label_sq += (labels**2).sum(0)
        self._sum_pred += predictions.sum(0)
        self._sum_pred_sq += (predictions**2).sum(0)
        self._sum_label_pred += (labels * predictions).sum(0)

    def mean_squared_error(self, col: int) -> float:
        return float(self._sum_sq_err[col] / self.n)

    def mean_absolute_error(self, col: int) -> float:
        return float(self._sum_abs_err[col] / self.n)

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self._sum_sq_err[col] / self.n))

    def correlation_r2(self, col: int) -> float:
        """Pearson correlation between labels and predictions for a column."""
        n = self.n
        num = self._sum_label_pred[col] - self._sum_label[col] * self._sum_pred[col] / n
        den_l = self._sum_label_sq[col] - self._sum_label[col] ** 2 / n
        den_p = self._sum_pred_sq[col] - self._sum_pred[col] ** 2 / n
        den = np.sqrt(den_l * den_p)
        return float(num / den) if den else 0.0

    def average_mean_squared_error(self) -> float:
        return float(self._sum_sq_err.mean() / self.n)

    def average_mean_absolute_error(self) -> float:
        return float(self._sum_abs_err.mean() / self.n)

    def stats(self) -> str:
        cols = range(self.num_columns)
        lines = ["Column    MSE            MAE            RMSE           Corr"]
        for c in cols:
            lines.append(
                f"col_{c:<5} {self.mean_squared_error(c):<14.6f} "
                f"{self.mean_absolute_error(c):<14.6f} "
                f"{self.root_mean_squared_error(c):<14.6f} "
                f"{self.correlation_r2(c):<14.6f}"
            )
        return "\n".join(lines)
