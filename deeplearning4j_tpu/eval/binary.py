"""Per-output binary evaluation (multi-label).

Reference parity: `eval/EvaluationBinary.java` — independent binary metrics
per output column at threshold 0.5.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class EvaluationBinary:
    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = None
        self.fp = None
        self.tn = None
        self.fn = None

    def _ensure(self, n: int):
        if self.tp is None:
            self.tp = np.zeros(n, dtype=np.int64)
            self.fp = np.zeros(n, dtype=np.int64)
            self.tn = np.zeros(n, dtype=np.int64)
            self.fn = np.zeros(n, dtype=np.int64)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels) > 0.5
        pred = np.asarray(predictions) >= self.threshold
        if labels.ndim == 3:
            B, T, C = labels.shape
            labels = labels.reshape(B * T, C)
            pred = pred.reshape(B * T, C)
            if mask is not None:
                m = np.asarray(mask).reshape(B * T) > 0
                labels, pred = labels[m], pred[m]
        self._ensure(labels.shape[-1])
        self.tp += (labels & pred).sum(0)
        self.fp += (~labels & pred).sum(0)
        self.tn += (~labels & ~pred).sum(0)
        self.fn += (labels & ~pred).sum(0)

    def accuracy(self, col: int) -> float:
        total = self.tp[col] + self.fp[col] + self.tn[col] + self.fn[col]
        return float((self.tp[col] + self.tn[col]) / total) if total else 0.0

    def precision(self, col: int) -> float:
        d = self.tp[col] + self.fp[col]
        return float(self.tp[col] / d) if d else 0.0

    def recall(self, col: int) -> float:
        d = self.tp[col] + self.fn[col]
        return float(self.tp[col] / d) if d else 0.0

    def f1(self, col: int) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def stats(self) -> str:
        n = len(self.tp)
        lines = ["Label   Acc      Precision Recall   F1"]
        for c in range(n):
            lines.append(
                f"{c:<7} {self.accuracy(c):<8.4f} {self.precision(c):<9.4f} "
                f"{self.recall(c):<8.4f} {self.f1(c):<8.4f}"
            )
        return "\n".join(lines)
