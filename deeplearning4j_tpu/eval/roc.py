"""ROC / AUC evaluation.

Reference parity: `eval/ROC.java` (369 LoC, thresholded), `ROCBinary`,
`ROCMultiClass`. The reference accumulates threshold buckets; here we keep
exact scores (host memory is ample for eval-sized data) and compute exact AUC
by rank statistics, with `threshold_steps` bucketing available for parity.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def _auc_from_scores(pos: np.ndarray, neg: np.ndarray) -> float:
    """Exact AUROC via Mann-Whitney U."""
    if len(pos) == 0 or len(neg) == 0:
        return 0.0
    all_ = np.concatenate([pos, neg])
    # average ranks with tie handling
    order = np.argsort(all_)
    sorted_vals = all_[order]
    avg_ranks = np.empty(len(all_), dtype=np.float64)
    i = 0
    while i < len(sorted_vals):
        j = i
        while j + 1 < len(sorted_vals) and sorted_vals[j + 1] == sorted_vals[i]:
            j += 1
        avg = (i + j) / 2.0 + 1
        avg_ranks[order[i:j + 1]] = avg
        i = j + 1
    r_pos = avg_ranks[: len(pos)].sum()  # first len(pos) entries are positives
    n1, n2 = len(pos), len(neg)
    u = r_pos - n1 * (n1 + 1) / 2.0
    return float(u / (n1 * n2))


class ROC:
    """Binary ROC (positive class = column 1 of 2-col labels, or 1-col 0/1).
    Reference: `eval/ROC.java`."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._scores: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[-1] == 2:
            y = labels[:, 1]
            s = predictions[:, 1]
        else:
            y = labels.reshape(-1)
            s = predictions.reshape(-1)
        self._labels.append(y)
        self._scores.append(s)

    def calculate_auc(self) -> float:
        y = np.concatenate(self._labels)
        s = np.concatenate(self._scores)
        return _auc_from_scores(s[y > 0.5], s[y <= 0.5])

    def get_roc_curve(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (thresholds, fpr, tpr)."""
        y = np.concatenate(self._labels)
        s = np.concatenate(self._scores)
        steps = self.threshold_steps or 100
        thresholds = np.linspace(0, 1, steps + 1)
        P = max((y > 0.5).sum(), 1)
        N = max((y <= 0.5).sum(), 1)
        tpr = np.array([((s >= t) & (y > 0.5)).sum() / P for t in thresholds])
        fpr = np.array([((s >= t) & (y <= 0.5)).sum() / N for t in thresholds])
        return thresholds, fpr, tpr


class ROCBinary:
    """Per-output independent binary ROC. Reference: `eval/ROCBinary.java`."""

    def __init__(self):
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        n = labels.shape[-1]
        if self._rocs is None:
            self._rocs = [ROC() for _ in range(n)]
        for c in range(n):
            self._rocs[c].eval(labels[:, c], predictions[:, c])

    def calculate_auc(self, col: int) -> float:
        return self._rocs[col].calculate_auc()

    def average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))


class ROCMultiClass:
    """One-vs-all ROC per class. Reference: `eval/ROCMultiClass.java`."""

    def __init__(self):
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        n = labels.shape[-1]
        if self._rocs is None:
            self._rocs = [ROC() for _ in range(n)]
        for c in range(n):
            self._rocs[c].eval(labels[:, c], predictions[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))
