"""Per-example evaluation metadata.

Reference parity: `eval/meta/` (`Prediction.java`) + the RecordMetaData
plumbing (`datasets/datavec/RecordReaderDataSetIterator` carries
RecordMetaData through to `Evaluation.eval(labels, out, meta)`), so an
evaluation can answer WHICH examples were misclassified, not just how
many.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class RecordMetaData:
    """Where an example came from. Reference: datavec `RecordMetaData`
    (getLocation/getURI) — here source + location (e.g. file path + line
    or array index)."""

    source: str
    location: Any = None

    def __str__(self):
        return (f"{self.source}[{self.location}]"
                if self.location is not None else self.source)


@dataclasses.dataclass(frozen=True)
class Prediction:
    """One example's outcome. Reference: `eval/meta/Prediction.java`
    (actual/predicted class + record metadata)."""

    actual: int
    predicted: int
    record_meta: Optional[RecordMetaData] = None

    def __str__(self):
        return (f"actual={self.actual}, predicted={self.predicted}, "
                f"meta={self.record_meta}")
