"""DataSet containers.

Reference parity: ND4J `DataSet` (features, labels, featuresMask, labelsMask)
and `MultiDataSet` (lists of each) — the unit every iterator yields and every
`fit()` consumes. Arrays are host numpy until the train step moves them to
device (one transfer per batch; double-buffered by AsyncDataSetIterator).
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: Optional[np.ndarray] = None
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int) -> Tuple["DataSet", "DataSet"]:
        """Reference: DataSet.splitTestAndTrain."""
        def sl(a, lo, hi):
            return None if a is None else a[lo:hi]

        train = DataSet(self.features[:n_train], sl(self.labels, 0, n_train),
                        sl(self.features_mask, 0, n_train), sl(self.labels_mask, 0, n_train))
        n = self.num_examples()
        test = DataSet(self.features[n_train:], sl(self.labels, n_train, n),
                       sl(self.features_mask, n_train, n), sl(self.labels_mask, n_train, n))
        return train, test

    def shuffle(self, seed: Optional[int] = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        pick = lambda a: None if a is None else a[idx]
        return DataSet(self.features[idx], pick(self.labels),
                       pick(self.features_mask), pick(self.labels_mask))

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        n = self.num_examples()
        for lo in range(0, n, batch_size):
            hi = min(lo + batch_size, n)
            sl = lambda a: None if a is None else a[lo:hi]
            out.append(DataSet(self.features[lo:hi], sl(self.labels),
                               sl(self.features_mask), sl(self.labels_mask)))
        return out

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        """Reference: DataSet.merge."""
        cat = lambda xs: None if xs[0] is None else np.concatenate(xs, axis=0)
        return DataSet(
            cat([d.features for d in datasets]),
            cat([d.labels for d in datasets]),
            cat([d.features_mask for d in datasets]),
            cat([d.labels_mask for d in datasets]),
        )

    def save(self, path) -> str:
        """Persist as one .npz — the pre-saved-minibatch flow the
        reference drives with DataSet.save + ExistingMiniBatch/FileSplit
        iterators and Spark's fitPaths (SparkDl4jMultiLayer.java:259)."""
        path = os.fspath(path)
        if not path.endswith(".npz"):
            path += ".npz"       # keep directory iterators able to see it
        arrays = {"features": self.features}
        for k in ("labels", "features_mask", "labels_mask"):
            v = getattr(self, k)
            if v is not None:
                arrays[k] = v
        with open(path, "wb") as f:
            np.savez(f, **arrays)
        return path

    @staticmethod
    def load(path) -> "DataSet":
        with np.load(os.fspath(path)) as blob:
            g = lambda k: blob[k] if k in blob.files else None
            return DataSet(blob["features"], g("labels"),
                           g("features_mask"), g("labels_mask"))


@dataclasses.dataclass
class MultiDataSet:
    """Multi-input/multi-output container. Reference: ND4J MultiDataSet."""

    features: List[np.ndarray]
    labels: List[np.ndarray]
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])

    def save(self, path) -> str:
        """One .npz per MultiDataSet (reference: ND4J MultiDataSet.save);
        arrays keyed f<i>/l<i>/fm<i>/lm<i>, masks optional per slot."""
        path = os.fspath(path)
        if not path.endswith(".npz"):
            path += ".npz"
        arrays = {}
        for i, f in enumerate(self.features):
            arrays[f"f{i}"] = f
        for i, l in enumerate(self.labels):
            arrays[f"l{i}"] = l
        for key, group in (("fm", self.features_masks),
                           ("lm", self.labels_masks)):
            for i, m in enumerate(group or []):
                if m is not None:
                    arrays[f"{key}{i}"] = m
        with open(path, "wb") as f:
            np.savez(f, **arrays)
        return path

    @staticmethod
    def load(path) -> "MultiDataSet":
        blob = np.load(os.fspath(path))

        def group(prefix, n):
            out = [blob.get(f"{prefix}{i}") for i in range(n)]
            return out if any(m is not None for m in out) else None

        nf = sum(1 for k in blob.files if k.startswith("f")
                 and not k.startswith("fm"))
        nl = sum(1 for k in blob.files if k.startswith("l")
                 and not k.startswith("lm"))
        data = dict(blob)
        blob.close()
        blob = data
        return MultiDataSet(
            [blob[f"f{i}"] for i in range(nf)],
            [blob[f"l{i}"] for i in range(nl)],
            group("fm", nf), group("lm", nl))
