"""Data pipeline: DataSet containers, iterators, async prefetch, datasets.

Reference parity: ND4J `DataSet`/`MultiDataSet` + deeplearning4j-core
`datasets/` (iterators, fetchers) + dl4j-nn `datasets/iterator/`
(AsyncDataSetIterator and decorators).
"""

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    DataSetIterator, ArrayDataSetIterator, AsyncDataSetIterator,
    MultipleEpochsIterator, EarlyTerminationDataSetIterator,
    BenchmarkDataSetIterator, FileSplitDataSetIterator, as_iterator,
)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ArrayDataSetIterator",
    "AsyncDataSetIterator", "MultipleEpochsIterator",
    "EarlyTerminationDataSetIterator", "BenchmarkDataSetIterator",
    "FileSplitDataSetIterator",
    "as_iterator",
]
