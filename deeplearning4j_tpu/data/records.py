"""Record readers — the DataVec-bridge equivalent.

Reference parity: DataVec RecordReaders consumed through
`datasets/datavec/RecordReaderDataSetIterator.java`,
`SequenceRecordReaderDataSetIterator.java` (SURVEY §2.2): CSV, CSV
sequences, and images → DataSet batches with label one-hotting.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


class RecordReader:
    """Reference: DataVec RecordReader — iterable of records (value lists)."""

    def __iter__(self) -> Iterator[List]:
        raise NotImplementedError

    def reset(self):
        pass

    def load_from_meta_data(self, metas) -> List[List]:
        """Re-read the ORIGINAL records behind RecordMetaData entries
        (locations are record indices assigned by the consuming iterator).
        Reference: DataVec `RecordReader.loadFromMetaData` — what powers
        `Prediction.getRecord()`-style 'show me the misclassified
        example' workflows."""
        src = str(getattr(self, "path", type(self).__name__))
        wrong = [m for m in metas if m.source != src]
        if wrong:
            # index-only matching against a DIFFERENT source would silently
            # return unrelated records (DataVec matches by URI)
            raise ValueError(
                f"RecordMetaData source {wrong[0].source!r} does not match "
                f"this reader ({src!r})")
        wanted = {int(m.location) for m in metas}
        found: Dict[int, List] = {}
        for i, rec in enumerate(self):
            if i in wanted:
                found[i] = rec
                if len(found) == len(wanted):
                    break
        missing = wanted - found.keys()
        if missing:
            raise KeyError(
                f"records {sorted(missing)} not found in {self!r}")
        return [found[int(m.location)] for m in metas]


class CSVRecordReader(RecordReader):
    """Reference: DataVec CSVRecordReader."""

    def __init__(self, path: str, *, skip_lines: int = 0,
                 delimiter: str = ","):
        self.path = path
        self.skip = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        with open(self.path, newline="") as f:
            r = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(r):
                if i < self.skip or not row:
                    continue
                yield row


class CollectionRecordReader(RecordReader):
    """Reference: CollectionRecordReader (in-memory records)."""

    def __init__(self, records: Sequence[Sequence]):
        self.records = [list(r) for r in records]

    def __iter__(self):
        return iter(self.records)


class CSVSequenceRecordReader(RecordReader):
    """One CSV file per sequence in a directory. Reference: DataVec
    CSVSequenceRecordReader."""

    def __init__(self, directory: str, *, skip_lines: int = 0,
                 delimiter: str = ","):
        self.directory = directory
        self.skip = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for fn in sorted(os.listdir(self.directory)):
            rows = []
            with open(os.path.join(self.directory, fn), newline="") as f:
                for i, row in enumerate(csv.reader(f, delimiter=self.delimiter)):
                    if i < self.skip or not row:
                        continue
                    rows.append(row)
            yield rows


class ImageRecordReader(RecordReader):
    """Directory-per-class images → (pixels..., label) records.
    Reference: DataVec ImageRecordReader (labels from parent dir)."""

    def __init__(self, root: str, *, height: int, width: int,
                 channels: int = 3):
        self.root = root
        self.h, self.w, self.c = height, width, channels
        self.labels = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))

    def __iter__(self):
        from PIL import Image

        for li, label in enumerate(self.labels):
            d = os.path.join(self.root, label)
            for fn in sorted(os.listdir(d)):
                try:
                    img = Image.open(os.path.join(d, fn))
                except Exception:
                    continue
                img = img.convert("RGB" if self.c == 3 else "L")
                img = img.resize((self.w, self.h))
                arr = np.asarray(img, np.float32) / 255.0
                if self.c == 1:
                    arr = arr[..., None]
                yield [arr, li]


class RecordReaderDataSetIterator(DataSetIterator):
    """Reference: `datasets/datavec/RecordReaderDataSetIterator.java` —
    records → (features, one-hot labels) DataSet batches."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False, collect_meta: bool = False):
        self.reader = reader
        self.bs = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        # remember whether the caller pinned the width: pinned widths are
        # validated in _to_dataset (a corrupt label raises rather than
        # silently widening the one-hot / confusion-matrix width);
        # inferred widths stay sticky-growing
        self._num_classes_pinned = num_classes is not None
        self.regression = regression
        # reference: RecordReaderDataSetIterator.setCollectMetaData(true) —
        # each batch then exposes per-example RecordMetaData via
        # `last_meta` for Evaluation.eval(..., record_meta=...)
        self.collect_meta = collect_meta
        self.last_meta: Optional[list] = None
        self._record_index = 0
        self._it: Optional[Iterator] = None

    def set_collect_meta_data(self, v: bool) -> None:
        """Reference: setCollectMetaData."""
        self.collect_meta = v

    def reset(self):
        self._it = iter(self.reader)
        self._record_index = 0

    def __next__(self) -> DataSet:
        if self._it is None:
            self.reset()
        feats, labs = [], []
        metas = [] if self.collect_meta else None
        if metas is not None:
            from deeplearning4j_tpu.eval.meta import RecordMetaData

            src = str(getattr(self.reader, "path",
                              type(self.reader).__name__))
        for _ in range(self.bs):
            try:
                rec = next(self._it)
            except StopIteration:
                break
            if metas is not None:
                metas.append(RecordMetaData(src, self._record_index))
            self._record_index += 1
            self._append_parsed(rec, feats, labs)
        if not feats:
            self._it = None
            raise StopIteration
        self.last_meta = metas
        return self._to_dataset(feats, labs)

    def _append_parsed(self, rec, feats, labs):
        if isinstance(rec[0], np.ndarray):  # image record
            feats.append(rec[0])
            labs.append(rec[1])
        else:
            vals = [float(v) for v in rec]
            li = self.label_index if self.label_index >= 0 \
                else len(vals) - 1
            labs.append(vals[li])
            feats.append(vals[:li] + vals[li + 1:])

    def _to_dataset(self, feats, labs) -> DataSet:
        x = np.asarray(feats, np.float32)
        if self.regression:
            y = np.asarray(labs, np.float32).reshape(len(labs), -1)
        else:
            idx = np.asarray(labs, np.int64)
            if int(idx.min()) < 0:
                raise ValueError(
                    f"negative class label {int(idx.min())} — labels must "
                    "be non-negative integers")
            if self._num_classes_pinned and int(idx.max()) >= self.num_classes:
                raise ValueError(
                    f"label {int(idx.max())} out of range for the "
                    f"explicitly configured num_classes={self.num_classes}")
            # sticky width: once a class is seen, every later batch (and
            # load_from_meta_data subsets) one-hots to the same width
            n = max(self.num_classes or 0, int(idx.max()) + 1)
            self.num_classes = n
            y = np.eye(n, dtype=np.float32)[idx]
        return DataSet(x, y)

    def load_from_meta_data(self, metas) -> DataSet:
        """Rebuild the exact (features, labels) DataSet for specific
        RecordMetaData entries — e.g. `ev.get_prediction_errors()` →
        inspect the misclassified inputs. Reference:
        `RecordReaderDataSetIterator.loadFromMetaData`."""
        feats, labs = [], []
        for rec in self.reader.load_from_meta_data(metas):
            self._append_parsed(rec, feats, labs)
        return self._to_dataset(feats, labs)

    @property
    def batch_size(self):
        return self.bs


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Reference: SequenceRecordReaderDataSetIterator — per-sequence CSVs →
    padded [batch, time, features] with per-timestep masks."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self.bs = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self._it = None

    def reset(self):
        self._it = iter(self.reader)

    def __next__(self) -> DataSet:
        if self._it is None:
            self.reset()
        seqs = []
        for _ in range(self.bs):
            try:
                seqs.append(next(self._it))
            except StopIteration:
                break
        if not seqs:
            self._it = None
            raise StopIteration
        T = max(len(s) for s in seqs)
        first = seqs[0][0]
        li = self.label_index if self.label_index >= 0 else len(first) - 1
        F = len(first) - 1
        B = len(seqs)
        x = np.zeros((B, T, F), np.float32)
        mask = np.zeros((B, T), np.float32)
        lab_raw = np.zeros((B, T), np.float32)
        for b, s in enumerate(seqs):
            for t, row in enumerate(s):
                vals = [float(v) for v in row]
                lab_raw[b, t] = vals[li]
                x[b, t] = vals[:li] + vals[li + 1:]
                mask[b, t] = 1.0
        if self.regression:
            y = lab_raw[..., None]
        else:
            n = self.num_classes or int(lab_raw.max()) + 1
            y = np.eye(n, dtype=np.float32)[lab_raw.astype(np.int64)]
            y = y * mask[..., None]
        return DataSet(x, y, mask, mask)

    @property
    def batch_size(self):
        return self.bs


class RecordReaderMultiDataSetIterator(DataSetIterator):
    """Multi-input/multi-output DataVec bridge — flexible column mappings
    from one or more readers into MultiDataSet batches. Reference:
    `datasets/datavec/RecordReaderMultiDataSetIterator.java` (Builder:
    addReader / addInput(reader, from, to) / addOutput /
    addOutputOneHot), the iterator ComputationGraph training feeds from.

    Usage (builder-style, mirroring the reference):
        it = (RecordReaderMultiDataSetIterator.builder(batch_size)
              .add_reader("csv", reader)
              .add_input("csv", 0, 3)           # columns 0..3 inclusive
              .add_output_one_hot("csv", 4, 3)  # column 4 as 3-class 1-hot
              .build())
    """

    def __init__(self, batch_size: int, readers, inputs, outputs):
        self.bs = batch_size
        self._readers = readers      # name -> RecordReader
        self._inputs = inputs        # list of (reader, lo, hi)
        self._outputs = outputs      # list of (reader, lo, hi, one_hot_n)
        self._its: Optional[Dict[str, Iterator]] = None

    # ------------------------------------------------------------ builder
    class Builder:
        def __init__(self, batch_size: int):
            self._bs = batch_size
            self._readers: Dict[str, RecordReader] = {}
            self._inputs = []
            self._outputs = []

        def add_reader(self, name: str, reader: RecordReader):
            self._readers[name] = reader
            return self

        def add_input(self, reader: str, col_from: int, col_to: int):
            self._inputs.append((reader, col_from, col_to, None))
            return self

        def add_output(self, reader: str, col_from: int, col_to: int):
            self._outputs.append((reader, col_from, col_to, None))
            return self

        def add_output_one_hot(self, reader: str, column: int,
                               num_classes: int):
            self._outputs.append((reader, column, column, num_classes))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            if not self._readers or not self._inputs:
                raise ValueError("need at least one reader and one input")
            for reader, *_ in self._inputs + self._outputs:
                if reader not in self._readers:
                    raise ValueError(f"unknown reader {reader!r}")
            return RecordReaderMultiDataSetIterator(
                self._bs, self._readers, self._inputs, self._outputs)

    @staticmethod
    def builder(batch_size: int) -> "RecordReaderMultiDataSetIterator.Builder":
        return RecordReaderMultiDataSetIterator.Builder(batch_size)

    # ----------------------------------------------------------- iterate
    def reset(self):
        self._its = {n: iter(r) for n, r in self._readers.items()}

    def __next__(self):
        from deeplearning4j_tpu.data.dataset import MultiDataSet

        if self._its is None:
            self.reset()
        rows: Dict[str, list] = {n: [] for n in self._readers}
        for _ in range(self.bs):
            batch_row = {}
            try:
                for n, it in self._its.items():
                    batch_row[n] = list(next(it))   # raw values; only
                    # MAPPED columns get converted (mixed-type CSVs with
                    # unmapped string columns must work, like DataVec)
            except StopIteration:
                break    # readers must align; stop at the shortest
            for n, vals in batch_row.items():
                rows[n].append(vals)
        if not next(iter(rows.values())):
            self._its = None
            raise StopIteration

        # validate mapped ranges against the actual record width ONCE per
        # batch — Python slices would silently truncate out-of-range cols
        for reader, lo, hi, _ in self._inputs + self._outputs:
            width = len(rows[reader][0])
            if lo < 0 or hi >= width:
                raise ValueError(
                    f"column range [{lo}, {hi}] out of bounds for reader "
                    f"{reader!r} records of width {width}")

        def slab(spec):
            reader, lo, hi, one_hot = spec
            arr = np.asarray(
                [[float(v) for v in r[lo:hi + 1]] for r in rows[reader]],
                np.float32)
            if one_hot:
                idx = arr[:, 0].astype(np.int64)
                if ((idx < 0) | (idx >= one_hot)).any():
                    raise ValueError(
                        f"one-hot column {lo} of reader {reader!r} has "
                        f"labels outside [0, {one_hot})")
                arr = np.eye(one_hot, dtype=np.float32)[idx]
            return arr

        feats = [slab(s) for s in self._inputs]
        labs = [slab(s) for s in self._outputs]
        return MultiDataSet(feats, labs)

    @property
    def batch_size(self):
        return self.bs
