"""Record readers — the DataVec-bridge equivalent.

Reference parity: DataVec RecordReaders consumed through
`datasets/datavec/RecordReaderDataSetIterator.java`,
`SequenceRecordReaderDataSetIterator.java` (SURVEY §2.2): CSV, CSV
sequences, and images → DataSet batches with label one-hotting.
"""

from __future__ import annotations

import csv
import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


class RecordReader:
    """Reference: DataVec RecordReader — iterable of records (value lists)."""

    def __iter__(self) -> Iterator[List]:
        raise NotImplementedError

    def reset(self):
        pass


class CSVRecordReader(RecordReader):
    """Reference: DataVec CSVRecordReader."""

    def __init__(self, path: str, *, skip_lines: int = 0,
                 delimiter: str = ","):
        self.path = path
        self.skip = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        with open(self.path, newline="") as f:
            r = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(r):
                if i < self.skip or not row:
                    continue
                yield row


class CollectionRecordReader(RecordReader):
    """Reference: CollectionRecordReader (in-memory records)."""

    def __init__(self, records: Sequence[Sequence]):
        self.records = [list(r) for r in records]

    def __iter__(self):
        return iter(self.records)


class CSVSequenceRecordReader(RecordReader):
    """One CSV file per sequence in a directory. Reference: DataVec
    CSVSequenceRecordReader."""

    def __init__(self, directory: str, *, skip_lines: int = 0,
                 delimiter: str = ","):
        self.directory = directory
        self.skip = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for fn in sorted(os.listdir(self.directory)):
            rows = []
            with open(os.path.join(self.directory, fn), newline="") as f:
                for i, row in enumerate(csv.reader(f, delimiter=self.delimiter)):
                    if i < self.skip or not row:
                        continue
                    rows.append(row)
            yield rows


class ImageRecordReader(RecordReader):
    """Directory-per-class images → (pixels..., label) records.
    Reference: DataVec ImageRecordReader (labels from parent dir)."""

    def __init__(self, root: str, *, height: int, width: int,
                 channels: int = 3):
        self.root = root
        self.h, self.w, self.c = height, width, channels
        self.labels = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))

    def __iter__(self):
        from PIL import Image

        for li, label in enumerate(self.labels):
            d = os.path.join(self.root, label)
            for fn in sorted(os.listdir(d)):
                try:
                    img = Image.open(os.path.join(d, fn))
                except Exception:
                    continue
                img = img.convert("RGB" if self.c == 3 else "L")
                img = img.resize((self.w, self.h))
                arr = np.asarray(img, np.float32) / 255.0
                if self.c == 1:
                    arr = arr[..., None]
                yield [arr, li]


class RecordReaderDataSetIterator(DataSetIterator):
    """Reference: `datasets/datavec/RecordReaderDataSetIterator.java` —
    records → (features, one-hot labels) DataSet batches."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False, collect_meta: bool = False):
        self.reader = reader
        self.bs = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        # reference: RecordReaderDataSetIterator.setCollectMetaData(true) —
        # each batch then exposes per-example RecordMetaData via
        # `last_meta` for Evaluation.eval(..., record_meta=...)
        self.collect_meta = collect_meta
        self.last_meta: Optional[list] = None
        self._record_index = 0
        self._it: Optional[Iterator] = None

    def set_collect_meta_data(self, v: bool) -> None:
        """Reference: setCollectMetaData."""
        self.collect_meta = v

    def reset(self):
        self._it = iter(self.reader)
        self._record_index = 0

    def __next__(self) -> DataSet:
        if self._it is None:
            self.reset()
        feats, labs = [], []
        metas = [] if self.collect_meta else None
        if metas is not None:
            from deeplearning4j_tpu.eval.meta import RecordMetaData

            src = str(getattr(self.reader, "path",
                              type(self.reader).__name__))
        for _ in range(self.bs):
            try:
                rec = next(self._it)
            except StopIteration:
                break
            if metas is not None:
                metas.append(RecordMetaData(src, self._record_index))
            self._record_index += 1
            if isinstance(rec[0], np.ndarray):  # image record
                feats.append(rec[0])
                labs.append(rec[1])
            else:
                vals = [float(v) for v in rec]
                li = self.label_index if self.label_index >= 0 \
                    else len(vals) - 1
                labs.append(vals[li])
                feats.append(vals[:li] + vals[li + 1:])
        if not feats:
            self._it = None
            raise StopIteration
        self.last_meta = metas
        x = np.asarray(feats, np.float32)
        if self.regression:
            y = np.asarray(labs, np.float32).reshape(len(labs), -1)
        else:
            idx = np.asarray(labs, np.int64)
            n = self.num_classes or int(idx.max()) + 1
            y = np.eye(n, dtype=np.float32)[idx]
        return DataSet(x, y)

    @property
    def batch_size(self):
        return self.bs


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Reference: SequenceRecordReaderDataSetIterator — per-sequence CSVs →
    padded [batch, time, features] with per-timestep masks."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self.bs = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self._it = None

    def reset(self):
        self._it = iter(self.reader)

    def __next__(self) -> DataSet:
        if self._it is None:
            self.reset()
        seqs = []
        for _ in range(self.bs):
            try:
                seqs.append(next(self._it))
            except StopIteration:
                break
        if not seqs:
            self._it = None
            raise StopIteration
        T = max(len(s) for s in seqs)
        first = seqs[0][0]
        li = self.label_index if self.label_index >= 0 else len(first) - 1
        F = len(first) - 1
        B = len(seqs)
        x = np.zeros((B, T, F), np.float32)
        mask = np.zeros((B, T), np.float32)
        lab_raw = np.zeros((B, T), np.float32)
        for b, s in enumerate(seqs):
            for t, row in enumerate(s):
                vals = [float(v) for v in row]
                lab_raw[b, t] = vals[li]
                x[b, t] = vals[:li] + vals[li + 1:]
                mask[b, t] = 1.0
        if self.regression:
            y = lab_raw[..., None]
        else:
            n = self.num_classes or int(lab_raw.max()) + 1
            y = np.eye(n, dtype=np.float32)[lab_raw.astype(np.int64)]
            y = y * mask[..., None]
        return DataSet(x, y, mask, mask)

    @property
    def batch_size(self):
        return self.bs
