"""Streaming ingestion — dl4j-streaming parity (Kafka/Camel NDArray routes).

Reference parity: `dl4j-streaming/` (SURVEY §2.4) — `NDArrayConsumer` /
`NDArrayPublisher` move serialized NDArrays through Kafka topics
(`streaming/kafka/NDArrayPubSubRoute.java`), `conversion/` turns DataVec
records into NDArrays, and tests run against an in-JVM
`EmbeddedKafkaCluster` (SURVEY §4 "embedded-infra fixtures").

TPU-native redesign: the transport is an SPI (`Broker`). The default
`InMemoryBroker` is the embedded-cluster equivalent (and the right tool for
single-host pipelines: a lock-free-enough queue per topic). A Kafka broker
can be slotted in where the environment provides `kafka-python`; the codec
and iterator layers are transport-agnostic. The consumer side terminates in
`StreamingDataSetIterator`, a standard DataSetIterator that a training loop
can drink from while a producer publishes concurrently — the host-side
analogue of the reference's Camel route into Spark Streaming.
"""

from __future__ import annotations

import io
import queue
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


# ------------------------------------------------------------------ codec
def ndarray_to_bytes(arr: np.ndarray) -> bytes:
    """Serialize one ndarray (reference: NDArrayMessage binary format —
    ours is the npy container: self-describing dtype + shape)."""
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return buf.getvalue()


def bytes_to_ndarray(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


def record_to_ndarray(record: Sequence) -> np.ndarray:
    """DataVec-record → ndarray (reference: `conversion/` writable lists)."""
    return np.asarray([float(v) for v in record], np.float32)


# ------------------------------------------------------------------ broker
class Broker:
    """Transport SPI: named topics carrying opaque byte messages."""

    def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def poll(self, topic: str, timeout: float) -> Optional[bytes]:
        raise NotImplementedError


class InMemoryBroker(Broker):
    """Embedded single-process broker (the EmbeddedKafkaCluster analogue)."""

    def __init__(self, max_queue: int = 1024):
        self._topics: Dict[str, queue.Queue] = {}
        self._lock = threading.Lock()
        self._max = max_queue

    def _q(self, topic: str) -> queue.Queue:
        with self._lock:
            if topic not in self._topics:
                self._topics[topic] = queue.Queue(self._max)
            return self._topics[topic]

    def publish(self, topic: str, payload: bytes) -> None:
        self._q(topic).put(payload)

    def poll(self, topic: str, timeout: float) -> Optional[bytes]:
        try:
            return self._q(topic).get(timeout=timeout)
        except queue.Empty:
            return None


class KafkaBroker(Broker):
    """Kafka transport — gated on kafka-python being installed (it is not
    part of the baked image; construct raises ImportError otherwise)."""

    def __init__(self, bootstrap_servers: str):
        try:
            from kafka import KafkaConsumer, KafkaProducer  # noqa: F401
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError(
                "KafkaBroker requires the kafka-python package") from e
        from kafka import KafkaProducer

        self._servers = bootstrap_servers
        self._producer = KafkaProducer(bootstrap_servers=bootstrap_servers)
        self._consumers: Dict[str, object] = {}

    def publish(self, topic, payload):  # pragma: no cover - env-dependent
        self._producer.send(topic, payload)

    def poll(self, topic, timeout):  # pragma: no cover - env-dependent
        from kafka import KafkaConsumer

        if topic not in self._consumers:
            self._consumers[topic] = KafkaConsumer(
                topic, bootstrap_servers=self._servers,
                consumer_timeout_ms=int(timeout * 1000))
        for msg in self._consumers[topic]:
            return msg.value
        return None


# ------------------------------------------------------------ pub/sub ends
class NDArrayPublisher:
    """Reference: `streaming/kafka/NDArrayPublisher` — push arrays to a
    topic."""

    def __init__(self, broker: Broker, topic: str):
        self.broker = broker
        self.topic = topic

    def publish(self, arr: np.ndarray) -> None:
        self.broker.publish(self.topic, ndarray_to_bytes(arr))

    def publish_record(self, record: Sequence) -> None:
        self.publish(record_to_ndarray(record))


class NDArrayConsumer:
    """Reference: `streaming/kafka/NDArrayConsumer.java` — pull arrays."""

    def __init__(self, broker: Broker, topic: str, timeout: float = 5.0):
        self.broker = broker
        self.topic = topic
        self.timeout = timeout

    def get(self) -> Optional[np.ndarray]:
        payload = self.broker.poll(self.topic, self.timeout)
        return None if payload is None else bytes_to_ndarray(payload)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            arr = self.get()
            if arr is None:
                return
            yield arr


class StreamingDataSetIterator(DataSetIterator):
    """Drain (features, labels) array pairs from two topics into DataSets.

    The training-loop end of the route (reference: the Camel route feeding
    `pipeline/spark/`): blocks up to `timeout` per batch; a None/timeout
    ends the epoch, so `fit` completes when the stream goes quiet."""

    def __init__(self, broker: Broker, *, features_topic: str,
                 labels_topic: str, batch_size: int = 32,
                 timeout: float = 2.0):
        self._consumer_x = NDArrayConsumer(broker, features_topic, timeout)
        self._consumer_y = NDArrayConsumer(broker, labels_topic, timeout)
        self._batch = batch_size
        # A feature whose label hasn't arrived yet is parked here, NOT
        # dropped — dropping would permanently desync the two topics.
        self._pending_x: Optional[np.ndarray] = None

    @property
    def batch_size(self):
        return self._batch

    def reset(self):  # a stream has no rewind
        pass

    def __iter__(self):
        return self

    def __next__(self) -> DataSet:
        xs: List[np.ndarray] = []
        ys: List[np.ndarray] = []
        while len(xs) < self.batch_size:
            if self._pending_x is not None:
                x, self._pending_x = self._pending_x, None
            else:
                x = self._consumer_x.get()
                if x is None:
                    break
            y = self._consumer_y.get()
            if y is None:
                self._pending_x = x  # keep pairing intact for the next batch
                break
            xs.append(x)
            ys.append(y)
        if not xs:
            raise StopIteration
        return DataSet(np.stack(xs), np.stack(ys))
