"""DataSet iterators: base protocol, array-backed, async prefetch, decorators.

Reference parity: `DataSetIterator` (ND4J iface) + dl4j-nn
`datasets/iterator/`: `AsyncDataSetIterator.java:30-68` (background thread +
LinkedBlockingQueue — here a Python thread + queue feeding the device while
TPU computes), `MultipleEpochsIterator`, `EarlyTerminationDataSetIterator`,
`BenchmarkDataSetIterator` (synthetic fixed batches for throughput
measurement).

The async iterator is the host↔device overlap seam: JAX dispatch is already
asynchronous, so the thread only needs to hide HOST-side ETL (decode,
augmentation, numpy collation), exactly the role the reference gives it.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.observe import get_registry


class DataSetIterator:
    """Base protocol. Mirrors the reference DataSetIterator (hasNext/next/
    reset/batch/totalOutcomes) as a Python iterable with reset()."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        raise StopIteration

    def reset(self) -> None:
        pass

    @property
    def batch_size(self) -> Optional[int]:
        return None

    @property
    def num_outcomes(self) -> Optional[int]:
        return None

    def async_(self, prefetch: int = 2) -> "AsyncDataSetIterator":
        return AsyncDataSetIterator(self, prefetch)


class ArrayDataSetIterator(DataSetIterator):
    """Batches over in-memory arrays (the workhorse for tests + canned data)."""

    def __init__(self, features, labels=None, batch_size: int = 32,
                 features_mask=None, labels_mask=None,
                 shuffle: bool = False, seed: int = 0, drop_last: bool = False):
        self._data = DataSet(
            np.asarray(features),
            None if labels is None else np.asarray(labels),
            features_mask, labels_mask,
        )
        self._bs = batch_size
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._drop_last = drop_last
        self._pos = 0
        self._cur = self._data

    def reset(self):
        self._pos = 0
        if self._shuffle:
            self._cur = self._data.shuffle(self._seed + self._epoch)
            self._epoch += 1

    def __next__(self) -> DataSet:
        n = self._cur.num_examples()
        if self._pos >= n:
            raise StopIteration
        hi = min(self._pos + self._bs, n)
        if self._drop_last and hi - self._pos < self._bs:
            raise StopIteration
        sl = lambda a: None if a is None else a[self._pos:hi]
        d = DataSet(self._cur.features[self._pos:hi], sl(self._cur.labels),
                    sl(self._cur.features_mask), sl(self._cur.labels_mask))
        self._pos = hi
        return d

    @property
    def batch_size(self):
        return self._bs

    @property
    def num_outcomes(self):
        if self._data.labels is not None and self._data.labels.ndim >= 2:
            return int(self._data.labels.shape[-1])
        return None


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch. Reference:
    `datasets/iterator/AsyncDataSetIterator.java:30-68`."""

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, prefetch: int = 2):
        self._base = base
        self._prefetch = prefetch
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._stop: Optional[threading.Event] = None
        reg = get_registry()
        self._m_batches = reg.counter("etl_batches_total", stage="async")
        self._m_hits = reg.counter("prefetch_hits_total", stage="async")
        self._m_misses = reg.counter("prefetch_misses_total", stage="async")
        self._m_depth = reg.gauge("prefetch_queue_depth", stage="async")

    def _pump(self, q: queue.Queue, stop: threading.Event):
        try:
            for d in self._base:
                # Bounded put that aborts when a reset() orphaned this thread,
                # so abandoned pumps don't block forever holding batches.
                while not stop.is_set():
                    try:
                        q.put(d, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # surfaced on the consumer side
            self._error = e
        finally:
            # The sentinel MUST reach the consumer (a dropped sentinel hangs
            # the consumer) — block with the same stop-aware loop.
            while not stop.is_set():
                try:
                    q.put(self._SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def reset(self):
        if self._stop is not None:
            self._stop.set()
        self._queue = queue.Queue(maxsize=self._prefetch + 1)  # +1: sentinel
        self._error = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump, args=(self._queue, self._stop), daemon=True)
        self._thread.start()

    def __next__(self) -> DataSet:
        if self._queue is None:
            self.reset()
        if self._error is not None:
            # Fail fast: don't hand out already-buffered batches once the
            # pump has died — the consumer would train on a silently
            # truncated epoch before seeing the error.
            err, self._error = self._error, None
            self.close()
            raise err
        # qsize() before the get: non-empty means the pump stayed ahead
        # of the consumer (a prefetch hit); empty means this step waited
        # on host ETL. Advisory but cheap — the ratio is the signal.
        depth = self._queue.qsize()
        self._m_depth.set(depth)
        item = self._queue.get()
        if item is self._SENTINEL:
            self._queue = None
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            raise StopIteration
        (self._m_hits if depth > 0 else self._m_misses).inc()
        self._m_batches.inc()
        return item

    def close(self) -> None:
        """Stop the pump and join the worker thread. Safe to call twice;
        called automatically when used as a context manager."""
        if self._stop is not None:
            self._stop.set()
        q, t = self._queue, self._thread
        if q is not None:
            # Drain so a pump blocked on a full queue observes the stop
            # event and exits promptly.
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:  # graft: allow(GL403): drain-until-empty
                pass
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self._queue = None
        self._thread = None

    def __enter__(self) -> "AsyncDataSetIterator":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    @property
    def batch_size(self):
        return self._base.batch_size

    @property
    def num_outcomes(self):
        return self._base.num_outcomes


class MultipleEpochsIterator(DataSetIterator):
    """Repeat a base iterator N times. Reference: MultipleEpochsIterator."""

    def __init__(self, base: DataSetIterator, epochs: int):
        self._base = base
        self._epochs = epochs
        self._epoch = 0
        self._inner: Optional[Iterator] = None

    def reset(self):
        self._epoch = 0
        self._inner = iter(self._base)

    def __next__(self) -> DataSet:
        if self._inner is None:
            self.reset()
        while True:
            try:
                return next(self._inner)
            except StopIteration:
                self._epoch += 1
                if self._epoch >= self._epochs:
                    raise
                self._inner = iter(self._base)


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Cap the number of minibatches. Reference: EarlyTerminationDataSetIterator."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self._base = base
        self._max = max_batches
        self._count = 0
        self._inner: Optional[Iterator] = None

    def reset(self):
        self._count = 0
        self._inner = iter(self._base)

    def __next__(self) -> DataSet:
        if self._inner is None:
            self.reset()
        if self._count >= self._max:
            raise StopIteration
        self._count += 1
        return next(self._inner)


class BenchmarkDataSetIterator(DataSetIterator):
    """Synthetic fixed batches for throughput measurement. Reference:
    `datasets/iterator/impl/BenchmarkDataSetIterator.java`."""

    def __init__(self, feature_shape, num_classes: int, num_batches: int,
                 seed: int = 0, label_shape=None):
        rng = np.random.default_rng(seed)
        self._features = rng.standard_normal(feature_shape, dtype=np.float32)
        b = feature_shape[0]
        if label_shape is None:
            labels = np.zeros((b, num_classes), dtype=np.float32)
            labels[np.arange(b), rng.integers(0, num_classes, b)] = 1.0
        else:
            labels = rng.standard_normal(label_shape).astype(np.float32)
        self._labels = labels
        self._n = num_batches
        self._i = 0

    def reset(self):
        self._i = 0

    def __next__(self) -> DataSet:
        if self._i >= self._n:
            raise StopIteration
        self._i += 1
        return DataSet(self._features, self._labels)

    @property
    def batch_size(self):
        return int(self._features.shape[0])

    @property
    def num_outcomes(self):
        return int(self._labels.shape[-1])


class IterableDataSetIterator(DataSetIterator):
    """Adapt any Python iterable of pre-built DataSet/MultiDataSet batches
    (list, generator, custom loader) to the DataSetIterator protocol.

    Re-iterables (lists, custom __iter__ objects) get a fresh ``iter()``
    every reset, so multi-epoch ``fit(..., epochs=N)`` replays each epoch.
    One-shot iterators/generators are replay-cached: batches seen in the
    first pass are recorded and replayed on subsequent resets (the
    generator itself can only be consumed once)."""

    def __init__(self, source):
        self._replay = isinstance(source, Iterator)
        self._source = iter(source) if self._replay else source
        self._cache: List = []
        self._first_pass = True
        self._inner: Optional[Iterator] = None

    def reset(self):
        if self._replay:
            if self._first_pass:
                self._inner = self._source
            else:
                self._inner = iter(self._cache)
        else:
            self._inner = iter(self._source)

    def __next__(self):
        if self._inner is None:
            self.reset()
        try:
            item = next(self._inner)
        except StopIteration:
            if self._replay and self._first_pass:
                self._first_pass = False
            raise
        if self._replay and self._first_pass:
            self._cache.append(item)
        return item


class DevicePrefetchIterator(DataSetIterator):
    """Overlap host→device transfer with compute: issue `jax.device_put`
    for batch N+1 while batch N's step is still executing.

    `device_put` merely ENQUEUES the transfer (JAX dispatch is async), so
    no thread is needed — this iterator just runs ``depth`` batches ahead
    of the consumer, double-buffered by default. Composes with
    `AsyncDataSetIterator` underneath (thread hides host ETL, this hides
    the H2D copy).

    ``put_fn(array) -> jax.Array`` defaults to the active sharding
    spine's batch placement (`parallel.mesh.current_mesh_context()`) when
    one is installed — each batch lands pre-sharded over the batch axis
    in ONE device_put — and to plain `jax.device_put` (single device)
    otherwise. The data-parallel trainer passes its spine's put
    explicitly. ``transform(ds) -> ds`` is a host-side hook applied
    before the put (e.g. padding to device-count divisible).
    """

    def __init__(self, base: DataSetIterator, depth: int = 2,
                 put_fn: Optional[Callable] = None,
                 transform: Optional[Callable] = None):
        self._base = base
        self._depth = max(1, int(depth))
        self._put_fn = put_fn
        self._transform = transform
        self._inner: Optional[Iterator] = None
        self._buf: List = []
        self._exhausted = False
        self._pending: Optional[BaseException] = None
        reg = get_registry()
        self._m_hits = reg.counter("prefetch_hits_total", stage="device")
        self._m_misses = reg.counter("prefetch_misses_total", stage="device")
        self._m_batches = reg.counter("etl_batches_total", stage="device")

    def _put(self, ds):
        import jax

        put = self._put_fn
        if put is None:
            # resolved per batch: the spine is active only for the
            # duration of the fit driving this iterator
            from deeplearning4j_tpu.parallel.mesh import (
                current_mesh_context,
            )
            ctx = current_mesh_context()
            put = ctx.put_batch if ctx is not None else jax.device_put
        if self._transform is not None:
            ds = self._transform(ds)
        if hasattr(ds, "features_masks"):   # MultiDataSet
            cls = type(ds)
            pl = lambda xs: None if xs is None else type(xs)(
                None if x is None else put(x) for x in xs)
            return cls(pl(ds.features), pl(ds.labels),
                       pl(ds.features_masks), pl(ds.labels_masks))
        p = lambda a: None if a is None else put(a)
        return DataSet(p(ds.features), p(ds.labels),
                       p(ds.features_mask), p(ds.labels_mask))

    def _fill(self):
        while (not self._exhausted and self._pending is None
               and len(self._buf) < self._depth):
            try:
                self._buf.append(self._put(next(self._inner)))
            except StopIteration:
                self._exhausted = True
            except BaseException as e:
                # a failed AHEAD fetch must not poison the batch already
                # in hand: hold the error until the consumer actually
                # reaches the failed position (exact-resume cursors and
                # checkpoints then reflect every batch that trained)
                self._pending = e

    def reset(self):
        self._inner = iter(self._base)
        self._buf = []
        self._exhausted = False
        self._pending = None

    def __next__(self):
        if self._inner is None:
            self.reset()
        # a batch already buffered = its device_put was enqueued while the
        # consumer computed (hit); an empty buffer = this step pays the
        # host-side fetch+put latency in line (miss)
        ready = bool(self._buf)
        self._fill()
        if not self._buf:
            if self._pending is not None:
                e, self._pending = self._pending, None
                raise e
            raise StopIteration
        (self._m_hits if ready else self._m_misses).inc()
        self._m_batches.inc()
        item = self._buf.pop(0)
        self._fill()    # immediately enqueue the replacement transfer
        return item

    @property
    def batch_size(self):
        return self._base.batch_size

    @property
    def num_outcomes(self):
        return self._base.num_outcomes


def as_iterator(data, labels=None, batch_size: int = 32) -> DataSetIterator:
    """Coerce arrays / DataSet / iterables of DataSets / iterator into a
    DataSetIterator."""
    if isinstance(data, DataSetIterator):
        return data
    if isinstance(data, DataSet):
        return ArrayDataSetIterator(
            data.features, data.labels, batch_size,
            data.features_mask, data.labels_mask,
        )
    if labels is None and _is_dataset_iterable(data):
        return IterableDataSetIterator(data)
    return ArrayDataSetIterator(data, labels, batch_size)


def _is_dataset_iterable(data) -> bool:
    """True for generators/iterators, and for non-array iterables whose
    first element is a DataSet-like batch (has .features)."""
    if isinstance(data, Iterator):
        return True
    if isinstance(data, np.ndarray) or hasattr(data, "shape"):
        return False
    if isinstance(data, (list, tuple)):
        return bool(data) and hasattr(data[0], "features")
    # custom iterable wrappers (loaders, the chaos injectors in
    # parallel/chaos.py) satisfy "any iterable of DataSets" too — anything
    # non-array that can produce an iterator is a batch source
    return hasattr(data, "__iter__")


class FileSplitDataSetIterator(DataSetIterator):
    """One pre-saved DataSet file per step. Reference:
    `datasets/iterator/FileSplitDataSetIterator.java` (file list + load
    callback) / `ExistingMiniBatchDataSetIterator` — the executor side of
    Spark's fitPaths (`SparkDl4jMultiLayer.java:259`): minibatches are
    materialized to storage once, then any number of training runs
    stream them back. `files`: an iterable of paths or a directory
    (sorted *.npz); `loader` defaults to DataSet.load."""

    def __init__(self, files, loader=None):
        if isinstance(files, (str, os.PathLike)):
            d = os.fspath(files)
            self.files = [
                os.path.join(d, n) for n in sorted(os.listdir(d))
                if n.endswith(".npz")]
        else:
            self.files = [os.fspath(f) for f in files]
        if not self.files:
            raise ValueError("FileSplitDataSetIterator: no files")
        self.loader = loader or DataSet.load
        self._i = 0

    def reset(self):
        self._i = 0

    def __next__(self):
        if self._i >= len(self.files):
            raise StopIteration    # stays exhausted; __iter__ resets
        ds = self.loader(self.files[self._i])
        self._i += 1
        return ds
