"""Canned datasets: MNIST, CIFAR-10, Iris.

Reference parity: deeplearning4j-core `datasets/iterator/impl/`
(MnistDataSetIterator, CifarDataSetIterator, IrisDataSetIterator) and the
binary fetchers in `datasets/mnist/`. The reference downloads on first use;
this environment is zero-egress, so loaders read the standard cache layout
(`~/.deeplearning4j_tpu/<name>/` or $DL4J_TPU_DATA_DIR) and otherwise fall
back to a DETERMINISTIC synthetic surrogate with identical shapes/dtypes,
clearly flagged via `.synthetic` so tests/benches know.

Iris ships embedded (150 rows, public-domain Fisher data).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator


def data_dir() -> str:
    return os.environ.get(
        "DL4J_TPU_DATA_DIR",
        os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu"))


# --------------------------------------------------------------------- MNIST
def _read_idx_images(path: str) -> np.ndarray:
    from deeplearning4j_tpu import native
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        arr = native.read_idx(f.read())
    assert arr.ndim == 3, f"bad idx image rank {arr.ndim}"
    return arr


def _read_idx_labels(path: str) -> np.ndarray:
    from deeplearning4j_tpu import native
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        arr = native.read_idx(f.read())
    assert arr.ndim == 1, f"bad idx label rank {arr.ndim}"
    return arr


def _find(name_options, base) -> Optional[str]:
    for n in name_options:
        for ext in ("", ".gz"):
            p = os.path.join(base, n + ext)
            if os.path.exists(p):
                return p
    return None


def load_mnist(train: bool = True) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Returns (images [N,784] float32 in [0,1], labels one-hot [N,10],
    synthetic_flag)."""
    base = os.path.join(data_dir(), "mnist")
    prefix = "train" if train else "t10k"
    img = _find([f"{prefix}-images-idx3-ubyte", f"{prefix}-images.idx3-ubyte"], base)
    lab = _find([f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels.idx1-ubyte"], base)
    if img and lab:
        from deeplearning4j_tpu import native
        x = native.u8_to_f32(_read_idx_images(img)).reshape(-1, 784)
        y = native.one_hot(_read_idx_labels(lab), 10)
        return x, y, False
    # Deterministic synthetic surrogate: 10 gaussian digit prototypes.
    n = 60000 if train else 10000
    rng = np.random.default_rng(42 if train else 43)
    protos = np.random.default_rng(7).random((10, 784)).astype(np.float32)
    labels = rng.integers(0, 10, n)
    x = 0.6 * protos[labels] + 0.4 * rng.random((n, 784), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[labels]
    return x.astype(np.float32), y, True


class MnistDataSetIterator(ArrayDataSetIterator):
    """Reference: `datasets/iterator/impl/MnistDataSetIterator`."""

    def __init__(self, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None):
        x, y, synthetic = load_mnist(train)
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        self.synthetic = synthetic
        super().__init__(x, y, batch_size, shuffle=shuffle, seed=seed)


# -------------------------------------------------------------------- CIFAR
def load_cifar10(train: bool = True) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Returns (images [N,32,32,3] float32, one-hot labels [N,10], synthetic)."""
    base = os.path.join(data_dir(), "cifar-10-batches-bin")
    files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    paths = [os.path.join(base, f) for f in files]
    if all(os.path.exists(p) for p in paths):
        xs, ys = [], []
        for p in paths:
            raw = np.fromfile(p, np.uint8).reshape(-1, 3073)
            ys.append(raw[:, 0])
            xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        x = np.concatenate(xs).astype(np.float32) / 255.0
        y = np.eye(10, dtype=np.float32)[np.concatenate(ys)]
        return x, y, False
    n = 50000 if train else 10000
    rng = np.random.default_rng(44 if train else 45)
    protos = np.random.default_rng(8).random((10, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, n)
    x = 0.6 * protos[labels] + 0.4 * rng.random((n, 32, 32, 3), dtype=np.float32)
    return x.astype(np.float32), np.eye(10, dtype=np.float32)[labels], True


class CifarDataSetIterator(ArrayDataSetIterator):
    """Reference: `datasets/iterator/impl/CifarDataSetIterator`."""

    def __init__(self, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None):
        x, y, synthetic = load_cifar10(train)
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        self.synthetic = synthetic
        super().__init__(x, y, batch_size, shuffle=shuffle, seed=seed)


# --------------------------------------------------------------------- Iris
# Fisher's iris data (public domain): 150 rows of
# sepal_len, sepal_wid, petal_len, petal_wid, class(0..2)
_IRIS = np.array([
    [5.1,3.5,1.4,0.2,0],[4.9,3.0,1.4,0.2,0],[4.7,3.2,1.3,0.2,0],[4.6,3.1,1.5,0.2,0],
    [5.0,3.6,1.4,0.2,0],[5.4,3.9,1.7,0.4,0],[4.6,3.4,1.4,0.3,0],[5.0,3.4,1.5,0.2,0],
    [4.4,2.9,1.4,0.2,0],[4.9,3.1,1.5,0.1,0],[5.4,3.7,1.5,0.2,0],[4.8,3.4,1.6,0.2,0],
    [4.8,3.0,1.4,0.1,0],[4.3,3.0,1.1,0.1,0],[5.8,4.0,1.2,0.2,0],[5.7,4.4,1.5,0.4,0],
    [5.4,3.9,1.3,0.4,0],[5.1,3.5,1.4,0.3,0],[5.7,3.8,1.7,0.3,0],[5.1,3.8,1.5,0.3,0],
    [5.4,3.4,1.7,0.2,0],[5.1,3.7,1.5,0.4,0],[4.6,3.6,1.0,0.2,0],[5.1,3.3,1.7,0.5,0],
    [4.8,3.4,1.9,0.2,0],[5.0,3.0,1.6,0.2,0],[5.0,3.4,1.6,0.4,0],[5.2,3.5,1.5,0.2,0],
    [5.2,3.4,1.4,0.2,0],[4.7,3.2,1.6,0.2,0],[4.8,3.1,1.6,0.2,0],[5.4,3.4,1.5,0.4,0],
    [5.2,4.1,1.5,0.1,0],[5.5,4.2,1.4,0.2,0],[4.9,3.1,1.5,0.2,0],[5.0,3.2,1.2,0.2,0],
    [5.5,3.5,1.3,0.2,0],[4.9,3.6,1.4,0.1,0],[4.4,3.0,1.3,0.2,0],[5.1,3.4,1.5,0.2,0],
    [5.0,3.5,1.3,0.3,0],[4.5,2.3,1.3,0.3,0],[4.4,3.2,1.3,0.2,0],[5.0,3.5,1.6,0.6,0],
    [5.1,3.8,1.9,0.4,0],[4.8,3.0,1.4,0.3,0],[5.1,3.8,1.6,0.2,0],[4.6,3.2,1.4,0.2,0],
    [5.3,3.7,1.5,0.2,0],[5.0,3.3,1.4,0.2,0],
    [7.0,3.2,4.7,1.4,1],[6.4,3.2,4.5,1.5,1],[6.9,3.1,4.9,1.5,1],[5.5,2.3,4.0,1.3,1],
    [6.5,2.8,4.6,1.5,1],[5.7,2.8,4.5,1.3,1],[6.3,3.3,4.7,1.6,1],[4.9,2.4,3.3,1.0,1],
    [6.6,2.9,4.6,1.3,1],[5.2,2.7,3.9,1.4,1],[5.0,2.0,3.5,1.0,1],[5.9,3.0,4.2,1.5,1],
    [6.0,2.2,4.0,1.0,1],[6.1,2.9,4.7,1.4,1],[5.6,2.9,3.6,1.3,1],[6.7,3.1,4.4,1.4,1],
    [5.6,3.0,4.5,1.5,1],[5.8,2.7,4.1,1.0,1],[6.2,2.2,4.5,1.5,1],[5.6,2.5,3.9,1.1,1],
    [5.9,3.2,4.8,1.8,1],[6.1,2.8,4.0,1.3,1],[6.3,2.5,4.9,1.5,1],[6.1,2.8,4.7,1.2,1],
    [6.4,2.9,4.3,1.3,1],[6.6,3.0,4.4,1.4,1],[6.8,2.8,4.8,1.4,1],[6.7,3.0,5.0,1.7,1],
    [6.0,2.9,4.5,1.5,1],[5.7,2.6,3.5,1.0,1],[5.5,2.4,3.8,1.1,1],[5.5,2.4,3.7,1.0,1],
    [5.8,2.7,3.9,1.2,1],[6.0,2.7,5.1,1.6,1],[5.4,3.0,4.5,1.5,1],[6.0,3.4,4.5,1.6,1],
    [6.7,3.1,4.7,1.5,1],[6.3,2.3,4.4,1.3,1],[5.6,3.0,4.1,1.3,1],[5.5,2.5,4.0,1.3,1],
    [5.5,2.6,4.4,1.2,1],[6.1,3.0,4.6,1.4,1],[5.8,2.6,4.0,1.2,1],[5.0,2.3,3.3,1.0,1],
    [5.6,2.7,4.2,1.3,1],[5.7,3.0,4.2,1.2,1],[5.7,2.9,4.2,1.3,1],[6.2,2.9,4.3,1.3,1],
    [5.1,2.5,3.0,1.1,1],[5.7,2.8,4.1,1.3,1],
    [6.3,3.3,6.0,2.5,2],[5.8,2.7,5.1,1.9,2],[7.1,3.0,5.9,2.1,2],[6.3,2.9,5.6,1.8,2],
    [6.5,3.0,5.8,2.2,2],[7.6,3.0,6.6,2.1,2],[4.9,2.5,4.5,1.7,2],[7.3,2.9,6.3,1.8,2],
    [6.7,2.5,5.8,1.8,2],[7.2,3.6,6.1,2.5,2],[6.5,3.2,5.1,2.0,2],[6.4,2.7,5.3,1.9,2],
    [6.8,3.0,5.5,2.1,2],[5.7,2.5,5.0,2.0,2],[5.8,2.8,5.1,2.4,2],[6.4,3.2,5.3,2.3,2],
    [6.5,3.0,5.5,1.8,2],[7.7,3.8,6.7,2.2,2],[7.7,2.6,6.9,2.3,2],[6.0,2.2,5.0,1.5,2],
    [6.9,3.2,5.7,2.3,2],[5.6,2.8,4.9,2.0,2],[7.7,2.8,6.7,2.0,2],[6.3,2.7,4.9,1.8,2],
    [6.7,3.3,5.7,2.1,2],[7.2,3.2,6.0,1.8,2],[6.2,2.8,4.8,1.8,2],[6.1,3.0,4.9,1.8,2],
    [6.4,2.8,5.6,2.1,2],[7.2,3.0,5.8,1.6,2],[7.4,2.8,6.1,1.9,2],[7.9,3.8,6.4,2.0,2],
    [6.4,2.8,5.6,2.2,2],[6.3,2.8,5.1,1.5,2],[6.1,2.6,5.6,1.4,2],[7.7,3.0,6.1,2.3,2],
    [6.3,3.4,5.6,2.4,2],[6.4,3.1,5.5,1.8,2],[6.0,3.0,4.8,1.8,2],[6.9,3.1,5.4,2.1,2],
    [6.7,3.1,5.6,2.4,2],[6.9,3.1,5.1,2.3,2],[5.8,2.7,5.1,1.9,2],[6.8,3.2,5.9,2.3,2],
    [6.7,3.3,5.7,2.5,2],[6.7,3.0,5.2,2.3,2],[6.3,2.5,5.0,1.9,2],[6.5,3.0,5.2,2.0,2],
    [6.2,3.4,5.4,2.3,2],[5.9,3.0,5.1,1.8,2],
], dtype=np.float32)


def load_iris() -> Tuple[np.ndarray, np.ndarray]:
    x = _IRIS[:, :4].copy()
    y = np.eye(3, dtype=np.float32)[_IRIS[:, 4].astype(int)]
    return x, y


class IrisDataSetIterator(ArrayDataSetIterator):
    """Reference: `datasets/iterator/impl/IrisDataSetIterator`."""

    def __init__(self, batch_size: int = 150, shuffle: bool = False,
                 seed: int = 123):
        x, y = load_iris()
        super().__init__(x, y, batch_size, shuffle=shuffle, seed=seed)
