"""Canned datasets: MNIST, CIFAR-10, Iris.

Reference parity: deeplearning4j-core `datasets/iterator/impl/`
(MnistDataSetIterator, CifarDataSetIterator, IrisDataSetIterator) and the
binary fetchers in `datasets/mnist/`. The reference downloads on first use;
this environment is zero-egress, so loaders read the standard cache layout
(`~/.deeplearning4j_tpu/<name>/` or $DL4J_TPU_DATA_DIR) and otherwise fall
back to a DETERMINISTIC synthetic surrogate with identical shapes/dtypes,
clearly flagged via `.synthetic` so tests/benches know.

Iris ships embedded (150 rows, public-domain Fisher data).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator


def data_dir() -> str:
    return os.environ.get(
        "DL4J_TPU_DATA_DIR",
        os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu"))


def _synthetic_surrogate(n: int, k: int, shape: Tuple[int, ...],
                         proto_seed: int, sample_seed: int,
                         blend: float = 0.6
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic stand-in when real files are absent (zero-egress):
    k class prototypes blended with per-example noise — separable enough
    to train on, identical shapes/dtypes to the real data."""
    rng = np.random.default_rng(sample_seed)
    protos = np.random.default_rng(proto_seed).random(
        (k,) + shape).astype(np.float32)
    labels = rng.integers(0, k, n)
    x = (blend * protos[labels]
         + (1.0 - blend) * rng.random((n,) + shape, dtype=np.float32))
    return x.astype(np.float32), np.eye(k, dtype=np.float32)[labels]


# --------------------------------------------------------------------- MNIST
def _read_idx_images(path: str) -> np.ndarray:
    from deeplearning4j_tpu import native
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        arr = native.read_idx(f.read())
    assert arr.ndim == 3, f"bad idx image rank {arr.ndim}"
    return arr


def _read_idx_labels(path: str) -> np.ndarray:
    from deeplearning4j_tpu import native
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        arr = native.read_idx(f.read())
    assert arr.ndim == 1, f"bad idx label rank {arr.ndim}"
    return arr


def _find(name_options, base) -> Optional[str]:
    for n in name_options:
        for ext in ("", ".gz"):
            p = os.path.join(base, n + ext)
            if os.path.exists(p):
                return p
    return None


def load_mnist(train: bool = True) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Returns (images [N,784] float32 in [0,1], labels one-hot [N,10],
    synthetic_flag)."""
    base = os.path.join(data_dir(), "mnist")
    prefix = "train" if train else "t10k"
    img = _find([f"{prefix}-images-idx3-ubyte", f"{prefix}-images.idx3-ubyte"], base)
    lab = _find([f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels.idx1-ubyte"], base)
    if img and lab:
        from deeplearning4j_tpu import native
        x = native.u8_to_f32(_read_idx_images(img)).reshape(-1, 784)
        y = native.one_hot(_read_idx_labels(lab), 10)
        return x, y, False
    x, y = _synthetic_surrogate(60000 if train else 10000, 10, (784,),
                                proto_seed=7,
                                sample_seed=42 if train else 43)
    return x, y, True


class MnistDataSetIterator(ArrayDataSetIterator):
    """Reference: `datasets/iterator/impl/MnistDataSetIterator`."""

    def __init__(self, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None):
        x, y, synthetic = load_mnist(train)
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        self.synthetic = synthetic
        super().__init__(x, y, batch_size, shuffle=shuffle, seed=seed)


# -------------------------------------------------------------------- CIFAR
def load_cifar10(train: bool = True) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Returns (images [N,32,32,3] float32, one-hot labels [N,10], synthetic)."""
    base = os.path.join(data_dir(), "cifar-10-batches-bin")
    files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    paths = [os.path.join(base, f) for f in files]
    if all(os.path.exists(p) for p in paths):
        xs, ys = [], []
        for p in paths:
            raw = np.fromfile(p, np.uint8).reshape(-1, 3073)
            ys.append(raw[:, 0])
            xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        x = np.concatenate(xs).astype(np.float32) / 255.0
        y = np.eye(10, dtype=np.float32)[np.concatenate(ys)]
        return x, y, False
    x, y = _synthetic_surrogate(50000 if train else 10000, 10, (32, 32, 3),
                                proto_seed=8,
                                sample_seed=44 if train else 45)
    return x, y, True


class CifarDataSetIterator(ArrayDataSetIterator):
    """Reference: `datasets/iterator/impl/CifarDataSetIterator`."""

    def __init__(self, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None):
        x, y, synthetic = load_cifar10(train)
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        self.synthetic = synthetic
        super().__init__(x, y, batch_size, shuffle=shuffle, seed=seed)


# --------------------------------------------------------------------- Iris
# Fisher's iris data (public domain): 150 rows of
# sepal_len, sepal_wid, petal_len, petal_wid, class(0..2)
_IRIS = np.array([
    [5.1,3.5,1.4,0.2,0],[4.9,3.0,1.4,0.2,0],[4.7,3.2,1.3,0.2,0],[4.6,3.1,1.5,0.2,0],
    [5.0,3.6,1.4,0.2,0],[5.4,3.9,1.7,0.4,0],[4.6,3.4,1.4,0.3,0],[5.0,3.4,1.5,0.2,0],
    [4.4,2.9,1.4,0.2,0],[4.9,3.1,1.5,0.1,0],[5.4,3.7,1.5,0.2,0],[4.8,3.4,1.6,0.2,0],
    [4.8,3.0,1.4,0.1,0],[4.3,3.0,1.1,0.1,0],[5.8,4.0,1.2,0.2,0],[5.7,4.4,1.5,0.4,0],
    [5.4,3.9,1.3,0.4,0],[5.1,3.5,1.4,0.3,0],[5.7,3.8,1.7,0.3,0],[5.1,3.8,1.5,0.3,0],
    [5.4,3.4,1.7,0.2,0],[5.1,3.7,1.5,0.4,0],[4.6,3.6,1.0,0.2,0],[5.1,3.3,1.7,0.5,0],
    [4.8,3.4,1.9,0.2,0],[5.0,3.0,1.6,0.2,0],[5.0,3.4,1.6,0.4,0],[5.2,3.5,1.5,0.2,0],
    [5.2,3.4,1.4,0.2,0],[4.7,3.2,1.6,0.2,0],[4.8,3.1,1.6,0.2,0],[5.4,3.4,1.5,0.4,0],
    [5.2,4.1,1.5,0.1,0],[5.5,4.2,1.4,0.2,0],[4.9,3.1,1.5,0.2,0],[5.0,3.2,1.2,0.2,0],
    [5.5,3.5,1.3,0.2,0],[4.9,3.6,1.4,0.1,0],[4.4,3.0,1.3,0.2,0],[5.1,3.4,1.5,0.2,0],
    [5.0,3.5,1.3,0.3,0],[4.5,2.3,1.3,0.3,0],[4.4,3.2,1.3,0.2,0],[5.0,3.5,1.6,0.6,0],
    [5.1,3.8,1.9,0.4,0],[4.8,3.0,1.4,0.3,0],[5.1,3.8,1.6,0.2,0],[4.6,3.2,1.4,0.2,0],
    [5.3,3.7,1.5,0.2,0],[5.0,3.3,1.4,0.2,0],
    [7.0,3.2,4.7,1.4,1],[6.4,3.2,4.5,1.5,1],[6.9,3.1,4.9,1.5,1],[5.5,2.3,4.0,1.3,1],
    [6.5,2.8,4.6,1.5,1],[5.7,2.8,4.5,1.3,1],[6.3,3.3,4.7,1.6,1],[4.9,2.4,3.3,1.0,1],
    [6.6,2.9,4.6,1.3,1],[5.2,2.7,3.9,1.4,1],[5.0,2.0,3.5,1.0,1],[5.9,3.0,4.2,1.5,1],
    [6.0,2.2,4.0,1.0,1],[6.1,2.9,4.7,1.4,1],[5.6,2.9,3.6,1.3,1],[6.7,3.1,4.4,1.4,1],
    [5.6,3.0,4.5,1.5,1],[5.8,2.7,4.1,1.0,1],[6.2,2.2,4.5,1.5,1],[5.6,2.5,3.9,1.1,1],
    [5.9,3.2,4.8,1.8,1],[6.1,2.8,4.0,1.3,1],[6.3,2.5,4.9,1.5,1],[6.1,2.8,4.7,1.2,1],
    [6.4,2.9,4.3,1.3,1],[6.6,3.0,4.4,1.4,1],[6.8,2.8,4.8,1.4,1],[6.7,3.0,5.0,1.7,1],
    [6.0,2.9,4.5,1.5,1],[5.7,2.6,3.5,1.0,1],[5.5,2.4,3.8,1.1,1],[5.5,2.4,3.7,1.0,1],
    [5.8,2.7,3.9,1.2,1],[6.0,2.7,5.1,1.6,1],[5.4,3.0,4.5,1.5,1],[6.0,3.4,4.5,1.6,1],
    [6.7,3.1,4.7,1.5,1],[6.3,2.3,4.4,1.3,1],[5.6,3.0,4.1,1.3,1],[5.5,2.5,4.0,1.3,1],
    [5.5,2.6,4.4,1.2,1],[6.1,3.0,4.6,1.4,1],[5.8,2.6,4.0,1.2,1],[5.0,2.3,3.3,1.0,1],
    [5.6,2.7,4.2,1.3,1],[5.7,3.0,4.2,1.2,1],[5.7,2.9,4.2,1.3,1],[6.2,2.9,4.3,1.3,1],
    [5.1,2.5,3.0,1.1,1],[5.7,2.8,4.1,1.3,1],
    [6.3,3.3,6.0,2.5,2],[5.8,2.7,5.1,1.9,2],[7.1,3.0,5.9,2.1,2],[6.3,2.9,5.6,1.8,2],
    [6.5,3.0,5.8,2.2,2],[7.6,3.0,6.6,2.1,2],[4.9,2.5,4.5,1.7,2],[7.3,2.9,6.3,1.8,2],
    [6.7,2.5,5.8,1.8,2],[7.2,3.6,6.1,2.5,2],[6.5,3.2,5.1,2.0,2],[6.4,2.7,5.3,1.9,2],
    [6.8,3.0,5.5,2.1,2],[5.7,2.5,5.0,2.0,2],[5.8,2.8,5.1,2.4,2],[6.4,3.2,5.3,2.3,2],
    [6.5,3.0,5.5,1.8,2],[7.7,3.8,6.7,2.2,2],[7.7,2.6,6.9,2.3,2],[6.0,2.2,5.0,1.5,2],
    [6.9,3.2,5.7,2.3,2],[5.6,2.8,4.9,2.0,2],[7.7,2.8,6.7,2.0,2],[6.3,2.7,4.9,1.8,2],
    [6.7,3.3,5.7,2.1,2],[7.2,3.2,6.0,1.8,2],[6.2,2.8,4.8,1.8,2],[6.1,3.0,4.9,1.8,2],
    [6.4,2.8,5.6,2.1,2],[7.2,3.0,5.8,1.6,2],[7.4,2.8,6.1,1.9,2],[7.9,3.8,6.4,2.0,2],
    [6.4,2.8,5.6,2.2,2],[6.3,2.8,5.1,1.5,2],[6.1,2.6,5.6,1.4,2],[7.7,3.0,6.1,2.3,2],
    [6.3,3.4,5.6,2.4,2],[6.4,3.1,5.5,1.8,2],[6.0,3.0,4.8,1.8,2],[6.9,3.1,5.4,2.1,2],
    [6.7,3.1,5.6,2.4,2],[6.9,3.1,5.1,2.3,2],[5.8,2.7,5.1,1.9,2],[6.8,3.2,5.9,2.3,2],
    [6.7,3.3,5.7,2.5,2],[6.7,3.0,5.2,2.3,2],[6.3,2.5,5.0,1.9,2],[6.5,3.0,5.2,2.0,2],
    [6.2,3.4,5.4,2.3,2],[5.9,3.0,5.1,1.8,2],
], dtype=np.float32)


def load_iris() -> Tuple[np.ndarray, np.ndarray]:
    x = _IRIS[:, :4].copy()
    y = np.eye(3, dtype=np.float32)[_IRIS[:, 4].astype(int)]
    return x, y


class IrisDataSetIterator(ArrayDataSetIterator):
    """Reference: `datasets/iterator/impl/IrisDataSetIterator`."""

    def __init__(self, batch_size: int = 150, shuffle: bool = False,
                 seed: int = 123):
        x, y = load_iris()
        super().__init__(x, y, batch_size, shuffle=shuffle, seed=seed)


# ---------------------------------------------------------------------- LFW
def load_lfw(*, height: int = 64, width: int = 64, channels: int = 3,
             num_labels: Optional[int] = None,
             num_examples: Optional[int] = None
             ) -> Tuple[np.ndarray, np.ndarray, list, bool]:
    """Labeled Faces in the Wild. Returns (images [N,H,W,C] float32 in
    [0,1], one-hot labels [N,num_labels], label_names, synthetic_flag).

    Reference: `datasets/iterator/impl/LFWDataSetIterator.java` +
    `datasets/fetchers/LFWDataFetcher` (downloads the lfw tarball and
    reads the directory-per-person layout). Zero-egress here: reads the
    same layout from `<data_dir>/lfw/<person>/<person>_NNNN.jpg`,
    otherwise falls back to a deterministic synthetic face surrogate
    (per-identity gaussian prototypes), flagged via the returned bool.
    `num_labels` keeps only the people with the MOST images (the
    reference's useSubset/numLabels knob); `num_examples` truncates."""
    base = os.path.join(data_dir(), "lfw")
    per_label: dict = {}
    if os.path.isdir(base):
        from deeplearning4j_tpu.data.records import ImageRecordReader

        rr = ImageRecordReader(base, height=height, width=width,
                               channels=channels)
        for arr, li in rr:
            per_label.setdefault(li, []).append(arr)
    if per_label:
        keep = sorted(per_label,
                      key=lambda li: (-len(per_label[li]), li))
        if num_labels:
            keep = keep[:num_labels]
        names = [rr.labels[li] for li in keep]
        xs, ys = [], []
        for new_li, li in enumerate(keep):
            xs.extend(per_label[li])
            ys.extend([new_li] * len(per_label[li]))
        x = np.asarray(xs, np.float32)
        y = np.eye(len(keep), dtype=np.float32)[np.asarray(ys)]
        if num_examples:
            # shuffle before truncating (reference LFWDataFetcher does) —
            # examples are grouped by identity, so a head-slice would keep
            # only the most-photographed people
            perm = np.random.default_rng(12345).permutation(len(x))
            sel = perm[:num_examples]
            x, y = x[sel], y[sel]
        return x, y, names, False
    # Absent OR empty/undecodable cache dir -> synthetic surrogate:
    # per-identity prototypes, blended harder (0.7) because faces of one
    # person are more alike than different samples of one digit class.
    k = num_labels or 10
    n = num_examples or 40 * k
    x, y = _synthetic_surrogate(n, k, (height, width, channels),
                                proto_seed=9, sample_seed=46, blend=0.7)
    names = [f"person_{i:04d}" for i in range(k)]
    return x, y, names, True


class LFWDataSetIterator(ArrayDataSetIterator):
    """Reference: `datasets/iterator/impl/LFWDataSetIterator.java` —
    ctor knobs follow the reference (batchSize, imgDim, numExamples,
    numLabels, train + splitTrainTest)."""

    def __init__(self, batch_size: int, image_shape: Tuple[int, int, int]
                 = (64, 64, 3), *, train: bool = True,
                 split_train_test: float = 0.8, num_examples:
                 Optional[int] = None, num_labels: Optional[int] = None,
                 shuffle: bool = True, seed: int = 123):
        h, w, c = image_shape
        x, y, names, synthetic = load_lfw(
            height=h, width=w, channels=c, num_labels=num_labels,
            num_examples=num_examples)
        # deterministic stratified-ish split (reference splitTrainTest)
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(x))
        cut = int(len(x) * split_train_test)
        sel = perm[:cut] if train else perm[cut:]
        self.synthetic = synthetic
        self.label_names = names
        super().__init__(x[sel], y[sel], batch_size,
                         shuffle=shuffle, seed=seed)
