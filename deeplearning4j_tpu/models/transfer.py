"""Transfer learning: freeze, replace, append layers of a trained network.

Reference parity: `nn/transferlearning/TransferLearning.java:35` (Builder
`:37`, GraphBuilder `:428`), `FineTuneConfiguration.java`,
`TransferLearningHelper.java` (featurize-and-cache frozen prefix).

Because configs are immutable data and params are pytrees, transfer learning
is pure config surgery + param copying — no runtime object rewiring.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.special import FrozenLayer
from deeplearning4j_tpu.models.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optim.updaters import resolve_updater


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global overrides applied to every retained layer. Reference:
    `nn/transferlearning/FineTuneConfiguration.java`."""

    updater: Any = None
    learning_rate: Any = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None

    def apply_to(self, layer: Layer) -> Layer:
        upd = {}
        if self.updater is not None:
            upd["updater"] = resolve_updater(self.updater)
        if self.learning_rate is not None:
            upd["learning_rate"] = self.learning_rate
        if self.l1 is not None:
            upd["l1"] = self.l1
        if self.l2 is not None:
            upd["l2"] = self.l2
        if self.dropout is not None:
            upd["dropout"] = self.dropout
        return dataclasses.replace(layer, **upd) if upd else layer


class TransferLearning:
    """Entry: `TransferLearning.builder(net)`. Reference: Builder `:37`."""

    @staticmethod
    def builder(net: MultiLayerNetwork) -> "TransferLearningBuilder":
        return TransferLearningBuilder(net)


class TransferLearningBuilder:
    def __init__(self, net: MultiLayerNetwork):
        if net.params_tree is None:
            raise RuntimeError("Source network must be initialized")
        self._net = net
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_until: Optional[int] = None
        self._remove_from: Optional[int] = None
        self._appended: List[Layer] = []
        self._replacements: Dict[int, Layer] = {}

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, layer_index: int):
        """Freeze layers [0..layer_index] inclusive. Reference:
        `setFeatureExtractor`."""
        self._freeze_until = layer_index
        return self

    def remove_layers_from_output(self, count: int):
        """Drop the last `count` layers. Reference: `removeLayersFromOutput`."""
        self._remove_from = len(self._net.layers) - count
        return self

    def remove_output_layer_and_below(self, n: int = 1):
        return self.remove_layers_from_output(n)

    def n_out_replace(self, layer_index: int, n_out: int,
                      weight_init: Optional[str] = None):
        """Replace a layer's output width (params re-initialized; next
        layer's n_in is rewired). Reference: `nOutReplace`."""
        old = self._net.layers[layer_index]
        new = dataclasses.replace(
            old, n_out=n_out,
            weight_init=weight_init or old.weight_init)
        self._replacements[layer_index] = new
        nxt = layer_index + 1
        if nxt < len(self._net.layers) and nxt not in self._replacements:
            nxt_layer = self._net.layers[nxt]
            if hasattr(nxt_layer, "n_in"):
                self._replacements[nxt] = dataclasses.replace(
                    nxt_layer, n_in=n_out)
        return self

    def add_layer(self, layer: Layer):
        """Append after the retained stack. Reference: `addLayer`."""
        self._appended.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        src = self._net
        conf = src.conf
        n_keep = self._remove_from if self._remove_from is not None else len(conf.layers)
        new_layers: List[Layer] = []
        reinit: set = set()

        for i in range(n_keep):
            layer = conf.layers[i]
            if i in self._replacements:
                layer = self._replacements[i]
                reinit.add(i)
            if self._fine_tune is not None:
                layer = self._fine_tune.apply_to(layer)
            if self._freeze_until is not None and i <= self._freeze_until:
                if not isinstance(layer, FrozenLayer):
                    layer = dataclasses.replace(layer, frozen=True)
            new_layers.append(layer)

        base_idx = len(new_layers)
        for j, layer in enumerate(self._appended):
            if self._fine_tune is not None:
                layer = self._fine_tune.apply_to(layer)
            if layer.name is None:
                layer = dataclasses.replace(
                    layer, name=f"layer{base_idx + j}_{type(layer).__name__.lower()}")
            new_layers.append(layer)
            reinit.add(base_idx + j)

        # Re-run shape inference through the new stack.
        cur = conf.input_type
        wired: List[Layer] = []
        for i, layer in enumerate(new_layers):
            if cur is not None:
                if i in conf.preprocessors and i < n_keep:
                    cur = conf.preprocessors[i].output_type(cur)
                layer = layer.infer_n_in(cur)
                try:
                    cur = layer.output_type(cur)
                except Exception:
                    cur = None
            wired.append(layer)

        new_conf = dataclasses.replace(
            conf,
            layers=tuple(wired),
            preprocessors={k: v for k, v in conf.preprocessors.items()
                           if k < n_keep},
            seed=(self._fine_tune.seed if self._fine_tune and
                  self._fine_tune.seed is not None else conf.seed),
        )
        new_net = MultiLayerNetwork(new_conf).init()

        # Copy params/state for retained, non-reinitialized layers.
        for i, layer in enumerate(wired):
            if i in reinit or i >= n_keep:
                continue
            src_name = conf.layers[i].name
            dst_name = layer.name
            if src_name in src.params_tree:
                new_net.params_tree[dst_name] = jax.tree_util.tree_map(
                    lambda a: a, src.params_tree[src_name])
            if src_name in src.state_tree and src.state_tree[src_name]:
                new_net.state_tree[dst_name] = jax.tree_util.tree_map(
                    lambda a: a, src.state_tree[src_name])
        return new_net


class TransferLearningHelper:
    """Featurize through the frozen prefix once, then train only the
    unfrozen tail on cached features. Reference:
    `nn/transferlearning/TransferLearningHelper.java` (426 LoC)."""

    def __init__(self, net: MultiLayerNetwork):
        self.net = net
        self.split = 0
        for i, layer in enumerate(net.layers):
            if layer.frozen or isinstance(layer, FrozenLayer):
                self.split = i + 1
        if self.split == 0:
            raise ValueError("No frozen layers — nothing to featurize")

    def featurize(self, features) -> np.ndarray:
        """Run inputs through the frozen prefix."""
        import jax.numpy as jnp

        x = jnp.asarray(features, self.net.dtype)
        for i in range(self.split):
            if i in self.net.conf.preprocessors:
                x = self.net.conf.preprocessors[i].apply(x)
            layer = self.net.layers[i]
            x, _ = layer.apply(
                self.net.params_tree[layer.name], x,
                state=self.net.state_tree.get(layer.name) or None,
                train=False, rng=None)
        return np.asarray(x)

    def unfrozen_network(self) -> MultiLayerNetwork:
        """A standalone net of the unfrozen tail sharing param arrays."""
        conf = self.net.conf
        tail = conf.layers[self.split:]
        tail_pp = {
            k - self.split: v for k, v in conf.preprocessors.items()
            if k >= self.split
        }
        new_conf = dataclasses.replace(
            conf, layers=tuple(tail), preprocessors=tail_pp, input_type=None)
        tail_net = MultiLayerNetwork(new_conf).init()
        for layer in tail:
            tail_net.params_tree[layer.name] = self.net.params_tree[layer.name]
            if self.net.state_tree.get(layer.name):
                tail_net.state_tree[layer.name] = self.net.state_tree[layer.name]
        return tail_net

    def fit_featurized(self, features, labels, **kw) -> MultiLayerNetwork:
        tail = self.unfrozen_network()
        tail.fit(self.featurize(features), labels, **kw)
        # copy trained tail params back into the full network
        for layer in tail.layers:
            self.net.params_tree[layer.name] = tail.params_tree[layer.name]
        return self.net
