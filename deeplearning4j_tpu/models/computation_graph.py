"""ComputationGraph — DAG model runtime (multi-input / multi-output).

Reference parity: `nn/graph/ComputationGraph.java` — `init():340` (toposort
`:357`), `fit(DataSetIterator):778`, forward loop over `topologicalOrder`
`:1313,1325`, backprop `:1200-1210` (reverse topo order with fan-in epsilon
accumulation — here `jax.grad` through the forward fold).

The runtime folds over the configuration's topological order; the whole
forward + losses for ALL outputs + backward + update is one jitted XLA
computation, with multi-output loss = sum of per-output-layer losses
(reference: ComputationGraph sums output layer scores).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    DataSetIterator, DevicePrefetchIterator, as_iterator,
)
from deeplearning4j_tpu.optim.executor import LossTracker, TrainingExecutor
from deeplearning4j_tpu.optim.recovery import build_plan, run_with_recovery
from deeplearning4j_tpu.observe import donatemon
from deeplearning4j_tpu.nn.graph import (
    ComputationGraphConfiguration, GraphVertex, LayerVertex,
    resolve_output_type,
)
from deeplearning4j_tpu.nn.layers.special import CenterLossOutputLayer
from deeplearning4j_tpu.models.multilayer import (
    _check_decode_budget, _checkpointed, _dtype_of, _is_recurrent,
    _normalize_grads,
)
from deeplearning4j_tpu.optim.listeners import TrainingListener
from deeplearning4j_tpu.optim.updaters import NoOp, Updater, resolve_updater
from deeplearning4j_tpu.models.decode_state import DecodeState
from deeplearning4j_tpu.parallel.ring_attention import (
    SeqCtxJitCache, SeqCtxSolverCache,
)
from deeplearning4j_tpu.utils.pytrees import (
    flatten_params, param_count, unflatten_params,
)

_tmap = jax.tree_util.tree_map


class ComputationGraph(SeqCtxJitCache, SeqCtxSolverCache):
    """DAG network runtime over a ComputationGraphConfiguration."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.dtype = _dtype_of(conf.dtype)
        self.params_tree: Optional[Dict[str, Any]] = None
        self.state_tree: Dict[str, Any] = {}
        self.updater_state: Optional[Dict[str, Any]] = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[TrainingListener] = []
        self.last_batch_size: Optional[int] = None
        self._loss_tracker = LossTracker()
        self._rng = jax.random.PRNGKey(conf.seed)
        # rnnTimeStep statefulness, lock-guarded (ISSUE 7: the bare-attr
        # version was an unlocked shared-state mutation)
        self._decode_state = DecodeState()
        self._stateful: set = set()
        self._vertex_updaters: Dict[str, Updater] = {}
        self._jit_caches: Dict[Any, Dict[Any, Any]] = {}
        self._solvers: Dict[Any, Any] = {}      # full-batch solver cache

    @property
    def score_(self) -> Optional[float]:
        """Most recent training loss as a float — reading this materializes
        the deferred device loss (see MultiLayerNetwork.score_)."""
        return self._loss_tracker.value

    @score_.setter
    def score_(self, value) -> None:
        self._loss_tracker.set(value)

    # ------------------------------------------------------------- init
    def init(self) -> "ComputationGraph":
        key = jax.random.PRNGKey(self.conf.seed)
        params, states = {}, {}
        known = dict(self.conf.input_types)
        for name in self.conf.topological_order:
            v = self.conf.vertices[name]
            in_types = [known[i] for i in self.conf.vertex_inputs[name]
                        if i in known]
            key, sub = jax.random.split(key)
            p, s = v.init_params(sub, in_types, self.dtype)
            params[name] = p
            states[name] = s
            if s:
                self._stateful.add(name)
            resolve_output_type(name, v, in_types,
                                len(self.conf.vertex_inputs[name]), known)
        self.params_tree = params
        self.state_tree = states
        self._build_updaters()
        self.updater_state = {
            n: u.init(params[n]) for n, u in self._vertex_updaters.items()
        }
        return self

    def _build_updaters(self):
        global_u = resolve_updater(self.conf.updater or "sgd")
        for name in self.conf.topological_order:
            v = self.conf.vertices[name]
            u = global_u
            if isinstance(v, LayerVertex):
                layer = v.layer
                if layer.updater is not None:
                    u = resolve_updater(layer.updater)
                if layer.learning_rate is not None and hasattr(u, "learning_rate"):
                    u = dataclasses.replace(u, learning_rate=layer.learning_rate)
                if layer.frozen:
                    u = NoOp()
            self._vertex_updaters[name] = u

    # ---------------------------------------------------------- forward
    @property
    def _rnn_vertex_names(self) -> List[str]:
        """Vertices that carry RNN state (tBPTT / rnnTimeStep persistence)."""
        if not hasattr(self, "_rnn_names_cache"):
            self._rnn_names_cache = [
                n for n, v in self.conf.vertices.items()
                if isinstance(v, LayerVertex) and _is_recurrent(v.layer)
            ]
        return self._rnn_names_cache

    @property
    def _decode_vertex_names(self) -> List[str]:
        """Vertices with KV-cache decode carries (attention stepping)."""
        if not hasattr(self, "_decode_names_cache"):
            self._decode_names_cache = [
                n for n, v in self.conf.vertices.items()
                if isinstance(v, LayerVertex)
                and hasattr(v.layer, "decode_carry")
            ]
        return self._decode_names_cache

    def _forward(self, params, states, inputs: Dict[str, Any], *, train, rng,
                 fmasks: Optional[Dict[str, Any]] = None,
                 carries: Optional[Dict[str, Any]] = None,
                 stop_before: Optional[str] = None):
        """Fold over topological order. Returns (values, out_inputs, states)
        where out_inputs[name] is the input activation each output layer saw
        (needed for fused-loss score). `carries` override the stored state of
        recurrent vertices (tBPTT / rnnTimeStep statefulness — reference:
        `ComputationGraph.rnnTimeStep` / `rnnUpdateStateWithTBPTTState`)."""
        values: Dict[str, Any] = dict(inputs)
        out_inputs: Dict[str, Any] = {}
        new_states: Dict[str, Any] = {}
        for idx, name in enumerate(self.conf.topological_order):
            if name == stop_before:
                break
            v = self.conf.vertices[name]
            ins = [values[i] for i in self.conf.vertex_inputs[name]]
            st = states.get(name) or None
            if carries is not None and name in carries:
                st = carries[name]
            lrng = None if rng is None else jax.random.fold_in(rng, idx)
            mask = None
            if fmasks:
                # A vertex may name the network input whose mask it wants
                # (CrossAttentionVertex.key_mask_input — the generic
                # first-match rule below would deliver the wrong stream's
                # mask to a two-input attention vertex).
                pref = getattr(v, "key_mask_input", None)
                if pref is not None:
                    # Named-input mask ONLY — falling back to first-match
                    # would hand a different stream's mask to a vertex
                    # that trusts whatever it receives as a key mask.
                    mask = fmasks.get(pref)
                else:
                    for i in self.conf.vertex_inputs[name]:
                        if i in fmasks:
                            mask = fmasks[i]
                            break
            if isinstance(v, LayerVertex) and v.layer.is_output_layer:
                x = ins[0]
                if v.preprocessor is not None:
                    x = v.preprocessor.apply(x)
                out_inputs[name] = x
                y, new_st = v.layer.apply(
                    params[name], x, state=st, train=train, rng=lrng, mask=mask)
            elif (train and self.conf.gradient_checkpointing
                  and isinstance(v, LayerVertex)):
                # remat this layer vertex in the backward pass; cheap
                # parameterless vertices (merge/elementwise/...) are NOT
                # wrapped — their outputs are checkpoint residuals
                # anyway, so wrapping buys nothing and blocks CSE
                y, new_st = _checkpointed(v.apply, mask)(
                    params[name], ins, st, lrng)
            else:
                y, new_st = v.apply(
                    params[name], ins, state=st, train=train, rng=lrng, mask=mask)
            values[name] = y
            new_states[name] = new_st
        return values, out_inputs, new_states

    # ------------------------------------------------------------- loss
    def _loss(self, params, states, inputs, labels: Dict[str, Any],
              fmasks, lmasks, rng, train=True, carries=None):
        values, out_inputs, new_states = self._forward(
            params, states, inputs, train=train, rng=rng, fmasks=fmasks,
            carries=carries)
        total = jnp.asarray(0.0, jnp.float32)
        for name in self.conf.network_outputs:
            v = self.conf.vertices[name]
            if not (isinstance(v, LayerVertex) and v.layer.is_output_layer):
                continue
            lm = lmasks.get(name) if lmasks else None
            lab = labels[name]
            if isinstance(v.layer, CenterLossOutputLayer):
                s, cstate = v.layer.score_and_state(
                    params[name], out_inputs[name], lab, states[name], lm)
                new_states[name] = cstate
            else:
                s = v.layer.score(params[name], out_inputs[name], lab, lm)
            total = total + s
        for name, v in self.conf.vertices.items():
            if isinstance(v, LayerVertex):
                total = total + v.layer.regularization(params[name])
        # Activity-dependent auxiliary losses (e.g. MoE load balancing)
        # reported via vertex state — differentiated with the score.
        for st in new_states.values():
            if isinstance(st, dict) and "aux_loss" in st:
                total = total + st["aux_loss"]
        return total, new_states

    # ------------------------------------------------------ train step
    def make_step_fn(self, tbptt: bool = False):
        """Pure (un-jitted) train-step fn for parallel trainers (see
        MultiLayerNetwork.make_step_fn)."""
        return self._build_step(jit=False, tbptt=tbptt)

    def _get_train_step(self, key, tbptt: bool = False):
        key = (key, tbptt)
        if key in self._jit_cache:
            return self._jit_cache[key]
        fn = self._build_step(jit=True, tbptt=tbptt)
        self._jit_cache[key] = fn
        # read back through the cache: __setitem__ may have wrapped the
        # callable in the watchdog's cost/comm probe, and returning the
        # raw local lets the FIRST dispatch bypass the ledger
        return self._jit_cache[key]

    def _build_step(self, jit: bool, tbptt: bool = False):
        mode = self.conf.gradient_normalization
        thr = self.conf.gradient_normalization_threshold
        updaters = self._vertex_updaters
        stateful = self._stateful
        rnn_names = self._rnn_vertex_names

        def step_fn(params, opt_state, states, step, inputs, labels,
                    fmasks, lmasks, rng, carries=None):
            def loss_fn(p):
                return self._loss(p, states, inputs, labels, fmasks, lmasks,
                                  rng, train=True, carries=carries)

            (loss, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = _normalize_grads(grads, mode, thr)
            new_params, new_opt = {}, {}
            for name, u in updaters.items():
                # Whole-update seam (fused-kernel capable): see
                # MultiLayerNetwork._build_step.
                new_params[name], new_opt[name] = u.update_with_params(
                    grads[name], opt_state[name], params[name], step)
            persist = {
                n: (new_states[n] if n in stateful else states.get(n, {}))
                for n in states
            }
            if tbptt:
                # Carry RNN state to the next chunk, gradients truncated at
                # the chunk boundary (reference:
                # `ComputationGraph.rnnUpdateStateWithTBPTTState`).
                out_carries = {
                    n: _tmap(jax.lax.stop_gradient, new_states[n])
                    for n in rnn_names
                }
                return new_params, new_opt, persist, loss, out_carries
            return new_params, new_opt, persist, loss

        if not jit:
            return step_fn
        # donatemon.instrument is identity with DL4J_TPU_DONATEMON off;
        # on, it witnesses the (params, opt_state, states) donation.
        return donatemon.instrument(
            jax.jit(step_fn, donate_argnums=(0, 1, 2)), (0, 1, 2),
            name="ComputationGraph._step",
            arg_names=("params", "opt_state", "states"))

    # ---------------------------------------------------- data plumbing
    def _to_dicts(self, ds: Union[DataSet, MultiDataSet], host: bool = False):
        """Map a DataSet/MultiDataSet onto named inputs/outputs by order.
        `host=True` keeps leaves as numpy (multi-controller feeding: the
        caller lifts them into global arrays in one upload)."""
        asarray = np.asarray if host else jnp.asarray
        ins = self.conf.network_inputs
        outs = self.conf.network_outputs
        if isinstance(ds, MultiDataSet):
            feats = {n: asarray(f, self.dtype)
                     for n, f in zip(ins, ds.features)}
            labs = {n: asarray(l) for n, l in zip(outs, ds.labels)}
            fmasks = {}
            if ds.features_masks:
                fmasks = {n: asarray(m) for n, m in
                          zip(ins, ds.features_masks) if m is not None}
            lmasks = {}
            if ds.labels_masks:
                lmasks = {n: asarray(m) for n, m in
                          zip(outs, ds.labels_masks) if m is not None}
            return feats, labs, fmasks or None, lmasks or None
        feats = {ins[0]: asarray(ds.features, self.dtype)}
        labs = {outs[0]: asarray(ds.labels)} if ds.labels is not None else {}
        fmasks = ({ins[0]: asarray(ds.features_mask)}
                  if ds.features_mask is not None else None)
        lmasks = ({outs[0]: asarray(ds.labels_mask)}
                  if ds.labels_mask is not None else None)
        return feats, labs, fmasks, lmasks

    # ---------------------------------------------------------- fit API
    def fit(self, data, labels=None, *, epochs: int = 1, batch_size: int = 32,
            steps_per_dispatch: int = 1, device_prefetch: bool = True,
            sync_every: int = 0, checkpointer=None, checkpoint_every: int = 1,
            resume=None, stop_fn=None, preemption=None):
        """Reference: `ComputationGraph.fit(DataSetIterator):778` (also
        accepts MultiDataSet / arrays / iterator / iterable of batches).
        Pipelined per the async-dispatch contract — see
        `MultiLayerNetwork.fit` for the knob semantics, including the
        recovery knobs (``checkpointer``/``checkpoint_every``/``resume``/
        ``stop_fn``/``preemption`` — `optim/recovery.RecoveryPlan`). Each
        epoch re-iterates the source (`iter(...)` per epoch), so
        multi-epoch fit over a DataSetIterator or an iterable of DataSets
        replays every batch every epoch."""
        if self.params_tree is None:
            raise RuntimeError("Network not initialized — call init() first")
        plan = build_plan(self, checkpointer=checkpointer,
                          checkpoint_every=checkpoint_every, resume=resume,
                          stop_fn=stop_fn, preemption=preemption)
        if isinstance(data, MultiDataSet):
            iterable: Any = [data]
        else:
            iterable = as_iterator(data, labels, batch_size)
        if device_prefetch:
            iterable = DevicePrefetchIterator(
                iterable, depth=max(2, int(steps_per_dispatch)))
        self._loss_tracker.sync_every = int(sync_every)
        execu = TrainingExecutor(
            self,
            step=self._fit_batch,
            fused_step=self._fused_dispatch,
            can_fuse=self._can_fuse,
            steps_per_dispatch=steps_per_dispatch,
            before_batch=plan.before_batch if plan else None,
            after_dispatch=plan.after_dispatch if plan else None,
            epoch_start=plan.epoch_start if plan else None,
            epoch_end=plan.epoch_end if plan else None,
        )
        run_with_recovery(execu, plan, iterable, epochs)
        self.stopped_early = execu.stopped
        return self

    def _fit_batch(self, ds: Union[DataSet, MultiDataSet]):
        """One training step; returns the loss as a DEVICE array on the
        SGD path (deferred sync — see LossTracker)."""
        feats, labs, fmasks, lmasks = self._to_dicts(ds)
        self.last_batch_size = next(iter(feats.values())).shape[0]
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            from deeplearning4j_tpu.optim.solvers import fit_with_solver

            return fit_with_solver(self, feats, labs, fmasks, lmasks)
        if (self.conf.tbptt_fwd_length > 0
                and all(v.ndim == 3 for v in feats.values())):
            return self._fit_tbptt(feats, labs, fmasks, lmasks)
        key = (fmasks is not None, lmasks is not None)
        fn = self._get_train_step(key)
        self._rng, k = jax.random.split(self._rng)
        (self.params_tree, self.updater_state, self.state_tree,
         loss) = fn(self.params_tree, self.updater_state, self.state_tree,
                    jnp.asarray(self.iteration, jnp.int32),
                    feats, labs, fmasks, lmasks, k)
        return loss

    def _can_fuse(self, ds) -> bool:
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            return False
        if self.conf.tbptt_fwd_length > 0:
            fs = ds.features if hasattr(ds, "features_masks") else [ds.features]
            if all(f.ndim == 3 for f in fs):
                return False
        return True

    def _get_fused_step(self, key, k: int):
        cache_key = ("fused", key, k)
        if cache_key in self._jit_cache:
            return self._jit_cache[cache_key]
        base = self._build_step(jit=False, tbptt=False)

        def fused(params, opt_state, states, step0, rng, feats, labs, fms,
                  lms):
            # rng splits inside the scan carry — the same sequential
            # `self._rng, k = split(self._rng)` chain as the K=1 path.
            def body(carry, xs):
                p, o, s, step, r = carry
                f, l, fm, lm = xs
                r, sub = jax.random.split(r)
                new_p, new_o, persist, loss = base(
                    p, o, s, step, f, l, fm, lm, sub, None)
                return (new_p, new_o, persist, step + 1, r), loss

            (params, opt_state, states, _, rng), losses = jax.lax.scan(
                body, (params, opt_state, states, step0, rng),
                (feats, labs, fms, lms))
            return params, opt_state, states, rng, losses

        fn = donatemon.instrument(
            jax.jit(fused, donate_argnums=(0, 1, 2)), (0, 1, 2),
            name="ComputationGraph._fused_step",
            arg_names=("params", "opt_state", "states"))
        self._jit_cache[cache_key] = fn
        # read back through the cache (probe wrapping; see _get_train_step)
        return self._jit_cache[cache_key]

    def _fused_dispatch(self, batches: Sequence):
        """K same-shape batches → one `lax.scan` dispatch → (K,) losses."""
        # host=True keeps leaves as numpy when the batch is host-resident,
        # so each tensor stacks on host and crosses to device ONCE; a
        # prefetched (device-array) batch keeps the jnp path instead.
        f0 = batches[0].features
        host = isinstance(
            f0[0] if hasattr(batches[0], "features_masks") else f0,
            np.ndarray)
        conv = [self._to_dicts(b, host=host) for b in batches]
        self.last_batch_size = next(iter(conv[0][0].values())).shape[0]
        stack = ((lambda vs: jnp.asarray(np.stack(vs))) if host
                 else jnp.stack)

        def stk(idx):
            head = conv[0][idx]
            if head is None:
                return None
            return {n: stack([c[idx][n] for c in conv]) for n in head}

        key = (conv[0][2] is not None, conv[0][3] is not None)
        fn = self._get_fused_step(key, len(batches))
        (self.params_tree, self.updater_state, self.state_tree, self._rng,
         losses) = fn(self.params_tree, self.updater_state, self.state_tree,
                      np.int32(self.iteration), self._rng,
                      stk(0), stk(1), stk(2), stk(3))
        return losses

    def _fit_tbptt(self, feats, labs, fmasks, lmasks) -> float:
        """Truncated BPTT over every 3-D input/label dict entry; RNN vertex
        state carried across chunks with stop_gradient. Reference:
        `ComputationGraph.fit` tBPTT dispatch (`:778`) + doTruncatedBPTT."""
        L = self.conf.tbptt_fwd_length
        Lb = min(self.conf.tbptt_back_length or L, L)
        T = next(iter(feats.values())).shape[1]
        for name, lab in labs.items():
            if lab.ndim != 3:
                raise ValueError(
                    f"Truncated BPTT requires per-timestep 3-D labels; "
                    f"output {name!r} has shape {tuple(lab.shape)}")
        key = (fmasks is not None, lmasks is not None)
        fn = self._get_train_step(key, tbptt=True)
        carries = {}
        losses = []
        for lo in range(0, T, L):
            hi = min(lo + L, T)
            t_lo = lo

            def sl(d, a, b):
                return None if d is None else {
                    n: jnp.asarray(v[:, a:b]) for n, v in d.items()}

            if Lb < hi - lo:
                # fwd > back: advance carries over the prefix, no update.
                t_lo = hi - Lb
                carries = self._advance_carries(
                    sl(feats, lo, t_lo), sl(fmasks, lo, t_lo), carries)
            self._rng, k = jax.random.split(self._rng)
            (self.params_tree, self.updater_state, self.state_tree, loss,
             carries) = fn(
                self.params_tree, self.updater_state, self.state_tree,
                jnp.asarray(self.iteration, jnp.int32),
                sl(feats, t_lo, hi), sl(labs, t_lo, hi),
                sl(fmasks, t_lo, hi), sl(lmasks, t_lo, hi), k,
                carries if carries else None)
            losses.append(loss)
        # Mean on device — no per-chunk host syncs.
        return jnp.stack(losses).mean()

    def _advance_carries(self, feats, fmasks, carries):
        """Gradient-free forward that only advances RNN vertex carries."""
        key = ("advance", fmasks is not None, bool(carries))
        if key not in self._jit_cache:
            rnn_names = self._rnn_vertex_names

            def adv(params, states, inputs, fm, car):
                _, _, new_states = self._forward(
                    params, states, inputs, train=False, rng=None,
                    fmasks=fm, carries=car)
                return {n: new_states[n] for n in rnn_names}

            self._jit_cache[key] = jax.jit(adv)
        return self._jit_cache[key](
            self.params_tree, self.state_tree, feats, fmasks,
            carries if carries else None)

    # ----------------------------------------------------- rnn stepping
    @property
    def _rnn_carries(self):
        """Read view of the ambient stepping carries (mutations live in
        the lock-guarded `DecodeState`)."""
        return self._decode_state.carries

    @property
    def _decode_pos(self):
        return self._decode_state.pos

    def rnn_time_step(self, *xs):
        """Stateful single-step inference; RNN vertex carries persist across
        calls. Reference: `ComputationGraph.rnnTimeStep`. Attention
        vertices step the same way via their decode carries (KV cache),
        mirroring `MultiLayerNetwork.rnn_time_step`. The read-step-write
        runs under the decode-state lock so concurrent callers serialize
        instead of corrupting each other's carries."""
        inputs = {}
        for n, x in zip(self.conf.network_inputs, xs):
            x = jnp.asarray(x, self.dtype)
            if x.ndim == 2:
                x = x[:, None, :]
            inputs[n] = x
        decode_names = self._decode_vertex_names
        st = self._decode_state
        with st.lock():
            t_step = None
            if decode_names:
                # Host-side decode-length guard (under jit the layers'
                # eager overflow checks cannot fire — see
                # MultiLayerNetwork). Only meaningful when every input
                # steps by the same length; a multi-length graph (e.g.
                # full encoder context + one decoder token per call) has
                # no single counter, so the in-kernel NaN poison is the
                # remaining overflow signal there.
                lens = {v.shape[1] for v in inputs.values() if v.ndim >= 3}
                if len(lens) == 1:
                    t_step = lens.pop()
                    _check_decode_budget(
                        self,
                        (self.conf.vertices[n].layer for n in decode_names),
                        t_step)
            if not st.carries and decode_names:
                batch = next(iter(inputs.values())).shape[0]
                # validate ALL before seeding ANY: a mid-loop raise would
                # leave partial carries behind and disarm this guard
                for n in decode_names:
                    if not getattr(self.conf.vertices[n].layer,
                                   "causal", True):
                        raise ValueError(
                            f"rnn_time_step requires causal attention; "
                            f"vertex {n!r} is non-causal (stepped "
                            f"decoding cannot reproduce a bidirectional "
                            f"forward)")
                st.seed({n: self.conf.vertices[n].layer.decode_carry(
                    batch, self.dtype) for n in decode_names})
            stateful = set(self._rnn_vertex_names) | set(decode_names)
            carries = st.carries or None
            # One jitted program per (step shapes, carry presence) — see
            # MultiLayerNetwork.rnn_time_step for why eager per-op
            # dispatch is unacceptable in a per-token decode loop on TPU.
            key = ("rnn_step",
                   tuple(sorted((n, v.shape) for n, v in inputs.items())),
                   carries is not None)
            if key not in self._jit_cache:
                def step_fn(params, states, inputs_, carries_):
                    values, _, new_states = self._forward(
                        params, states, inputs_, train=False, rng=None,
                        carries=carries_)
                    return ({o: values[o]
                             for o in self.conf.network_outputs},
                            {n: new_states[n] for n in stateful})

                self._jit_cache[key] = jax.jit(step_fn)
            values, new_carries = self._jit_cache[key](
                self.params_tree, self.state_tree, inputs, carries)
            # advance only after a successful step
            st.update(new_carries,
                      advance=t_step if t_step is not None else 0)
        outs = [values[o] for o in self.conf.network_outputs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_reorder_state(self, idx) -> None:
        """Reorder/expand decode carries along the batch dimension (see
        `MultiLayerNetwork.rnn_reorder_state` — the beam-search carry
        contract is identical for graph vertices)."""
        ix = jnp.asarray(np.asarray(idx))
        self._decode_state.reorder(lambda carries: jax.tree_util.tree_map(
            lambda a: a[ix] if getattr(a, "ndim", 0) >= 1 else a, carries))

    def rnn_clear_previous_state(self):
        """Reference: `ComputationGraph.rnnClearPreviousState`."""
        self._decode_state.clear()

    # -------------------------------------------------------- pretrain
    def pretrain(self, data, *, epochs: int = 1, batch_size: int = 32):
        """Greedy layerwise unsupervised pretraining of pretrainable layer
        vertices (AutoEncoder/RBM/VAE), in topological order. Reference:
        `ComputationGraph.pretrain(DataSetIterator)`."""
        it = as_iterator(data, None, batch_size)
        for name in self.conf.topological_order:
            v = self.conf.vertices[name]
            if not (isinstance(v, LayerVertex) and v.layer.is_pretrainable):
                continue
            layer, vertex = v.layer, v
            updater = self._vertex_updaters[name]
            opt = updater.init(self.params_tree[name])

            def featurize(params, states, feats):
                """This vertex's input activation under the current params:
                fold the DAG only up to (not including) this vertex."""
                values, _, _ = self._forward(
                    params, states, feats, train=False, rng=None,
                    stop_before=name)
                x = values[self.conf.vertex_inputs[name][0]]
                if vertex.preprocessor is not None:
                    x = vertex.preprocessor.apply(x)
                return x

            @jax.jit
            # graft: allow(GL103): one program per pretrained layer by
            # design — layerwise pretraining compiles each layer once
            def pre_step(params, lp, opt_state, step, feats, rng):
                x = featurize(params, self.state_tree, feats)

                def loss_fn(p):
                    return layer.reconstruction_score(p, x, rng=rng)

                loss, grads = jax.value_and_grad(loss_fn)(lp)
                new_lp, new_opt = updater.update_with_params(
                    grads, opt_state, lp, step)
                return new_lp, new_opt, loss

            step = 0
            for _ in range(epochs):
                for ds in it:
                    feats, _, _, _ = self._to_dicts(ds)
                    self._rng, k = jax.random.split(self._rng)
                    lp, opt, _ = pre_step(
                        self.params_tree, self.params_tree[name], opt,
                        jnp.asarray(step, jnp.int32), feats, k)
                    self.params_tree[name] = lp
                    step += 1
        return self

    # -------------------------------------------------------- inference
    def output(self, *xs, train: bool = False):
        """Forward; returns a list of output arrays (single array if one
        output). Reference: `ComputationGraph.output(INDArray...)`."""
        if self.params_tree is None:
            raise RuntimeError("Network not initialized — call init() first")
        inputs = {n: jnp.asarray(x, self.dtype)
                  for n, x in zip(self.conf.network_inputs, xs)}
        key = ("output", train, tuple(sorted(inputs)))
        if key not in self._jit_cache:
            def out_fn(params, states, feats):
                values, _, _ = self._forward(
                    params, states, feats, train=train, rng=None)
                return [values[o] for o in self.conf.network_outputs]
            self._jit_cache[key] = jax.jit(out_fn)
        outs = self._jit_cache[key](self.params_tree, self.state_tree, inputs)
        return outs[0] if len(outs) == 1 else outs

    def score(self, ds: Union[DataSet, MultiDataSet]) -> float:
        feats, labs, fmasks, lmasks = self._to_dicts(ds)
        loss, _ = self._loss(self.params_tree, self.state_tree, feats, labs,
                             fmasks, lmasks, rng=None, train=False)
        return float(loss)

    def predict(self, *xs) -> np.ndarray:
        out = self.output(*xs)
        if isinstance(out, list):
            return [np.asarray(jnp.argmax(o, -1)) for o in out]
        return np.asarray(jnp.argmax(out, -1))

    def evaluate(self, iterator: DataSetIterator,
                 output_name: Optional[str] = None):
        """Classification evaluation of one head (default: first output),
        with the device-side argmax fast path for plain per-example labels
        (only int32 indices cross to host) — matching
        MultiLayerNetwork.evaluate. `output_name` selects a specific head
        of a multi-output graph (beyond the reference, whose
        `ComputationGraph.evaluate(DataSetIterator)` is first-output-only).
        Accepts DataSet batches (labels belong to the selected head) or
        MultiDataSet batches (labels matched to outputs by position).
        RecordMetaData from a meta-collecting iterator flows into
        per-example Prediction records."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        order = list(self.conf.network_outputs)
        idx = 0
        if output_name is not None:
            if output_name not in order:
                raise ValueError(
                    f"Unknown output {output_name!r}; graph outputs: {order}")
            idx = order.index(output_name)

        def head(out):
            return out[idx] if isinstance(out, list) else out

        ev = Evaluation()
        for ds in iterator:
            meta = getattr(iterator, "last_meta", None)
            if isinstance(ds, MultiDataSet):
                feats = list(ds.features)
                lab = np.asarray(ds.labels[idx])
                mask = ds.labels_masks[idx] if ds.labels_masks else None
            else:
                feats = [ds.features]
                lab = np.asarray(ds.labels)
                mask = ds.labels_mask
            if lab.ndim == 3 or mask is not None:
                ev.eval(lab, np.asarray(head(self.output(*feats))),
                        mask=mask,
                        record_meta=None if lab.ndim == 3 else meta)
                continue
            o = head(self.output(*feats))
            pred = jnp.argmax(o, axis=-1)       # argmax on device
            actual = (lab.argmax(-1) if lab.ndim == 2
                      else lab.astype(np.int64))
            n = lab.shape[-1] if lab.ndim == 2 else int(o.shape[-1])
            ev.eval_indices(actual, np.asarray(pred), num_classes=n,
                            record_meta=meta)
        return ev

    def evaluate_outputs(self, iterator,
                         output_names: Optional[Sequence[str]] = None
                         ) -> Dict[str, "Evaluation"]:
        """Per-output metrics for multi-output graphs in ONE forward pass
        per batch: returns {output_name: Evaluation}. Accepts DataSet
        (single-output graphs) or MultiDataSet iterators (labels matched
        to outputs by position, the _to_dicts ordering). RecordMetaData
        from a meta-collecting iterator flows into every head's
        Prediction records. Reference: `nn/graph/ComputationGraph.java`
        evaluate family (single-output) — multi-output eval is a
        capability extension."""
        from deeplearning4j_tpu.data.dataset import MultiDataSet
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        order = list(self.conf.network_outputs)
        names = list(output_names) if output_names is not None else order
        for n in names:
            if n not in order:
                raise ValueError(
                    f"Unknown output {n!r}; graph outputs: {order}")
        evals = {n: Evaluation() for n in names}
        for ds in iterator:
            if isinstance(ds, MultiDataSet):
                feats = [np.asarray(f) for f in ds.features]
                labels = {n: ds.labels[order.index(n)] for n in names}
                masks = ({n: ds.labels_masks[order.index(n)] for n in names}
                         if ds.labels_masks else {n: None for n in names})
            else:
                if len(order) > 1 and len(names) != 1:
                    raise ValueError(
                        "DataSet batches carry ONE labels array; evaluating "
                        f"{len(names)} heads of a multi-output graph needs "
                        "MultiDataSet batches (labels per output)")
                # single head requested: the DataSet's labels are its labels
                feats = [ds.features]
                labels = {n: ds.labels for n in names}
                masks = {n: ds.labels_mask for n in names}
            outs = self.output(*feats)
            if not isinstance(outs, list):
                outs = [outs]
            meta = getattr(iterator, "last_meta", None)
            for n in names:
                lab = np.asarray(labels[n])
                evals[n].eval(
                    lab, np.asarray(outs[order.index(n)]), mask=masks[n],
                    record_meta=None if lab.ndim == 3 else meta)
        return evals

    def evaluate_regression(self, iterator: DataSetIterator):
        """Reference: `ComputationGraph.evaluateRegression:2780`."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation

        return Evaluation.run_evaluation(
            RegressionEvaluation(), iterator, self.output)

    def evaluate_roc(self, iterator: DataSetIterator,
                     threshold_steps: int = 0):
        """Binary ROC over the (single) output. Reference: evaluateROC."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.eval.roc import ROC

        return Evaluation.run_evaluation(
            ROC(threshold_steps), iterator, self.output)

    def evaluate_roc_multi_class(self, iterator: DataSetIterator):
        """Reference: evaluateROCMultiClass."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.eval.roc import ROCMultiClass

        return Evaluation.run_evaluation(
            ROCMultiClass(), iterator, self.output)

    # ----------------------------------------------------- param views
    def params(self) -> np.ndarray:
        flat, _ = flatten_params(self.params_tree)
        return np.asarray(flat)

    def set_params(self, flat) -> None:
        self.params_tree = unflatten_params(jnp.asarray(flat), self.params_tree)

    def num_params(self) -> int:
        return param_count(self.params_tree)

    def set_listeners(self, *listeners: TrainingListener) -> None:
        self.listeners = list(listeners)

    def add_listener(self, l: TrainingListener) -> None:
        self.listeners.append(l)
