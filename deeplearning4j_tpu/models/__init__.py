"""Model runtimes: MultiLayerNetwork (sequential) and ComputationGraph (DAG).

Reference parity: `nn/multilayer/MultiLayerNetwork.java` and
`nn/graph/ComputationGraph.java`. The eager per-op loop of the reference
becomes one jitted XLA computation per train step here.
"""

from deeplearning4j_tpu.models.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu.models.fusion import fuse_conv_bn

__all__ = ["MultiLayerNetwork", "ComputationGraph", "fuse_conv_bn"]
