"""Graph-level conv+BN fusion pass.

STATUS: FROZEN/EXPERIMENTAL (2026-07-31) — the fused kernels measured
2x slower than XLA on the flagship (PERF_NOTES "DECISION"); this pass
stays opt-in and gets no new feature work.

Reference seam: DL4J points conv/BN layers at hand-fused cuDNN helpers
chosen reflectively per layer (`ConvolutionLayer.java:67-77`); here the
equivalent "use the fast kernel" decision is a MODEL TRANSFORM — any
network (zoo builder, DL4J import, Keras import) can have its eligible
conv -> batch-norm pairs (1x1 any stride; 3x3 stride-1 SAME) rewritten
into `FusedConvBNLayer` (`ops/conv_fused.py`: Pallas conv kernels with
in-kernel BN statistics) after construction, without per-builder flags. The inverse of torch's
inference-only `fuse_modules`: this fusion is TRAINING-mode (batch
statistics ride the matmul), eval folding stays in XLA.

Eligibility (both checked structurally, nothing silently approximated):
- ConvolutionLayer with no bias, identity activation, dilation-free, and
  a fusable shape: kernel (1,1) in same mode (explicit padding is
  ignored under same) or in strict/truncate mode with zero explicit
  padding, or kernel (3,3) stride-1 with SAME-equivalent padding;
- whose ONLY consumer is a BatchNormalization vertex with learnable
  gamma+beta, itself not consuming anything else.

The fused vertex keeps the BN vertex's NAME, so downstream edges and
checkpoint keys for everything else are untouched; conv weights and BN
gamma/beta/mean/var transfer over. Per-layer updater state for the fused
pair is re-initialized (the DL4J transfer-learning behavior when layers
are replaced)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.convolution import _pair


def _copy_tree(tree):
    # fresh buffers: the source net's jitted step DONATES its param
    # arrays, so shared leaves would be deleted under the new net
    return jax.tree_util.tree_map(jnp.array, tree)


def fusable_conv_shape(kernel, stride, padding, mode) -> bool:
    """Whether a conv of this geometry has a fused Pallas conv+BN kernel
    (`ops/conv_fused.py`). The single source of truth for the shape
    predicate — used by this transform's eligibility check and by zoo
    builders deciding to emit FusedConvBNLayer directly."""
    k = _pair(kernel)
    if k == (1, 1):
        # for k=1, SAME == VALID; same-mode ignores explicit padding
        # entirely, other modes need it to actually be zero
        return mode == "same" or _pair(padding) == (0, 0)
    if k == (3, 3):
        # the fused 3x3 kernel is stride-1 SAME only
        if _pair(stride) != (1, 1):
            return False
        return (mode == "same"
                or (_pair(padding) == (1, 1)
                    and mode in ("strict", "truncate")))
    return False


def _eligible_conv(layer) -> bool:
    from deeplearning4j_tpu.nn.layers import ConvolutionLayer

    if type(layer) is not ConvolutionLayer:
        return False
    if _pair(layer.dilation) != (1, 1) or layer.has_bias:
        return False
    if (layer.activation or "identity") != "identity" or layer.dropout:
        return False
    return fusable_conv_shape(layer.kernel, layer.stride, layer.padding,
                              layer.convolution_mode)


def _eligible_bn(layer) -> bool:
    from deeplearning4j_tpu.nn.layers import BatchNormalization

    return (type(layer) is BatchNormalization
            and not layer.lock_gamma_beta
            and layer.scale and layer.center)


_CARRIED = ("l1", "l2", "l1_bias", "l2_bias", "updater", "learning_rate",
            "frozen")


def _pair_config_matches(conv, bn) -> bool:
    # the fused layer has ONE set of per-layer training knobs; fusing a
    # pair whose knobs differ would silently change regularization /
    # optimizer / trainability semantics — such pairs stay unfused
    return all(getattr(conv, k) == getattr(bn, k) for k in _CARRIED)


def fuse_conv_bn(net):
    """Rewrite eligible conv -> BN pairs (1x1 any stride; 3x3 stride-1
    SAME) of a ComputationGraph into FusedConvBNLayer vertices,
    transferring weights and running stats. Returns a NEW initialized
    network (the input is untouched); `net.fused_pairs` on the result
    lists the (conv, bn) names rewritten."""
    from deeplearning4j_tpu.models import ComputationGraph
    from deeplearning4j_tpu.nn.graph import LayerVertex, toposort
    from deeplearning4j_tpu.nn.layers import FusedConvBNLayer

    conf = net.conf
    if not hasattr(conf, "vertices"):
        raise TypeError(
            "fuse_conv_bn operates on ComputationGraph models; wrap "
            "sequential nets as graphs (to_computation_graph) first")
    consumers: Dict[str, list] = {}
    for name, ins in conf.vertex_inputs.items():
        for i in ins:
            consumers.setdefault(i, []).append(name)

    pairs = []   # (conv_name, bn_name)
    for cname, v in conf.vertices.items():
        if not isinstance(v, LayerVertex) or not _eligible_conv(v.layer):
            continue
        if getattr(v, "preprocessor", None) is not None:
            continue
        if cname in conf.network_outputs:
            continue
        cons = consumers.get(cname, [])
        if len(cons) != 1:
            continue
        b = conf.vertices[cons[0]]
        if not isinstance(b, LayerVertex) or not _eligible_bn(b.layer):
            continue
        if getattr(b, "preprocessor", None) is not None:
            continue
        if conf.vertex_inputs[cons[0]] != (cname,):
            continue
        if not _pair_config_matches(v.layer, b.layer):
            continue
        pairs.append((cname, cons[0]))

    if not pairs:
        out = ComputationGraph(conf).init()
        out.params_tree = _copy_tree(net.params_tree)
        out.state_tree = _copy_tree(net.state_tree)
        out.updater_state = _copy_tree(net.updater_state)
        out.fused_pairs = []
        return out

    vertices = dict(conf.vertices)
    vertex_inputs = {k: tuple(v) for k, v in conf.vertex_inputs.items()}
    for conv_name, bn_name in pairs:
        conv = vertices[conv_name].layer
        bn = vertices[bn_name].layer
        fused = FusedConvBNLayer(
            name=bn_name, n_in=conv.n_in, n_out=conv.n_out,
            kernel=_pair(conv.kernel),
            stride=_pair(conv.stride), decay=bn.decay, eps=bn.eps,
            activation=bn.activation or "identity",
            weight_init=conv.weight_init,
            # per-layer training knobs carry over (eligibility already
            # requires conv and BN to agree on them)
            **{k: getattr(conv, k) for k in _CARRIED})
        vertices[bn_name] = dataclasses.replace(
            vertices[bn_name], layer=fused)
        vertex_inputs[bn_name] = vertex_inputs[conv_name]
        del vertices[conv_name]
        del vertex_inputs[conv_name]

    new_conf = dataclasses.replace(
        conf, vertices=vertices, vertex_inputs=vertex_inputs,
        topological_order=tuple(toposort(vertex_inputs,
                                         conf.network_inputs)))
    fused_net = ComputationGraph(new_conf).init()

    # transfer params/state: untouched vertices copy through; fused
    # vertices take conv W + BN gamma/beta (+ running stats as f32)
    params = dict(net.params_tree)
    states = dict(net.state_tree)
    fused_names = set()
    for conv_name, bn_name in pairs:
        fused_names.add(bn_name)
        fused_net.params_tree[bn_name] = _copy_tree({
            "W": params[conv_name]["W"],
            "gamma": params[bn_name]["gamma"],
            "beta": params[bn_name]["beta"],
        })
        fused_net.state_tree[bn_name] = {
            "mean": jnp.array(states[bn_name]["mean"], jnp.float32),
            "var": jnp.array(states[bn_name]["var"], jnp.float32),
        }
        del params[conv_name]
    for name, p in params.items():
        if name not in fused_names:
            fused_net.params_tree[name] = _copy_tree(p)
            if name in states:
                fused_net.state_tree[name] = _copy_tree(states[name])
            if name in net.updater_state:
                # optimizer state carries over for untouched layers;
                # only the fused pair restarts its moments (their param
                # structure changed — the DL4J replaced-layer behavior)
                fused_net.updater_state[name] = _copy_tree(
                    net.updater_state[name])
    fused_net.fused_pairs = pairs
    return fused_net
