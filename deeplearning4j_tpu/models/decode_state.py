"""DecodeState — the lock-owning holder for a model's ambient decode
state (`rnn_time_step` carries + decode position).

Why this exists: `_rnn_carries`/`_decode_pos` used to live as bare
attributes on MultiLayerNetwork/ComputationGraph, mutated with no lock —
two threads stepping the same net interleave their read-modify-write and
corrupt each other's KV caches silently. Serving fixes this properly by
not sharing at all (serving/sessions.py threads carries through the
jitted step as ARGUMENTS); this class fixes the remaining ambient path:
every mutation happens under one reentrant lock, and a caller that needs
a multi-step critical section (seed -> step -> advance) takes the same
lock via `lock()` around the whole sequence.

The lock is reentrant so the model's step method can hold it across the
read-modify-write while the individual accessors stay safe for external
callers. Pickling/deepcopy drops the lock (a fresh one is made on
restore) — locks don't serialize, model snapshots do.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict


class DecodeState:
    """Carries + decode position behind one reentrant lock."""

    def __init__(self):
        self._lock = threading.RLock()
        self._carries: Dict[str, Any] = {}
        self._pos: int = 0

    def lock(self):
        """The lock itself, for multi-step critical sections:
        ``with st.lock(): ...`` composes with the locked accessors
        (reentrant)."""
        return self._lock

    @property
    def carries(self) -> Dict[str, Any]:
        with self._lock:
            return self._carries

    @property
    def pos(self) -> int:
        with self._lock:
            return self._pos

    def seed(self, carries: Dict[str, Any]) -> None:
        with self._lock:
            self._carries = carries

    def update(self, carries: Dict[str, Any], advance: int = 0) -> None:
        """Install the post-step carries and advance the decode position
        (only after a successful step — a trace failure must not burn
        decode budget)."""
        with self._lock:
            self._carries = carries
            self._pos += advance

    def clear(self) -> None:
        with self._lock:
            self._carries = {}
            self._pos = 0

    def reorder(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]]):
        """Replace the carries with `fn(carries)` atomically (beam-search
        parent gathers)."""
        with self._lock:
            self._carries = fn(self._carries)

    # locks don't pickle/deepcopy; state snapshots do
    def __getstate__(self):
        with self._lock:
            return {"carries": self._carries, "pos": self._pos}

    def __setstate__(self, state):
        self._lock = threading.RLock()
        with self._lock:
            self._carries = state["carries"]
            self._pos = state["pos"]
