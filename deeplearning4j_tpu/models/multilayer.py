"""MultiLayerNetwork — sequential-stack model runtime.

Reference parity: `nn/multilayer/MultiLayerNetwork.java` — `init():446`,
`feedForward:752-858`, `fit(DataSetIterator):1046`, `backprop():1147`,
tBPTT `:1102-1104,1351`, `output:1716-1827`, `computeGradientAndScore():2047`,
pretrain `:214-301` — and the solver loop
(`optimize/solvers/StochasticGradientDescent.java:58-98`).

TPU-first redesign: the reference's OUTER HOT LOOP (SURVEY §3.1) ran dozens of
eager native ops per layer per step; here `fit()` compiles forward + backward
+ updater into ONE donated, jitted XLA computation. Parameters and optimizer
state are pytrees keyed by layer name (the reference's flattened view arrays
are available on demand via `params()` for serde/parity). Gradients come from
`jax.value_and_grad` — the reference's per-layer `backpropGradient` chain is
the autodiff transpose.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    DataSetIterator, DevicePrefetchIterator, as_iterator,
)
from deeplearning4j_tpu.models.decode_state import DecodeState
from deeplearning4j_tpu.observe import donatemon
from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
from deeplearning4j_tpu.optim.executor import LossTracker, TrainingExecutor
from deeplearning4j_tpu.optim.recovery import build_plan, run_with_recovery
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.recurrent import (
    BaseRecurrentLayer, Bidirectional, GravesBidirectionalLSTM, LastTimeStep,
)
from deeplearning4j_tpu.nn.layers.special import CenterLossOutputLayer
from deeplearning4j_tpu.optim.listeners import TrainingListener
from deeplearning4j_tpu.optim.updaters import NoOp, Updater, resolve_updater
from deeplearning4j_tpu.parallel.ring_attention import (
    SeqCtxJitCache, SeqCtxSolverCache,
)
from deeplearning4j_tpu.utils.pytrees import (
    flatten_params, param_count, tree_norm, unflatten_params,
)

_tmap = jax.tree_util.tree_map


def _dtype_of(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
            "float16": jnp.float16, "float64": jnp.float64}[name]


def _is_recurrent(layer: Layer) -> bool:
    return isinstance(
        layer, (BaseRecurrentLayer, Bidirectional, GravesBidirectionalLSTM)
    )


def _decode_limit(decode_layers) -> Optional[int]:
    """Smallest KV-cache/position bound among decode-capable layers —
    the host-side decode-length guard's ceiling (under the jitted
    stepping path the layers' own eager overflow checks cannot fire).
    Rolling-cache layers stream in fixed memory, so their max_cache is
    a buffer size, not a length bound."""
    limits = []
    for l in decode_layers:
        if not getattr(l, "rolling_cache", False):
            mc = getattr(l, "max_cache", None)
            if mc is not None:
                limits.append(mc)
        ml = getattr(l, "max_length", None)
        if ml is not None:
            limits.append(ml)
    return min(limits) if limits else None


def _check_decode_budget(model, decode_layers, t_step: int) -> None:
    """The shared host-side decode-length guard: raises before a step
    that would run past the smallest cache/position limit. The caller
    advances `model._decode_pos` only after a successful step."""
    limit = _decode_limit(decode_layers)
    pos0 = getattr(model, "_decode_pos", 0)
    if limit is not None and pos0 + t_step > limit:
        raise ValueError(
            f"decode position {pos0} + step {t_step} exceeds the "
            f"smallest cache/position limit {limit}; raise "
            f"max_cache/max_length or rnn_clear_previous_state()")


def _checkpointed(apply_fn, mask):
    """Wrap one layer/vertex apply in jax.checkpoint for the TRAIN path
    (gradient_checkpointing): its activations are rematerialized in the
    backward pass instead of stored. Shared by MultiLayerNetwork and
    ComputationGraph so the remat semantics can't drift."""
    return jax.checkpoint(
        lambda p, x, st, lr, _a=apply_fn:
        _a(p, x, state=st, train=True, rng=lr, mask=mask))


def _normalize_grads(grads, mode: str, threshold: float):
    """Gradient normalization/clipping per layer subtree.
    Reference: `nn/conf/GradientNormalization.java` applied in BaseLayer."""
    if mode == "none":
        return grads
    if mode == "clip_elementwise_absolute_value":
        return _tmap(lambda g: jnp.clip(g, -threshold, threshold), grads)

    def per_layer(sub):
        if mode == "renormalize_l2_per_layer":
            n = tree_norm(sub)
            return _tmap(lambda g: g / jnp.maximum(n, 1e-8), sub)
        if mode == "clip_l2_per_layer":
            n = tree_norm(sub)
            scale = jnp.minimum(1.0, threshold / jnp.maximum(n, 1e-8))
            return _tmap(lambda g: g * scale, sub)
        if mode == "renormalize_l2_per_param_type":
            return {k: v / jnp.maximum(jnp.linalg.norm(jnp.ravel(v)), 1e-8)
                    for k, v in sub.items()}
        if mode == "clip_l2_per_param_type":
            out = {}
            for k, v in sub.items():
                n = jnp.linalg.norm(jnp.ravel(v))
                out[k] = v * jnp.minimum(1.0, threshold / jnp.maximum(n, 1e-8))
            return out
        raise ValueError(mode)

    return {name: per_layer(sub) for name, sub in grads.items()}


class MultiLayerNetwork(SeqCtxJitCache, SeqCtxSolverCache):
    """Sequential network runtime over a MultiLayerConfiguration."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: Tuple[Layer, ...] = conf.layers
        self.dtype = _dtype_of(conf.dtype)
        self.params_tree: Optional[Dict[str, Any]] = None
        self.state_tree: Dict[str, Any] = {}
        self.updater_state: Optional[Dict[str, Any]] = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[TrainingListener] = []
        self.last_batch_size: Optional[int] = None
        self._loss_tracker = LossTracker()
        self._rng = jax.random.PRNGKey(conf.seed)
        self._stateful: set = set()           # layers with persistent state (BN)
        self._layer_updaters: Dict[str, Updater] = {}
        self._jit_caches: Dict[Any, Dict[Any, Any]] = {}
        # rnnTimeStep statefulness, lock-guarded (ISSUE 7: the bare-attr
        # version was an unlocked shared-state mutation)
        self._decode_state = DecodeState()
        self._solvers: Dict[Any, Any] = {}      # full-batch solver cache

    @property
    def score_(self) -> Optional[float]:
        """Most recent training loss as a float. Reading this MATERIALIZES
        the deferred device loss (forces a host sync) — cheap after epoch
        end, a pipeline stall if polled every step mid-fit."""
        return self._loss_tracker.value

    @score_.setter
    def score_(self, value) -> None:
        self._loss_tracker.set(value)

    # ------------------------------------------------------------- init
    def init(self) -> "MultiLayerNetwork":
        """Initialize params/state. Reference: `MultiLayerNetwork.init():446`."""
        key = jax.random.PRNGKey(self.conf.seed)
        params, states = {}, {}
        it = self.conf.input_type
        for i, layer in enumerate(self.layers):
            if it is not None and i in self.conf.preprocessors:
                it = self.conf.preprocessors[i].output_type(it)
            key, sub = jax.random.split(key)
            p, s = layer.init_params(sub, it, self.dtype)
            params[layer.name] = p
            states[layer.name] = s
            if s:
                self._stateful.add(layer.name)
            if it is not None:
                it = layer.output_type(it)
        self.params_tree = params
        self.state_tree = states
        self._build_updaters()
        self.updater_state = {
            name: u.init(params[name]) for name, u in self._layer_updaters.items()
        }
        return self

    def _build_updaters(self):
        """Per-layer updaters honoring per-layer overrides + freezing.
        Reference: `nn/updater/MultiLayerUpdater` / UpdaterBlock grouping."""
        global_u = resolve_updater(self.conf.updater or "sgd")
        for layer in self.layers:
            u = layer.updater if layer.updater is not None else global_u
            u = resolve_updater(u)
            if layer.learning_rate is not None and hasattr(u, "learning_rate"):
                u = dataclasses.replace(u, learning_rate=layer.learning_rate)
            if layer.frozen:
                u = NoOp()
            self._layer_updaters[layer.name] = u

    # ---------------------------------------------------------- forward
    def _forward(self, params, states, x, *, train: bool, rng, fmask=None,
                 carries: Optional[Dict[str, Any]] = None,
                 collect: bool = False):
        """Run the stack; returns (final_out, out_layer_input, new_states,
        activations?). Reference: `feedForward:752-858`."""
        acts = []
        new_states = {}
        out_in = x
        n = len(self.layers)
        for i, layer in enumerate(self.layers):
            if i in self.conf.preprocessors:
                x = self.conf.preprocessors[i].apply(x, fmask)
            if layer.is_output_layer and i == n - 1:
                out_in = x
            st = states.get(layer.name) or None
            if carries is not None and layer.name in carries:
                st = carries[layer.name]
            lrng = None if rng is None else jax.random.fold_in(rng, i)
            if (train and self.conf.gradient_checkpointing
                    and not (layer.is_output_layer and i == n - 1)):
                # remat this layer's activations in the backward pass
                # (memory ∝ depth → memory ∝ 1, +~33% FLOPs); the output
                # layer is skipped — its input is retained for the loss
                # anyway
                x, new_st = _checkpointed(layer.apply, fmask)(
                    params[layer.name], x, st, lrng)
            else:
                x, new_st = layer.apply(
                    params[layer.name], x, state=st, train=train,
                    rng=lrng, mask=fmask)
            new_states[layer.name] = new_st
            if collect:
                acts.append(x)
        return x, out_in, new_states, acts

    # ------------------------------------------------------------- loss
    def _loss(self, params, states, features, labels, fmask, lmask, rng,
              train: bool = True, carries=None):
        """Score = output-layer loss + L1/L2 regularization.
        Reference: `computeGradientAndScore():2047` + calcL1/calcL2."""
        out, out_in, new_states, _ = self._forward(
            params, states, features, train=train, rng=rng, fmask=fmask,
            carries=carries,
        )
        out_layer = self.layers[-1]
        score_mask = lmask if lmask is not None else (
            fmask if labels is not None and labels.ndim == 3 else None
        )
        if isinstance(out_layer, CenterLossOutputLayer):
            score, cstate = out_layer.score_and_state(
                params[out_layer.name], out_in, labels,
                states[out_layer.name], score_mask,
            )
            new_states[out_layer.name] = cstate
        else:
            score = out_layer.score(params[out_layer.name], out_in, labels, score_mask)
        reg = sum(
            layer.regularization(params[layer.name]) for layer in self.layers
        )
        # Activity-dependent auxiliary losses (e.g. MoE load balancing)
        # reported through layer state — added INSIDE the differentiated
        # closure so they contribute gradients.
        aux = sum(
            st["aux_loss"] for st in new_states.values()
            if isinstance(st, dict) and "aux_loss" in st
        )
        return score + reg + aux, new_states

    # ------------------------------------------------------ train step
    def make_step_fn(self, tbptt: bool = False):
        """The pure (un-jitted) train-step function — also consumed by the
        parallel trainers, which re-jit it with mesh shardings (DP/TP),
        the way the reference's ParallelWrapper wraps the same model fit."""
        return self._build_step(( False, False, tbptt), jit=False)

    def _get_train_step(self, key):
        if key in self._jit_cache:
            return self._jit_cache[key]
        fn = self._build_step(key, jit=True)
        self._jit_cache[key] = fn
        # read back through the cache: __setitem__ may have wrapped the
        # callable in the watchdog's cost/comm probe, and returning the
        # raw local lets the FIRST dispatch bypass the ledger
        return self._jit_cache[key]

    @property
    def _rnn_layer_names(self):
        """Layers that carry RNN state (tBPTT / rnnTimeStep persistence)."""
        if not hasattr(self, "_rnn_names_cache"):
            self._rnn_names_cache = [
                l.name for l in self.layers if _is_recurrent(l)]
        return self._rnn_names_cache

    @property
    def _decode_layer_names(self):
        """Layers with KV-cache decode carries (attention stepping)."""
        if not hasattr(self, "_decode_names_cache"):
            self._decode_names_cache = [
                l.name for l in self.layers if hasattr(l, "decode_carry")]
        return self._decode_names_cache

    def _build_step(self, key, jit: bool):
        has_fmask, has_lmask, tbptt = key[0], key[1], key[2]
        mode = self.conf.gradient_normalization
        thr = self.conf.gradient_normalization_threshold
        updaters = self._layer_updaters
        stateful = self._stateful
        rnn_names = self._rnn_layer_names

        def step_fn(params, opt_state, states, step, features, labels,
                    fmask, lmask, rng, carries):
            def loss_fn(p):
                return self._loss(p, states, features, labels, fmask, lmask,
                                  rng, train=True, carries=carries)

            (loss, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = _normalize_grads(grads, mode, thr)
            new_params, new_opt = {}, {}
            for name, u in updaters.items():
                # One seam for the whole read-modify-write: the default
                # is apply() + dtype-preserving subtract exactly as
                # before; Adam/Nesterovs may route through the one-pass
                # fused Pallas kernel (ops/fused_update.py) when the
                # measured policy selects it.
                new_params[name], new_opt[name] = u.update_with_params(
                    grads[name], opt_state[name], params[name], step)
            persist = {
                n: (new_states[n] if n in stateful else states.get(n, {}))
                for n in states
            }
            out_carries = {
                n: _tmap(jax.lax.stop_gradient, new_states[n]) for n in rnn_names
            } if tbptt else {}
            return new_params, new_opt, persist, loss, out_carries

        if not jit:
            return step_fn
        # donatemon.instrument is identity with DL4J_TPU_DONATEMON off;
        # on, it witnesses the (params, opt_state, states) donation.
        return donatemon.instrument(
            jax.jit(step_fn, donate_argnums=(0, 1, 2)), (0, 1, 2),
            name="MultiLayerNetwork._step",
            arg_names=("params", "opt_state", "states"))

    # ---------------------------------------------------------- fit API
    def fit(self, data, labels=None, *, epochs: int = 1, batch_size: int = 32,
            steps_per_dispatch: int = 1, device_prefetch: bool = True,
            sync_every: int = 0, checkpointer=None, checkpoint_every: int = 1,
            resume=None, stop_fn=None, preemption=None):
        """Train. Accepts arrays, a DataSet, a DataSetIterator, or any
        iterable of DataSets. Reference: `fit(DataSetIterator):1046`
        (+ tBPTT dispatch `:1102`), pipelined per the async-dispatch
        contract (PERF_NOTES):

        - the loss stays on device; ``score_`` materializes it lazily
          (``sync_every=N`` forces a float every N steps for listeners)
        - ``device_prefetch`` double-buffers the host→device transfer of
          batch N+1 behind batch N's compute
        - ``steps_per_dispatch=K`` (opt-in) fuses K same-shape batches
          into one `lax.scan` dispatch; tBPTT batches and non-SGD solvers
          fall back to per-step dispatch automatically

        Recovery knobs (see `optim/recovery.RecoveryPlan`): pass a
        ``checkpointer`` (`ShardedCheckpointer`) for continuous async
        checkpoints every ``checkpoint_every`` iterations; ``resume``
        (`"auto"` or a position dict) for exact mid-epoch resume;
        ``stop_fn`` / ``preemption=True`` to stop cleanly at a batch
        boundary with a final exact-position snapshot. None of these add
        a per-step host sync.
        """
        self._check_init()
        plan = build_plan(self, checkpointer=checkpointer,
                          checkpoint_every=checkpoint_every, resume=resume,
                          stop_fn=stop_fn, preemption=preemption)
        it = as_iterator(data, labels, batch_size)
        if device_prefetch:
            it = DevicePrefetchIterator(
                it, depth=max(2, int(steps_per_dispatch)),
                transform=self._cast_batch)
        self._loss_tracker.sync_every = int(sync_every)
        execu = TrainingExecutor(
            self,
            step=self._dispatch_batch,
            fused_step=self._fused_dispatch,
            can_fuse=self._can_fuse,
            steps_per_dispatch=steps_per_dispatch,
            before_batch=plan.before_batch if plan else None,
            after_dispatch=plan.after_dispatch if plan else None,
            epoch_start=plan.epoch_start if plan else None,
            epoch_end=plan.epoch_end if plan else None,
        )
        run_with_recovery(execu, plan, it, epochs)
        self.stopped_early = execu.stopped
        return self

    def _cast_batch(self, ds: DataSet) -> DataSet:
        """Pre-cast features to the model dtype so the prefetch transfer
        carries the bytes the step actually consumes (bf16 nets ship half
        the data)."""
        f = ds.features
        if hasattr(f, "dtype") and f.dtype != self.dtype:
            ds = DataSet(np.asarray(f, self.dtype), ds.labels,
                         ds.features_mask, ds.labels_mask)
        return ds

    def _dispatch_batch(self, ds: DataSet):
        if self.conf.tbptt_fwd_length > 0 and ds.features.ndim == 3:
            return self._fit_tbptt(ds)
        return self._fit_batch(ds)

    def _can_fuse(self, ds: DataSet) -> bool:
        """Fused dispatch needs the plain SGD step: tBPTT chunks and
        full-batch solvers require per-step host control flow."""
        return (self.conf.optimization_algo == "stochastic_gradient_descent"
                and not (self.conf.tbptt_fwd_length > 0
                         and ds.features.ndim == 3))

    def _get_fused_step(self, key, k: int):
        cache_key = ("fused", key, k)
        if cache_key in self._jit_cache:
            return self._jit_cache[cache_key]
        base = self._build_step(key, jit=False)

        def fused(params, opt_state, states, step0, rng, feats, labs, fms,
                  lms):
            # rng rides in the carry and splits INSIDE the scan — same
            # `self._rng, k = split(self._rng)` chain as the K=1 path
            # (bit-identical subkeys), but zero per-step host dispatches.
            def body(carry, xs):
                p, o, s, step, r = carry
                f, l, fm, lm = xs
                r, sub = jax.random.split(r)
                new_p, new_o, persist, loss, _ = base(
                    p, o, s, step, f, l, fm, lm, sub, None)
                return (new_p, new_o, persist, step + 1, r), loss

            (params, opt_state, states, _, rng), losses = jax.lax.scan(
                body, (params, opt_state, states, step0, rng),
                (feats, labs, fms, lms))
            return params, opt_state, states, rng, losses

        fn = donatemon.instrument(
            jax.jit(fused, donate_argnums=(0, 1, 2)), (0, 1, 2),
            name="MultiLayerNetwork._fused_step",
            arg_names=("params", "opt_state", "states"))
        self._jit_cache[cache_key] = fn
        # read back through the cache (probe wrapping; see _get_train_step)
        return self._jit_cache[cache_key]

    def _fused_dispatch(self, batches: List[DataSet]):
        """Run K stacked same-shape batches as ONE `lax.scan` dispatch.
        Returns the (K,) per-step losses as a device array."""
        first = batches[0]
        self._check_input(first.features)
        self.last_batch_size = first.num_examples()
        self._last_features = batches[-1].features
        key = (first.features_mask is not None,
               first.labels_mask is not None, False)
        fn = self._get_fused_step(key, len(batches))

        def stk(get, dtype=None):
            vals = [get(b) for b in batches]
            if vals[0] is None:
                return None
            if all(isinstance(v, np.ndarray) for v in vals):
                # host-resident batches: one np.stack + ONE device transfer
                # instead of K asarray dispatches + a device concat
                return jnp.asarray(np.stack(vals), dtype)
            return jnp.stack([jnp.asarray(v, dtype) for v in vals])

        (self.params_tree, self.updater_state, self.state_tree, self._rng,
         losses) = fn(self.params_tree, self.updater_state, self.state_tree,
                      np.int32(self.iteration), self._rng,
                      stk(lambda b: b.features, self.dtype),
                      stk(lambda b: b.labels),
                      stk(lambda b: b.features_mask),
                      stk(lambda b: b.labels_mask))
        return losses

    def _split_rng(self):
        self._rng, k = jax.random.split(self._rng)
        return k

    def _check_init(self):
        if self.params_tree is None:
            raise RuntimeError(
                "Network not initialized — call net.init() before "
                "fit()/output()/score() (reference: MultiLayerNetwork.init())"
            )

    def _check_input(self, x):
        it = self.conf.input_type
        if it is None:
            return
        expect = it.shape(int(x.shape[0]))
        if it.kind == "rnn" and it.timesteps is None:
            ok = x.ndim == 3 and x.shape[-1] == it.size
        else:
            ok = tuple(x.shape) == tuple(expect)
        if not ok:
            raise ValueError(
                f"Input shape {tuple(x.shape)} does not match configured "
                f"{it!r} (expected {tuple(expect)} for batch={x.shape[0]})"
            )

    def _fit_batch(self, ds: DataSet) -> float:
        self._check_input(ds.features)
        self.last_batch_size = ds.num_examples()
        self._last_features = ds.features   # for listener activation stats
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            # Full-batch solver path (CG / LBFGS / line GD) — reference:
            # Solver.java builds the configured optimizer per fit call.
            from deeplearning4j_tpu.optim.solvers import fit_with_solver

            return fit_with_solver(
                self, jnp.asarray(ds.features, self.dtype),
                None if ds.labels is None else jnp.asarray(ds.labels),
                None if ds.features_mask is None
                else jnp.asarray(ds.features_mask),
                None if ds.labels_mask is None
                else jnp.asarray(ds.labels_mask))
        key = (ds.features_mask is not None, ds.labels_mask is not None, False)
        fn = self._get_train_step(key)
        (self.params_tree, self.updater_state, self.state_tree, loss, _
         ) = fn(self.params_tree, self.updater_state, self.state_tree,
                jnp.asarray(self.iteration, jnp.int32),
                jnp.asarray(ds.features, self.dtype),
                None if ds.labels is None else jnp.asarray(ds.labels),
                None if ds.features_mask is None else jnp.asarray(ds.features_mask),
                None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
                self._split_rng(), None)
        # Deferred sync: the loss stays on device — LossTracker/score_
        # materializes it only on demand (async-dispatch contract).
        return loss

    def _fit_tbptt(self, ds: DataSet) -> float:
        """Truncated BPTT: slice time into fwd-length chunks, carry RNN
        state across chunks with stop_gradient. When tbptt_back_length <
        tbptt_fwd_length, the first (fwd - back) steps of each chunk only
        advance the carries (no gradient, no update) and the train step
        covers the last `back` steps — gradients flow at most back_length
        steps, the reference's fwd != back truncation
        (`MultiLayerNetwork.java:1102-1104,1351`)."""
        L = self.conf.tbptt_fwd_length
        Lb = min(self.conf.tbptt_back_length or L, L)
        T = ds.features.shape[1]
        if ds.labels is None or ds.labels.ndim != 3:
            raise ValueError(
                "Truncated BPTT requires per-timestep (3-D [batch, time, "
                "n_out]) labels, as the reference's doTruncatedBPTT does; for "
                "sequence-level labels use tbptt_fwd_length=0"
            )
        key = (ds.features_mask is not None, ds.labels_mask is not None, True)
        fn = self._get_train_step(key)
        carries = {}
        losses = []
        for lo in range(0, T, L):
            hi = min(lo + L, T)
            t_lo = lo
            if Lb < hi - lo:
                t_lo = hi - Lb
                carries = self._advance_carries(
                    jnp.asarray(ds.features[:, lo:t_lo], self.dtype),
                    None if ds.features_mask is None
                    else jnp.asarray(ds.features_mask[:, lo:t_lo]),
                    carries)
            sl = lambda a: None if a is None else jnp.asarray(a[:, t_lo:hi])
            (self.params_tree, self.updater_state, self.state_tree, loss,
             carries) = fn(
                self.params_tree, self.updater_state, self.state_tree,
                jnp.asarray(self.iteration, jnp.int32),
                jnp.asarray(ds.features[:, t_lo:hi], self.dtype),
                sl(ds.labels), sl(ds.features_mask), sl(ds.labels_mask),
                self._split_rng(), carries if carries else None)
            losses.append(loss)
        self.last_batch_size = ds.num_examples()
        # Mean on device — one divide instead of len(losses) host syncs.
        return jnp.stack(losses).mean()

    def _advance_carries(self, feats, fmask, carries):
        """Gradient-free forward that only moves the RNN carries along —
        the no-update prefix of a fwd>back tBPTT chunk."""
        key = ("advance", fmask is not None, bool(carries))
        if key not in self._jit_cache:
            rnn_names = self._rnn_layer_names

            def adv(params, states, x, fm, car):
                _, _, new_states, _ = self._forward(
                    params, states, x, train=False, rng=None, fmask=fm,
                    carries=car)
                return {n: new_states[n] for n in rnn_names}

            self._jit_cache[key] = jax.jit(adv)
        return self._jit_cache[key](
            self.params_tree, self.state_tree, feats, fmask,
            carries if carries else None)

    # -------------------------------------------------------- inference
    def output(self, x, train: bool = False):
        """Forward to final activations. Reference: `output:1716-1827`."""
        self._check_init()
        self._check_input(np.asarray(x) if not hasattr(x, "shape") else x)
        key = ("output", train)
        if key not in self._jit_cache:
            def out_fn(params, states, feats):
                y, _, _, _ = self._forward(
                    params, states, feats, train=train, rng=None)
                return y
            self._jit_cache[key] = jax.jit(out_fn)
        return self._jit_cache[key](
            self.params_tree, self.state_tree, jnp.asarray(x, self.dtype))

    def feed_forward(self, x, train: bool = False) -> List[jax.Array]:
        """All per-layer activations. Reference: `feedForward:752`."""
        _, _, _, acts = self._forward(
            self.params_tree, self.state_tree, jnp.asarray(x, self.dtype),
            train=train, rng=None, collect=True)
        return acts

    def score(self, data, labels=None) -> float:
        """Mean loss on data, as ONE jitted computation (an eager _loss
        call here would retrace per invocation). Reference:
        `score(DataSet)`."""
        ds = data if isinstance(data, DataSet) else DataSet(
            np.asarray(data), np.asarray(labels))
        key = ("score", ds.features_mask is not None,
               ds.labels_mask is not None)
        if key not in self._jit_cache:
            def score_fn(params, states, feats, labs, fm, lm):
                loss, _ = self._loss(params, states, feats, labs, fm, lm,
                                     None, train=False)
                return loss
            self._jit_cache[key] = jax.jit(score_fn)
        loss = self._jit_cache[key](
            self.params_tree, self.state_tree,
            jnp.asarray(ds.features, self.dtype),
            None if ds.labels is None else jnp.asarray(ds.labels),
            None if ds.features_mask is None else jnp.asarray(ds.features_mask),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask))
        return float(loss)

    def predict(self, x) -> np.ndarray:
        """Argmax class predictions. Reference: `predict(INDArray)`."""
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def evaluate(self, iterator: DataSetIterator):
        """Reference: `MultiLayerNetwork.evaluate(DataSetIterator)`.

        For plain per-example classification the argmax happens ON DEVICE
        and only int32 class indices cross to host (the full softmax
        round-trip only happens for masked/time-series labels, which the
        Evaluation flattens host-side)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        self._check_init()
        key = ("eval_argmax",)
        if key not in self._jit_cache:
            def pred_fn(params, states, feats):
                y, _, _, _ = self._forward(params, states, feats,
                                           train=False, rng=None)
                return jnp.argmax(y, axis=-1).astype(jnp.int32)
            self._jit_cache[key] = jax.jit(pred_fn)

        def predict_indices(feats):
            self._check_input(np.asarray(feats))
            idx = self._jit_cache[key](
                self.params_tree, self.state_tree,
                jnp.asarray(feats, self.dtype))
            return idx, getattr(self.layers[-1], "n_out", None)

        return Evaluation().evaluate_iterator(
            iterator, output_fn=self.output,
            predict_indices_fn=predict_indices)

    def evaluate_regression(self, iterator: DataSetIterator):
        """Reference: `MultiLayerNetwork.evaluateRegression:2668`."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation

        self._check_init()
        return Evaluation.run_evaluation(
            RegressionEvaluation(), iterator, self.output)

    def evaluate_roc(self, iterator: DataSetIterator,
                     threshold_steps: int = 0):
        """Binary ROC. Reference: `evaluateROC:2679`."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.eval.roc import ROC

        self._check_init()
        return Evaluation.run_evaluation(
            ROC(threshold_steps), iterator, self.output)

    def evaluate_roc_multi_class(self, iterator: DataSetIterator):
        """One-vs-all ROC per class. Reference:
        `evaluateROCMultiClass:2690`."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.eval.roc import ROCMultiClass

        self._check_init()
        return Evaluation.run_evaluation(
            ROCMultiClass(), iterator, self.output)

    # ----------------------------------------------------- rnn stepping
    @property
    def _rnn_carries(self):
        """Read view of the ambient stepping carries (the mutable path
        lives inside `DecodeState`, lock-guarded)."""
        return self._decode_state.carries

    @property
    def _decode_pos(self):
        return self._decode_state.pos

    def _validate_causal_decode(self, layers, what="rnn_time_step"):
        """Validate ALL before seeding ANY carries: a mid-loop raise
        would leave partial carries behind and disarm the guard."""
        for l in layers:
            if not getattr(l, "causal", True):
                raise ValueError(
                    f"{what} requires causal attention; layer "
                    f"{l.name!r} is non-causal (stepped decoding cannot "
                    f"see future tokens, so it cannot reproduce a "
                    f"bidirectional forward)")

    def rnn_time_step(self, x):
        """Stateful single-step inference; carries persist across calls.
        Reference: `rnnTimeStep` + `rnnClearPreviousState`. Attention
        stacks step the same way: layers exposing `decode_carry` (KV
        cache, position offset) are seeded on the first call, so a
        transformer generates token-by-token without re-running the
        prefix. The whole read-step-write runs under the decode-state
        lock, so concurrent callers serialize instead of corrupting each
        other's carries (serving threads its carries through
        `session_step` arguments instead and never touches this state)."""
        x = jnp.asarray(x, self.dtype)
        if x.ndim == 2:
            x = x[:, None, :]
        stateful = set(self._rnn_layer_names) | set(self._decode_layer_names)
        st = self._decode_state
        with st.lock():
            if not st.carries and self._decode_layer_names:
                decode = [l for l in self.layers
                          if hasattr(l, "decode_carry")]
                self._validate_causal_decode(decode)
                st.seed({l.name: l.decode_carry(x.shape[0], self.dtype)
                         for l in decode})
            if self._decode_layer_names:
                _check_decode_budget(
                    self,
                    (l for l in self.layers if hasattr(l, "decode_carry")),
                    x.shape[1])
            carries = st.carries or None
            # One jitted program per (step shape, carry presence): token-
            # by-token decoding is a fixed-shape loop, so eager per-op
            # dispatch (a device round-trip per op per token) would
            # dominate on TPU.
            key = ("rnn_step", x.shape, carries is not None)
            if key not in self._jit_cache:
                def step_fn(params, states, feats, carries_):
                    out, _, new_states, _ = self._forward(
                        params, states, feats, train=False, rng=None,
                        carries=carries_)
                    return out, {n: new_states[n] for n in stateful}

                self._jit_cache[key] = jax.jit(step_fn)
            out, new_carries = self._jit_cache[key](
                self.params_tree, self.state_tree, x, carries)
            # advance only after a successful step (a raise above or a
            # trace failure must not burn decode budget)
            st.update(new_carries,
                      advance=x.shape[1] if self._decode_layer_names else 0)
        return out

    def rnn_clear_previous_state(self):
        self._decode_state.clear()

    def rnn_reorder_state(self, idx) -> None:
        """Reorder (or expand) the stateful-decoding carries along the
        batch dimension — beam-search reselection gathers each beam's KV
        cache/h/c rows to follow its chosen parent. Every non-scalar
        carry leaf is batch-leading by the decode-carry contract
        (`decode_carry`/`initial_carry`); scalar leaves (decode
        positions) are shared across the batch and pass through."""
        ix = jnp.asarray(np.asarray(idx))
        self._decode_state.reorder(lambda carries: jax.tree_util.tree_map(
            lambda a: a[ix] if getattr(a, "ndim", 0) >= 1 else a, carries))

    # ------------------------------------------- slot-indexed sessions
    def decode_limit(self) -> Optional[int]:
        """Smallest non-rolling cache/position bound across decode
        layers (None = unbounded, e.g. a pure rolling-cache stack) — the
        serving session manager's host-side budget ceiling."""
        return _decode_limit(
            l for l in self.layers if hasattr(l, "decode_carry"))

    def session_carries(self, slots: int, kv_dtype: Optional[str] = None,
                        page_len: Optional[int] = None,
                        pages: Optional[int] = None):
        """Batched slot-indexed decode carries for `slots` independent
        sessions: attention layers get PER-SLOT position vectors
        (`decode_carry(per_slot=True)`), recurrent layers their h/c
        carries (mask-gated per step, so padded chunks hold them on pad
        tokens). This is the KVSlotPool's backing tree — pure data, no
        model-global state.

        `kv_dtype` ("native"/None, "int8", "fp8") selects the attention
        caches' storage dtype — quantized carries gain per-(token,
        kv-head) scale rows next to each cache (see
        `MultiHeadAttention.decode_carry`).

        `page_len` switches every attention cache to the PAGED layout
        (fixed [pages, page_len, Hkv, Dh] block pools + per-slot page
        tables — the prefix-cache storage; see `decode_carry`). One
        logical page id must mean the same physical row in EVERY layer's
        pool, so paged mode requires a uniform `max_cache` across decode
        layers (`prefix_cache_capable` checks the same). `pages`
        defaults to `slots * max_cache / page_len` per layer — the
        monolithic layout's exact memory."""
        self._check_init()
        decode = [l for l in self.layers if hasattr(l, "decode_carry")]
        rnn = [l for l in self.layers if _is_recurrent(l)]
        if not decode and not rnn:
            raise ValueError(
                "session_carries needs at least one stateful decode "
                "layer (attention decode_carry or recurrent carry)")
        for l in rnn:
            if isinstance(l, (Bidirectional, GravesBidirectionalLSTM,
                              LastTimeStep)):
                raise ValueError(
                    f"session decoding is causal left-to-right; layer "
                    f"{l.name!r} ({type(l).__name__}) cannot stream")
        self._validate_causal_decode(decode, what="session decoding")
        if page_len is not None:
            caches = {l.max_cache for l in decode
                      if hasattr(l, "max_cache")}
            if len(caches) > 1:
                raise ValueError(
                    f"paged session carries need a uniform max_cache "
                    f"across decode layers (one logical page id = one "
                    f"physical row in every layer's pool); got {sorted(caches)}")
            if pages is None and caches:
                pages = slots * (next(iter(caches)) // page_len)
        carries = {l.name: l.decode_carry(slots, self.dtype, per_slot=True,
                                          kv_dtype=kv_dtype,
                                          page_len=page_len, pages=pages)
                   if page_len is not None else
                   l.decode_carry(slots, self.dtype, per_slot=True,
                                  kv_dtype=kv_dtype)
                   for l in decode}
        for l in rnn:
            carries[l.name] = l.initial_carry(slots, self.dtype)
        return carries

    def spec_decode_capable(self) -> bool:
        """Can this net serve as a speculative-decode draft or target?
        The windows below un-write rejected tokens by REWINDING the
        per-slot positions — stale cache entries past `pos` are invisible
        (`k_ids <= pos`) and get overwritten by the next window. That
        trick needs every stateful carry to be position-addressed:
        recurrent h/c carries hold irreversible state, and rolling rings
        misattribute stale slots through their held-index arithmetic, so
        either disqualifies the net."""
        if self._rnn_layer_names:
            return False
        decode = [l for l in self.layers if hasattr(l, "decode_carry")]
        if not decode:
            return False
        return not any(getattr(l, "rolling_cache", False) for l in decode)

    def prefix_cache_capable(self) -> bool:
        """Can this net's session carries run PAGED (the prefix-cache
        storage)? Pages are position-addressed blocks, so the same
        rewind argument as `spec_decode_capable` applies (no recurrent
        carries, no rolling rings — both hold state a shared page cannot
        represent), plus one structural condition: every decode layer's
        `max_cache` must agree, because one logical page id must mean
        the same physical row in every layer's block pool."""
        if not self.spec_decode_capable():
            return False
        caches = {l.max_cache for l in self.layers
                  if hasattr(l, "decode_carry") and hasattr(l, "max_cache")}
        return len(caches) == 1

    _PAGE_POOL_KEYS = ("cache_k", "cache_v", "scale_k", "scale_v")

    @classmethod
    def _lane_merge(cls, old_tree, new_tree, act):
        """Revert inactive lanes' carry writes: slot-indexed leaves get
        a per-lane `where`. PAGED cache leaves (physical page pools —
        leading dim is pages, shared across slots) pass through
        untouched instead: a slot mask cannot address a page pool, and
        it does not need to — every paged write path is valid-masked at
        the scatter (invalid/inactive targets push out of range and
        `mode="drop"` discards them), so an inactive lane never dirtied
        a page in the first place."""
        paged = any(
            getattr(p[-1], "key", None) == "page_table"
            for p, _ in jax.tree_util.tree_leaves_with_path(new_tree))

        def lane(path, old, nw):
            if paged and getattr(path[-1], "key", None) \
                    in cls._PAGE_POOL_KEYS:
                return nw
            a = act.reshape(
                (-1,) + (1,) * (getattr(nw, "ndim", 1) - 1))
            return jnp.where(a, nw, old)

        return jax.tree_util.tree_map_with_path(lane, old_tree, new_tree)

    def session_step(self, x, carries, *, active=None, valid=None):
        """One slot-indexed decode step: carries and per-slot positions
        are ARGUMENTS threaded through the jitted program, not model
        state — any mix of sessions can ride one dispatch.

        `x` is [S, T, F] (S = slot count; T = the chunk bucket), `valid`
        an optional [S, T] prefix mask (1.0 = real token) letting short
        chunks and idle lanes share the padded bucket shape, `active` an
        optional [S] bool vector — inactive lanes' carries pass through
        unchanged (their lanes compute, their writes are masked, their
        outputs are garbage to be ignored). Returns (out, new_carries).

        One compiled program per (x.shape, active?, valid?) — the
        fixed-shape decode contract the recompile watchdog polices."""
        self._check_init()
        x = jnp.asarray(x, self.dtype)
        if x.ndim == 2:
            x = x[:, None, :]
        stateful = set(self._rnn_layer_names) | set(self._decode_layer_names)
        key = ("session_step", x.shape,
               active is not None, valid is not None)
        if key not in self._jit_cache:
            def step_fn(params, states, feats, carries_, active_, valid_):
                out, _, new_states, _ = self._forward(
                    params, states, feats, train=False, rng=None,
                    fmask=valid_, carries=carries_)
                new = {n: new_states[n] for n in stateful}
                if active_ is not None:
                    new = self._lane_merge(carries_, new, active_)
                return out, new

            self._jit_cache[key] = jax.jit(step_fn)
        return self._jit_cache[key](
            self.params_tree, self.state_tree, x, carries,
            None if active is None else jnp.asarray(active, bool),
            None if valid is None else jnp.asarray(valid, self.dtype))

    def session_decode_window(self, tokens, carries, *, active, k,
                              temperature, top_k, top_p, greedy,
                              keys, offsets, budgets, eos_ids):
        """K fused decode steps in ONE dispatch: a `lax.scan` that
        forwards each active lane's next token, samples on-device
        (utils/sampling.sample_token_lanes — greedy/temperature/top-k/
        top-p as lax ops), feeds the sample back in, and early-exits
        per lane on EOS or budget via the active mask — finished lanes
        stop writing carries without breaking the fixed shape. This is
        the decode twin of the training executor's fused-K machinery:
        one host round-trip buys K tokens.

        Arguments (S = slot count; everything per-lane so one compiled
        program serves any request mix — the zero-recompile contract):

        - ``tokens``   i32[S]    first input token per lane (the last
          prompt token on the first window, the previous window's last
          sample afterwards)
        - ``carries``  the KVSlotPool tree from :meth:`session_carries`
        - ``active``   bool[S]   lanes that decode this window
        - ``k``        python int, the window length (bucketed by the
          caller; part of the compile key)
        - ``temperature/top_k/top_p/greedy``  f32/i32/f32/bool [S]
        - ``keys``     u32[S, 2] per-lane base rng keys; token i of a
          lane always draws with fold_in(key, offsets+i), so streams
          are invariant to K and to how sessions share dispatches
        - ``offsets``  i32[S]    tokens already generated per lane
        - ``budgets``  i32[S]    remaining token budget per lane
        - ``eos_ids``  i32[S]    per-lane EOS id (-1 = none)

        Returns ``(tokens [S, k] i32, emitted [S, k] bool,
        new_carries)`` — positions where ``emitted`` is False carry -1
        and must be ignored (lane finished mid-window or was inactive).
        Greedy output is bit-exact against running the same program
        with k=1 K times: same per-step forwards, same carry merges —
        the parity contract tests/test_fused_decode.py pins."""
        from deeplearning4j_tpu.nn.layers.feedforward import (
            EmbeddingSequenceLayer,
        )
        from deeplearning4j_tpu.utils import sampling as _sampling

        self._check_init()
        k = int(k)
        if k < 1:
            raise ValueError(f"window length k must be >= 1, got {k}")
        tokens = jnp.asarray(tokens, jnp.int32)
        ids_input = isinstance(self.layers[0], EmbeddingSequenceLayer)
        feat = 1 if ids_input else int(self.layers[0].n_in)
        stateful = set(self._rnn_layer_names) | set(self._decode_layer_names)
        key = ("session_decode_window", k, tokens.shape, ids_input)
        if key not in self._jit_cache:
            def window_fn(params, states, tok0, carries_, active_, temps,
                          tks, tps, grdy, keys_, offs, buds, eos):
                dt = self.dtype

                def encode(tok):
                    if ids_input:
                        return tok[:, None, None].astype(dt)
                    return jax.nn.one_hot(tok, feat, dtype=dt)[:, None, :]

                def body(carry, _):
                    tok, c, act, n = carry
                    val = act.astype(dt)[:, None]
                    out, _, new_states, _ = self._forward(
                        params, states, encode(tok), train=False, rng=None,
                        fmask=val, carries=c)
                    new = {nm: new_states[nm] for nm in stateful}
                    new = self._lane_merge(c, new, act)
                    step_keys = jax.vmap(jax.random.fold_in)(keys_, offs + n)
                    nxt = _sampling.sample_token_lanes(
                        out[:, -1, :], temps, tks, tps, grdy, step_keys)
                    emit = act
                    n2 = n + emit.astype(jnp.int32)
                    finished = emit & ((nxt == eos) | (n2 >= buds))
                    return ((jnp.where(emit, nxt, tok), new,
                             act & jnp.logical_not(finished), n2),
                            (jnp.where(emit, nxt, -1), emit))

                init = (tok0, carries_, active_, jnp.zeros_like(offs))
                (_, cf, _, _), (toks, emits) = jax.lax.scan(
                    body, init, None, length=k)
                return (jnp.transpose(toks), jnp.transpose(emits), cf)

            self._jit_cache[key] = jax.jit(window_fn)
        return self._jit_cache[key](
            self.params_tree, self.state_tree, tokens, carries,
            jnp.asarray(active, bool), jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_k, jnp.int32), jnp.asarray(top_p, jnp.float32),
            jnp.asarray(greedy, bool), jnp.asarray(keys, jnp.uint32),
            jnp.asarray(offsets, jnp.int32), jnp.asarray(budgets, jnp.int32),
            jnp.asarray(eos_ids, jnp.int32))

    # ------------------------------------------- speculative decoding
    #
    # The draft/target window pair below shares one invariant: every
    # stateful carry is POSITION-ADDRESSED (linear caches + per-slot
    # positions — `spec_decode_capable` gates the rest out), so a
    # rejected token is un-written by rewinding `pos`: stale entries
    # past `pos` are invisible (`k_ids <= pos`) and the next window's
    # scatter overwrites them. Bookkeeping per window, for a lane that
    # accepted n_acc of k draft tokens and emitted n = n_acc + 1:
    #   target: verify writes k+1 entries, pos snaps back to old + n
    #   draft:  propose wrote k entries ([t0, d_1..d_{k-1}]); the next
    #           window enters with rewind = max(k - n, 0); on full
    #           acceptance (n = k+1) the draft lacks d_k's KV, so the
    #           next propose catch-up-writes it (pre_tokens/pre_valid)

    # rng stream salts: acceptance uniforms and residual/bonus draws
    # come from streams independent of both models' sampling draws
    # (fold_in(fold_in(base_key, SALT), position)) — the rejection
    # rule's correctness assumes the acceptance coin is independent of
    # the proposal.
    _SPEC_U_SALT = 0x5EC0DE
    _SPEC_R_SALT = 0xDEC0DE5

    @staticmethod
    def _pos_rewind(carries, delta):
        """Subtract `delta` [S] from every per-slot `pos` leaf (the
        decode-carry trees are nested dicts whose position leaves are
        always keyed "pos")."""
        def walk(node):
            if isinstance(node, dict):
                out = {}
                for kk, vv in node.items():
                    if kk == "pos":
                        out[kk] = vv - delta.astype(vv.dtype)
                    else:
                        out[kk] = walk(vv)
                return out
            return node
        return walk(carries)

    def session_propose_window(self, tokens, carries, *, active, k,
                               temperature, top_k, top_p, greedy, keys,
                               offsets, rewind, pre_tokens, pre_valid):
        """The DRAFT half of a speculative window: k sequential decode
        steps in one dispatch, sampling each proposal on-device and
        recording the warped distribution it was drawn from (the q the
        rejection rule needs). Entry bookkeeping per lane: `rewind` [S]
        is subtracted from the draft positions (un-writing proposals the
        target rejected last window) and, where `pre_valid`, one masked
        catch-up step writes `pre_tokens`' KV first (the fully-accepted
        d_k whose cache entry the draft never wrote). Proposal draws use
        the SAME stream as the non-speculative sampler
        (fold_in(base_key, offsets + i)); no EOS/budget early-exit — the
        target's verify applies the cuts.

        Returns ``(draft_tokens [S, k] i32, draft_probs [S, k, V] f32,
        new_carries)``."""
        from deeplearning4j_tpu.nn.layers.feedforward import (
            EmbeddingSequenceLayer,
        )
        from deeplearning4j_tpu.utils import sampling as _sampling

        self._check_init()
        k = int(k)
        if k < 1:
            raise ValueError(f"draft window k must be >= 1, got {k}")
        tokens = jnp.asarray(tokens, jnp.int32)
        ids_input = isinstance(self.layers[0], EmbeddingSequenceLayer)
        feat = 1 if ids_input else int(self.layers[0].n_in)
        stateful = set(self._rnn_layer_names) | set(self._decode_layer_names)
        key = ("session_propose_window", k, tokens.shape, ids_input)
        if key not in self._jit_cache:
            def propose_fn(params, states, tok0, carries_, active_, temps,
                           tks, tps, grdy, keys_, offs, rew, ptok, pval):
                dt = self.dtype

                def encode(tok):
                    if ids_input:
                        return tok[:, None, None].astype(dt)
                    return jax.nn.one_hot(tok, feat, dtype=dt)[:, None, :]

                def lane_merge(mask, old_tree, new_tree):
                    return self._lane_merge(old_tree, new_tree, mask)

                carries_ = self._pos_rewind(
                    carries_, jnp.where(active_, rew, 0))
                cu = active_ & pval
                _, _, cu_states, _ = self._forward(
                    params, states, encode(ptok), train=False, rng=None,
                    fmask=cu.astype(dt)[:, None], carries=carries_)
                carries_ = lane_merge(
                    cu, carries_, {nm: cu_states[nm] for nm in stateful})

                def body(carry, i):
                    tok, c = carry
                    out, _, new_states, _ = self._forward(
                        params, states, encode(tok), train=False, rng=None,
                        fmask=active_.astype(dt)[:, None], carries=c)
                    new = lane_merge(
                        active_, c, {nm: new_states[nm] for nm in stateful})
                    p = out[:, -1, :].astype(jnp.float32)
                    pw = _sampling.warp_probs_lanes(p, temps, tks, tps)
                    step_keys = jax.vmap(jax.random.fold_in)(keys_, offs + i)
                    logp = jnp.where(pw > 0.0, jnp.log(pw), -jnp.inf)
                    drawn = jax.vmap(jax.random.categorical)(
                        step_keys, logp).astype(jnp.int32)
                    g_tok = jnp.argmax(p, axis=-1).astype(jnp.int32)
                    nxt = jnp.where(grdy, g_tok, drawn)
                    return ((jnp.where(active_, nxt, tok), new), (nxt, pw))

                (_, cf), (toks, pws) = jax.lax.scan(
                    body, (tok0, carries_), jnp.arange(k))
                return (jnp.transpose(toks), jnp.moveaxis(pws, 0, 1), cf)

            self._jit_cache[key] = jax.jit(propose_fn)
        return self._jit_cache[key](
            self.params_tree, self.state_tree, tokens, carries,
            jnp.asarray(active, bool), jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_k, jnp.int32), jnp.asarray(top_p, jnp.float32),
            jnp.asarray(greedy, bool), jnp.asarray(keys, jnp.uint32),
            jnp.asarray(offsets, jnp.int32), jnp.asarray(rewind, jnp.int32),
            jnp.asarray(pre_tokens, jnp.int32),
            jnp.asarray(pre_valid, bool))

    def session_verify_window(self, tokens, carries, *, active, k,
                              draft_tokens, draft_probs, temperature,
                              top_k, top_p, greedy, keys, offsets,
                              budgets, eos_ids):
        """The TARGET half of a speculative window: ONE chunked forward
        over [t0, d_1..d_k] scores every draft position, accept/reject
        runs on device (utils/sampling.spec_accept_lanes — greedy
        longest-prefix fast path, standard rejection rule otherwise),
        EOS/budget prefix cuts apply, and the target positions snap back
        to old + n_emit so rejected entries are rewound. An alive lane
        always emits n_acc + 1 tokens (its accepted prefix plus the
        correction/bonus token), so the chain advances every window.

        Returns ``(packed [S, k+4] i32, new_carries)`` where packed rows
        are ``[n_emit, n_acc, last_draft, tok_0..tok_k]`` (-1 past
        n_emit) — one device array so the manager's single post-lock
        readback covers counts, catch-up token, and emissions together.
        `n_acc` (the acceptance verdict BEFORE the EOS/budget cuts) rides
        along so the manager can count exactly the accepted drafts that
        were actually emitted — ``min(n_acc, n_emit)`` — instead of
        inferring them from n_emit alone, which mis-counts when a fully
        verified window is truncated by the token budget."""
        from deeplearning4j_tpu.nn.layers.feedforward import (
            EmbeddingSequenceLayer,
        )
        from deeplearning4j_tpu.utils import sampling as _sampling

        self._check_init()
        k = int(k)
        if k < 1:
            raise ValueError(f"verify window k must be >= 1, got {k}")
        tokens = jnp.asarray(tokens, jnp.int32)
        draft_tokens = jnp.asarray(draft_tokens, jnp.int32)
        ids_input = isinstance(self.layers[0], EmbeddingSequenceLayer)
        feat = 1 if ids_input else int(self.layers[0].n_in)
        stateful = set(self._rnn_layer_names) | set(self._decode_layer_names)
        key = ("session_verify_window", k, tokens.shape, ids_input)
        if key not in self._jit_cache:
            def verify_fn(params, states, tok0, carries_, active_, d_toks,
                          q_pw, temps, tks, tps, grdy, keys_, offs, buds,
                          eos):
                dt = self.dtype
                chunk = jnp.concatenate([tok0[:, None], d_toks], axis=1)
                if ids_input:
                    x = chunk[:, :, None].astype(dt)
                else:
                    x = jax.nn.one_hot(chunk, feat, dtype=dt)
                val = active_.astype(dt)[:, None] * jnp.ones((1, k + 1), dt)
                out, _, new_states, _ = self._forward(
                    params, states, x, train=False, rng=None, fmask=val,
                    carries=carries_)
                p_raw = out.astype(jnp.float32)            # [S, k+1, V]
                pw = jax.vmap(
                    lambda pp: _sampling.warp_probs_lanes(
                        pp, temps, tks, tps),
                    in_axes=1, out_axes=1)(p_raw)

                def lane_u(key_, off):
                    sk = jax.random.fold_in(key_, self._SPEC_U_SALT)
                    return jax.vmap(
                        lambda i: jax.random.uniform(
                            jax.random.fold_in(sk, off + i)))(jnp.arange(k))

                u = jax.vmap(lane_u)(keys_, offs)          # [S, k]
                extra_keys = jax.vmap(
                    lambda key_, off: jax.random.fold_in(
                        jax.random.fold_in(key_, self._SPEC_R_SALT), off)
                )(keys_, offs)
                n_acc, extra = _sampling.spec_accept_lanes(
                    p_raw, pw, q_pw, d_toks, grdy, u, extra_keys)

                idx = jnp.arange(k + 1)[None, :]
                d_pad = jnp.concatenate(
                    [d_toks, jnp.zeros_like(d_toks[:, :1])], axis=1)
                cand = jnp.where(idx == n_acc[:, None], extra[:, None],
                                 d_pad)
                base = idx <= n_acc[:, None]
                eos_hit = base & (cand == eos[:, None]) & (eos[:, None] >= 0)
                prior_eos = jnp.cumsum(eos_hit, axis=1) - eos_hit
                emitted = (base & (prior_eos == 0)
                           & (idx < buds[:, None]) & active_[:, None])
                n_emit = emitted.sum(axis=1).astype(jnp.int32)
                toks_out = jnp.where(emitted, cand, -1)

                new = self._lane_merge(
                    carries_, {nm: new_states[nm] for nm in stateful},
                    active_)
                # position snap-back: the forward advanced active lanes
                # by k+1; the confirmed history is old + n_emit
                demit = jnp.where(active_, n_emit, 0)

                def fix(path, old_leaf, new_leaf):
                    # graft: allow(GL003): `path` is static pytree
                    # structure from tree_map_with_path, not a tracer
                    if getattr(path[-1], "key", None) == "pos":
                        return old_leaf + demit.astype(old_leaf.dtype)
                    return new_leaf

                new = jax.tree_util.tree_map_with_path(
                    fix, carries_, new)
                packed = jnp.concatenate(
                    [n_emit[:, None], n_acc[:, None].astype(jnp.int32),
                     d_toks[:, -1:], toks_out], axis=1)
                return packed.astype(jnp.int32), new

            self._jit_cache[key] = jax.jit(verify_fn)
        return self._jit_cache[key](
            self.params_tree, self.state_tree, tokens, carries,
            jnp.asarray(active, bool), draft_tokens,
            jnp.asarray(draft_probs, jnp.float32),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_k, jnp.int32), jnp.asarray(top_p, jnp.float32),
            jnp.asarray(greedy, bool), jnp.asarray(keys, jnp.uint32),
            jnp.asarray(offsets, jnp.int32), jnp.asarray(budgets, jnp.int32),
            jnp.asarray(eos_ids, jnp.int32))

    # -------------------------------------------------------- pretrain
    def pretrain(self, data, *, epochs: int = 1, batch_size: int = 32):
        """Greedy layerwise unsupervised pretraining for pretrainable layers
        (AutoEncoder/RBM/VAE). Reference: `pretrain:214-301`."""
        it = as_iterator(data, None, batch_size)
        for idx, layer in enumerate(self.layers):
            if not layer.is_pretrainable:
                continue
            updater = self._layer_updaters[layer.name]
            opt = updater.init(self.params_tree[layer.name])

            def featurize(feats):
                x = feats
                for j in range(idx):
                    if j in self.conf.preprocessors:
                        x = self.conf.preprocessors[j].apply(x)
                    x, _ = self.layers[j].apply(
                        self.params_tree[self.layers[j].name], x,
                        state=self.state_tree.get(self.layers[j].name) or None,
                        train=False, rng=None)
                if idx in self.conf.preprocessors:
                    x = self.conf.preprocessors[idx].apply(x)
                return x

            @jax.jit
            # graft: allow(GL103): one program per pretrained layer by
            # design — layerwise pretraining compiles each layer once
            def pre_step(lp, opt_state, step, feats, rng):
                x = featurize(feats)

                def loss_fn(p):
                    return layer.reconstruction_score(p, x, rng=rng)

                loss, grads = jax.value_and_grad(loss_fn)(lp)
                new_lp, new_opt = updater.update_with_params(
                    grads, opt_state, lp, step)
                return new_lp, new_opt, loss

            step = 0
            for _ in range(epochs):
                for ds in it:
                    lp, opt, loss = pre_step(
                        self.params_tree[layer.name], opt,
                        jnp.asarray(step, jnp.int32),
                        jnp.asarray(ds.features, self.dtype), self._split_rng())
                    self.params_tree[layer.name] = lp
                    step += 1
        return self

    # ----------------------------------------------------- param views
    def params(self) -> np.ndarray:
        """Single flat parameter vector. Reference: `Model.params()`."""
        flat, _ = flatten_params(self.params_tree)
        return np.asarray(flat)

    def set_params(self, flat) -> None:
        self.params_tree = unflatten_params(
            jnp.asarray(flat), self.params_tree)

    def num_params(self) -> int:
        return param_count(self.params_tree)

    def set_listeners(self, *listeners: TrainingListener) -> None:
        self.listeners = list(listeners)

    def add_listener(self, l: TrainingListener) -> None:
        self.listeners.append(l)

    def clone(self) -> "MultiLayerNetwork":
        """Deep copy (new runtime, copied params). Reference: MLN.clone()."""
        other = MultiLayerNetwork(self.conf)
        other.init()
        if self.params_tree is not None:
            other.params_tree = _tmap(lambda a: a, self.params_tree)
            other.state_tree = jax.tree_util.tree_map(lambda a: a, self.state_tree)
        return other
