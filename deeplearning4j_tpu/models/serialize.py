"""Model checkpointing: zip container + sharded native checkpoints.

Reference parity: `util/ModelSerializer.java:37-119` — zip of
`configuration.json` + `coefficients.bin` (flat params) + `updaterState.bin`;
restoreMultiLayerNetwork / restoreComputationGraph. Our zip holds the same
three logical artifacts (JSON config, params, updater state) plus a metadata
record (iteration/epoch/model class/format version) the reference lacked —
enabling exact training resume.

For TPU-scale models the zip (host-gathered, single-file) is the
compatibility path; `CheckpointManager` below wraps Orbax for sharded,
async checkpoints of pjit-sharded params (the reference has no sharded
checkpoint story — SURVEY §5 'no sharded checkpoints').
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Any, Dict, Optional, Union

import jax
import numpy as np

FORMAT_VERSION = 1

_CONFIG_JSON = "configuration.json"
_COEFFICIENTS = "coefficients.npz"
_UPDATER_STATE = "updaterState.npz"
_NET_STATE = "netState.npz"
_METADATA = "metadata.json"


def _tree_to_flat_dict(tree, prefix="") -> Dict[str, np.ndarray]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_tree_to_flat_dict(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_tree_to_flat_dict(v, f"{prefix}{i}/"))
    elif tree is None or (isinstance(tree, tuple) and not tree):
        pass
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _flat_dict_to_tree(flat: Dict[str, np.ndarray], like) -> Any:
    """Rebuild a pytree with `like`'s structure from path-keyed arrays."""
    def rebuild(sub, prefix):
        if isinstance(sub, dict):
            return {k: rebuild(v, f"{prefix}{k}/") for k, v in sub.items()}
        if isinstance(sub, (list, tuple)):
            vals = [rebuild(v, f"{prefix}{i}/") for i, v in enumerate(sub)]
            return type(sub)(vals)
        key = prefix.rstrip("/")
        if key in flat:
            return jax.numpy.asarray(flat[key])
        return sub
    return rebuild(like, "")


def _save_npz(zf: zipfile.ZipFile, name: str, tree) -> None:
    flat = _tree_to_flat_dict(tree)
    buf = io.BytesIO()
    np.savez(buf, **flat) if flat else np.savez(buf, __empty__=np.zeros(0))
    zf.writestr(name, buf.getvalue())


def _load_npz(zf: zipfile.ZipFile, name: str) -> Dict[str, np.ndarray]:
    with zf.open(name) as f:
        data = np.load(io.BytesIO(f.read()))
        return {k: data[k] for k in data.files if k != "__empty__"}


def save_model(net, path: Union[str, os.PathLike], *,
               save_updater: bool = True) -> None:
    """Reference: `ModelSerializer.writeModel:52,79`."""
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.models.multilayer import MultiLayerNetwork

    cls = ("ComputationGraph" if isinstance(net, ComputationGraph)
           else "MultiLayerNetwork")
    meta = {
        "format_version": FORMAT_VERSION,
        "model_class": cls,
        "iteration": net.iteration,
        "epoch": net.epoch,
        "framework": "deeplearning4j_tpu",
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(_CONFIG_JSON, net.conf.to_json())
        _save_npz(zf, _COEFFICIENTS, net.params_tree)
        _save_npz(zf, _NET_STATE, net.state_tree)
        if save_updater and net.updater_state is not None:
            _save_npz(zf, _UPDATER_STATE, net.updater_state)
        zf.writestr(_METADATA, json.dumps(meta, indent=2))


def load_model(path: Union[str, os.PathLike], *, load_updater: bool = True):
    """Reference: `ModelSerializer.restoreMultiLayerNetwork` /
    `restoreComputationGraph` (class auto-detected from metadata)."""
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.models.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration

    with zipfile.ZipFile(path, "r") as zf:
        meta = json.loads(zf.read(_METADATA))
        config_json = zf.read(_CONFIG_JSON).decode()
        if meta["model_class"] == "ComputationGraph":
            conf = ComputationGraphConfiguration.from_json(config_json)
            net = ComputationGraph(conf)
        else:
            conf = MultiLayerConfiguration.from_json(config_json)
            net = MultiLayerNetwork(conf)
        net.init()
        coeffs = _load_npz(zf, _COEFFICIENTS)
        net.params_tree = _flat_dict_to_tree(coeffs, net.params_tree)
        if _NET_STATE in zf.namelist():
            states = _load_npz(zf, _NET_STATE)
            net.state_tree = _flat_dict_to_tree(states, net.state_tree)
        if load_updater and _UPDATER_STATE in zf.namelist():
            upd = _load_npz(zf, _UPDATER_STATE)
            net.updater_state = _flat_dict_to_tree(upd, net.updater_state)
        net.iteration = meta.get("iteration", 0)
        net.epoch = meta.get("epoch", 0)
    return net


class CheckpointManager:
    """Sharded async checkpoints via Orbax — the TPU-native path for
    pjit-sharded params (capability extension beyond the reference; see
    module docstring). Falls back gracefully when orbax is unavailable."""

    def __init__(self, directory: Union[str, os.PathLike], *,
                 max_to_keep: int = 3, async_save: bool = True):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=async_save),
        )

    def save(self, step: int, net) -> None:
        payload = {
            "params": net.params_tree,
            "state": net.state_tree,
            "updater": net.updater_state,
            "meta": {"iteration": net.iteration, "epoch": net.epoch},
        }
        self._mgr.save(step, args=self._ocp.args.StandardSave(payload))

    def restore(self, net, step: Optional[int] = None):
        step = step if step is not None else self._mgr.latest_step()
        target = {
            "params": net.params_tree,
            "state": net.state_tree,
            "updater": net.updater_state,
            "meta": {"iteration": 0, "epoch": 0},
        }
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(target))
        net.params_tree = restored["params"]
        net.state_tree = restored["state"]
        net.updater_state = restored["updater"]
        net.iteration = int(restored["meta"]["iteration"])
        net.epoch = int(restored["meta"]["epoch"])
        return net

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
