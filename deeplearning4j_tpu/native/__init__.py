"""Native (C++) host runtime: threshold codec, record decoding, staging arena.

The reference's native substrate enters through external deps — libnd4j's
threshold-compression ops (EncodingHandler.java:65), DataVec record readers,
and ND4J MemoryWorkspace (SURVEY.md §2.8). Here the equivalents are C++
sources under ``csrc/`` compiled on demand with g++ into one shared library
and bound via ctypes; every entry point has a NumPy fallback so the package
works (slower) where no compiler is present.

The TPU compute path never goes through here — XLA owns device kernels.
This is the HOST side: feeding, compressing, staging.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_SRC_DIR = os.path.join(os.path.dirname(__file__), "csrc")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libdl4jtpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _sources():
    return sorted(
        os.path.join(_SRC_DIR, f)
        for f in os.listdir(_SRC_DIR) if f.endswith(".cpp"))


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > lib_mtime for s in _sources())


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = _LIB_PATH + ".tmp"
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
           "-o", tmp] + _sources()
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return False
    os.replace(tmp, _LIB_PATH)
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, i32p, u8p, f32p = (ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
                            ctypes.POINTER(ctypes.c_uint8),
                            ctypes.POINTER(ctypes.c_float))
    lib.dl4j_threshold_encode.restype = i64
    lib.dl4j_threshold_encode.argtypes = [f32p, i64, ctypes.c_float, i32p,
                                          u8p, i64]
    lib.dl4j_threshold_decode.restype = None
    lib.dl4j_threshold_decode.argtypes = [f32p, i64, ctypes.c_float, i32p,
                                          u8p, i64]
    lib.dl4j_csv_parse.restype = i64
    lib.dl4j_csv_parse.argtypes = [ctypes.c_char_p, i64, ctypes.c_char, f32p,
                                   i64, ctypes.POINTER(i64),
                                   ctypes.POINTER(i64)]
    lib.dl4j_idx_header.restype = i64
    lib.dl4j_idx_header.argtypes = [u8p, i64, ctypes.POINTER(ctypes.c_int32),
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.POINTER(i64)]
    lib.dl4j_u8_to_f32.restype = None
    lib.dl4j_u8_to_f32.argtypes = [u8p, i64, ctypes.c_float, f32p]
    lib.dl4j_one_hot.restype = None
    lib.dl4j_one_hot.argtypes = [i32p, i64, ctypes.c_int32, f32p]
    lib.dl4j_w2v_parse.restype = i64
    lib.dl4j_w2v_parse.argtypes = [u8p, i64, i64, i64, f32p, u8p, i64,
                                   ctypes.POINTER(i64)]
    lib.dl4j_arena_create.restype = ctypes.c_void_p
    lib.dl4j_arena_create.argtypes = [i64]
    lib.dl4j_arena_destroy.restype = None
    lib.dl4j_arena_destroy.argtypes = [ctypes.c_void_p]
    lib.dl4j_arena_alloc.restype = ctypes.c_void_p
    lib.dl4j_arena_alloc.argtypes = [ctypes.c_void_p, i64, i64]
    lib.dl4j_arena_reset.restype = None
    lib.dl4j_arena_reset.argtypes = [ctypes.c_void_p]
    lib.dl4j_arena_used.restype = i64
    lib.dl4j_arena_used.argtypes = [ctypes.c_void_p]
    lib.dl4j_arena_high_water.restype = i64
    lib.dl4j_arena_high_water.argtypes = [ctypes.c_void_p]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if the
    toolchain is unavailable (callers fall back to NumPy)."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if _needs_build() and not _build():
                _build_failed = True
                return None
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _build_failed = True
            return None
    return _lib


def available() -> bool:
    return get_lib() is not None


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


# ---------------------------------------------------------------------------
# Threshold codec (EncodingHandler.java:26-102 equivalent).

def threshold_encode(grad: np.ndarray, threshold: float,
                     max_elements: Optional[int] = None
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Strom-style 1-bit sparse encoding of a flat float32 gradient.

    Mutates ``grad`` in place to hold the residual (the part below the
    threshold, accumulated for later rounds). Returns (indices int32,
    signs uint8 — 1 for +threshold, 0 for -threshold).
    """
    if grad.dtype != np.float32 or not grad.flags["C_CONTIGUOUS"]:
        raise ValueError("grad must be C-contiguous float32")
    n = grad.size
    cap = n if max_elements is None else min(int(max_elements), n)
    lib = get_lib()
    if lib is not None:
        idx = np.empty(cap, dtype=np.int32)
        signs = np.empty(cap, dtype=np.uint8)
        m = lib.dl4j_threshold_encode(_f32p(grad), n, ctypes.c_float(threshold),
                                      _i32p(idx), _u8p(signs), cap)
        return idx[:m].copy(), signs[:m].copy()
    flat = grad.reshape(-1)
    hits = np.flatnonzero(np.abs(flat) >= threshold)[:cap]
    signs = (flat[hits] > 0).astype(np.uint8)
    flat[hits] -= np.where(signs, threshold, -threshold).astype(np.float32)
    return hits.astype(np.int32), signs


def threshold_decode(target: np.ndarray, threshold: float, indices: np.ndarray,
                     signs: np.ndarray) -> None:
    """Applies a sparse encoded update into ``target`` in place."""
    if target.dtype != np.float32 or not target.flags["C_CONTIGUOUS"]:
        raise ValueError("target must be C-contiguous float32")
    lib = get_lib()
    if lib is not None:
        idx = np.ascontiguousarray(indices, dtype=np.int32)
        sg = np.ascontiguousarray(signs, dtype=np.uint8)
        lib.dl4j_threshold_decode(_f32p(target), target.size,
                                  ctypes.c_float(threshold), _i32p(idx),
                                  _u8p(sg), idx.size)
        return
    flat = target.reshape(-1)
    idx = indices.astype(np.int64)
    ok = (idx >= 0) & (idx < flat.size)  # native path skips out-of-range too
    np.add.at(flat, idx[ok],
              np.where(signs.astype(bool)[ok], threshold, -threshold)
              .astype(np.float32))


# ---------------------------------------------------------------------------
# Record decoding (DataVec equivalent).

def parse_csv(text: str, delimiter: str = ",") -> np.ndarray:
    """Numeric CSV text → float32 matrix [rows, cols]."""
    lib = get_lib()
    if lib is None:
        rows = [r for r in text.splitlines() if r.strip()]
        return np.asarray(
            [[float(v) if _is_num(v) else 0.0 for v in r.split(delimiter)]
             for r in rows], dtype=np.float32)
    raw = text.encode()
    # Worst case one value per two bytes.
    cap = max(16, len(raw))
    out = np.empty(cap, dtype=np.float32)
    n_rows = ctypes.c_int64()
    n_cols = ctypes.c_int64()
    written = lib.dl4j_csv_parse(raw, len(raw), ctypes.c_char(
        delimiter.encode()), _f32p(out), cap, ctypes.byref(n_rows),
        ctypes.byref(n_cols))
    if written < 0:
        raise ValueError("csv buffer overflow")
    r, c = n_rows.value, n_cols.value
    if r * c != written:
        raise ValueError("ragged csv rows")
    return out[:written].reshape(r, c).copy()


def _is_num(v: str) -> bool:
    try:
        float(v)
        return True
    except ValueError:
        return False


def read_idx(data: bytes) -> np.ndarray:
    """IDX (MNIST ubyte/int/float) container → ndarray.

    Replaces the reference's MnistManager binary readers
    (deeplearning4j-core/.../datasets/mnist/)."""
    dtype_map = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                 0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}
    lib = get_lib()
    buf = np.frombuffer(data, dtype=np.uint8)
    if lib is not None:
        dt = ctypes.c_int32()
        nd = ctypes.c_int32()
        dims = (ctypes.c_int64 * 8)()
        off = lib.dl4j_idx_header(_u8p(buf), buf.size, ctypes.byref(dt),
                                  ctypes.byref(nd), dims)
        if off < 0:
            raise ValueError("bad idx header")
        shape = tuple(dims[i] for i in range(nd.value))
        np_dt = dtype_map[dt.value]
    else:
        if len(data) < 4 or data[0] or data[1]:
            raise ValueError("bad idx header")
        np_dt = dtype_map[data[2]]
        nd_ = data[3]
        shape = tuple(int.from_bytes(data[4 + 4 * i:8 + 4 * i], "big")
                      for i in range(nd_))
        off = 4 + 4 * nd_
    arr = np.frombuffer(data, dtype=np.dtype(np_dt).newbyteorder(">"),
                        offset=int(off))
    return arr.reshape(shape).astype(np_dt)


def u8_to_f32(pixels: np.ndarray, scale: float = 1.0 / 255.0) -> np.ndarray:
    """uint8 image bytes → scaled float32 (pixel normalisation hot loop)."""
    pixels = np.ascontiguousarray(pixels, dtype=np.uint8)
    lib = get_lib()
    if lib is None:
        return pixels.astype(np.float32) * scale
    out = np.empty(pixels.shape, dtype=np.float32)
    lib.dl4j_u8_to_f32(_u8p(pixels), pixels.size, ctypes.c_float(scale),
                       _f32p(out))
    return out


def one_hot(labels: np.ndarray, num_classes: int) -> np.ndarray:
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    lib = get_lib()
    if lib is None:
        out = np.zeros((labels.size, num_classes), dtype=np.float32)
        ok = (labels >= 0) & (labels < num_classes)
        out[np.arange(labels.size)[ok], labels[ok]] = 1.0
        return out
    out = np.empty((labels.size, num_classes), dtype=np.float32)
    lib.dl4j_one_hot(_i32p(labels), labels.size, num_classes, _f32p(out))
    return out


def w2v_parse(body: bytes, n_words: int, dim: int):
    """Google word2vec binary body (after the "V D\\n" header) →
    (words list[str], vectors [V, D] float32) in one C++ scan with bulk
    vector memcpy — the host-side hot path for GB-scale pretrained
    embedding loads (WordVectorSerializer.loadGoogleModel equivalent).

    Returns None when the native library is unavailable or the host is
    big-endian (format floats are little-endian); callers then use their
    Python path."""
    import sys

    lib = get_lib()
    if lib is None or sys.byteorder != "little":
        return None
    buf = np.frombuffer(body, dtype=np.uint8)
    vecs = np.empty((n_words, dim), dtype=np.float32)
    # Tight word-bytes bound: the body is words + separators + vectors,
    # so word bytes <= body - vectors (allocating the full body size
    # would double peak memory on GB-scale loads).
    words_cap = max(buf.size - n_words * dim * 4, 1)
    words_buf = np.empty(words_cap, dtype=np.uint8)
    offsets = np.zeros(n_words + 1, dtype=np.int64)
    consumed = lib.dl4j_w2v_parse(
        _u8p(buf), buf.size, n_words, dim, _f32p(vecs), _u8p(words_buf),
        words_buf.size,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if consumed < 0:
        raise ValueError(
            "malformed word2vec binary body (truncated record, missing "
            "separator, or empty word)")
    words = [bytes(words_buf[offsets[i]:offsets[i + 1]]).decode("utf-8")
             for i in range(n_words)]
    return words, vecs


# ---------------------------------------------------------------------------
# Staging arena (MemoryWorkspace host-side equivalent).

class Workspace:
    """Bump-allocated host staging arena for input-pipeline batches.

    Allocate numpy views inside the arena, feed them to the device, then
    ``reset()`` to reuse the memory next batch — the host-side analogue of
    ND4J's cyclic MemoryWorkspace (SURVEY.md §2.8 item 1). Falls back to
    plain numpy allocation without the native library.
    """

    def __init__(self, capacity_bytes: int):
        self.capacity = int(capacity_bytes)
        lib = get_lib()
        self._lib = lib
        self._views: list = []  # weakrefs to issued arrays (UAF guard)
        self._handle = (lib.dl4j_arena_create(self.capacity)
                        if lib is not None else None)
        if lib is not None and not self._handle:
            raise MemoryError("arena allocation failed")

    def alloc(self, shape, dtype=np.float32, align: int = 128) -> np.ndarray:
        dtype = np.dtype(dtype)
        if self._handle is None:
            return np.empty(shape, dtype=dtype)
        size = int(np.prod(shape)) * dtype.itemsize
        ptr = self._lib.dl4j_arena_alloc(self._handle, size, align)
        if not ptr:
            raise MemoryError(
                f"workspace exhausted ({self.used}/{self.capacity} bytes)")
        buf = (ctypes.c_char * size).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
        import weakref
        self._views.append(weakref.ref(arr))
        return arr

    def reset(self) -> None:
        if self._handle is not None:
            self._lib.dl4j_arena_reset(self._handle)
        self._views = [r for r in self._views if r() is not None]

    @property
    def used(self) -> int:
        return (self._lib.dl4j_arena_used(self._handle)
                if self._handle is not None else 0)

    @property
    def high_water(self) -> int:
        return (self._lib.dl4j_arena_high_water(self._handle)
                if self._handle is not None else 0)

    def close(self, force: bool = False) -> None:
        """Frees the arena. Refuses (unless force=True) while arrays
        allocated from it are still referenced — their memory would be
        freed under them (use-after-free)."""
        if self._handle is None:
            return
        if not force:
            live = sum(1 for r in self._views if r() is not None)
            if live:
                raise RuntimeError(
                    f"workspace still has {live} live array view(s); drop "
                    "them first or close(force=True)")
        self._lib.dl4j_arena_destroy(self._handle)
        self._handle = None
        self._views = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close(force=True)  # GC decided: nothing can reach the views
        except Exception:  # graft: allow(GL403): __del__ must never raise
            pass
