// Threshold gradient compression codec (Strom-style 1-bit with residual).
//
// TPU-native equivalent of the reference's native threshold-encoding ops
// consumed by EncodingHandler
// (deeplearning4j-nn/.../optimize/solvers/accumulation/EncodingHandler.java:65
//  calls Nd4j.getExecutioner().thresholdEncode(...), implemented in libnd4j).
// On TPU, intra-slice gradient exchange rides ICI via XLA psum and needs no
// compression; this codec is for the DCN-side exchange between hosts
// (parameter-server-style async updates, SURVEY.md §5 "Distributed
// communication backend"), where bandwidth is scarce.
//
// Encoding: for every |g[i]| >= t, emit index i and a sign bit; subtract
// sign*t from g in place, so g retains the residual for later rounds.

#include <cstdint>
#include <cstring>

extern "C" {

// Returns number of encoded elements (clipped at max_out). grad is modified
// in place to hold the residual.
int64_t dl4j_threshold_encode(float* grad, int64_t n, float threshold,
                              int32_t* idx_out, uint8_t* sign_out,
                              int64_t max_out) {
    int64_t m = 0;
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i];
        if (g >= threshold) {
            if (m >= max_out) return m;
            idx_out[m] = static_cast<int32_t>(i);
            sign_out[m] = 1;
            grad[i] = g - threshold;
            ++m;
        } else if (g <= -threshold) {
            if (m >= max_out) return m;
            idx_out[m] = static_cast<int32_t>(i);
            sign_out[m] = 0;
            grad[i] = g + threshold;
            ++m;
        }
    }
    return m;
}

// Applies a sparse encoded update into target: target[idx] += (+t | -t).
void dl4j_threshold_decode(float* target, int64_t n, float threshold,
                           const int32_t* idx, const uint8_t* signs,
                           int64_t m) {
    for (int64_t j = 0; j < m; ++j) {
        int32_t i = idx[j];
        if (i < 0 || i >= n) continue;
        target[i] += signs[j] ? threshold : -threshold;
    }
}

// Bit-packs sign+index into one int32 stream (sign in the top bit) for wire
// transport; returns bytes written into out (must hold 4*m bytes).
int64_t dl4j_threshold_pack(const int32_t* idx, const uint8_t* signs,
                            int64_t m, int32_t* out) {
    for (int64_t j = 0; j < m; ++j) {
        out[j] = (idx[j] & 0x7fffffff) | (signs[j] ? (int32_t)0x80000000 : 0);
    }
    return m * 4;
}

void dl4j_threshold_unpack(const int32_t* packed, int64_t m, int32_t* idx,
                           uint8_t* signs) {
    for (int64_t j = 0; j < m; ++j) {
        int32_t v = packed[j];
        idx[j] = v & 0x7fffffff;
        signs[j] = (v & (int32_t)0x80000000) ? 1 : 0;
    }
}

}  // extern "C"
