// Fast host-side record decoding: CSV → float32 matrix, IDX (MNIST) readers.
//
// TPU-native equivalent of the DataVec native record readers the reference
// consumes as an external Maven dep (SURVEY.md §2.8 item 3: RecordReaders
// feeding RecordReaderDataSetIterator). The host CPU must decode and stage
// batches fast enough to keep the TPU fed; Python-level parsing becomes the
// bottleneck at high samples/sec, so the inner parse loops live here.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parses delimiter-separated numeric text into out (row-major float32).
// Rows are '\n'-separated; empty rows skipped. Returns total values written,
// or -1 if out/max_vals is exceeded. n_rows/n_cols receive the matrix shape
// (n_cols = columns of the first non-empty row).
int64_t dl4j_csv_parse(const char* buf, int64_t len, char delim, float* out,
                       int64_t max_vals, int64_t* n_rows, int64_t* n_cols) {
    int64_t written = 0, rows = 0, cols = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        // Find end of line.
        const char* eol = p;
        while (eol < end && *eol != '\n') ++eol;
        if (eol > p && eol[-1] == '\r') {
            // Trim CR of CRLF by treating it as line end below.
        }
        const char* line_end = (eol > p && eol[-1] == '\r') ? eol - 1 : eol;
        bool blank = true;
        for (const char* q = p; q < line_end; ++q) {
            if (*q != ' ' && *q != '\t') { blank = false; break; }
        }
        if (!blank) {
            // Exactly one delimiter per field separator: a row with k
            // delimiters has k+1 fields; empty or non-numeric fields
            // parse as 0.0 (matches the Python fallback).
            int64_t row_cols = 0;
            const char* field = p;
            for (const char* q = p; q <= line_end; ++q) {
                if (q == line_end || *q == delim) {
                    float v = 0.0f;
                    if (q > field) {
                        char* next = nullptr;
                        double d = strtod(field, &next);
                        if (next != field && next <= q) {
                            v = static_cast<float>(d);
                        }
                    }
                    if (written >= max_vals) return -1;
                    out[written++] = v;
                    ++row_cols;
                    field = q + 1;
                }
            }
            if (rows == 0) cols = row_cols;
            ++rows;
        }
        p = eol + 1;
    }
    *n_rows = rows;
    *n_cols = cols;
    return written;
}

// IDX (MNIST ubyte) header parse. Returns data offset in bytes, or -1 on a
// malformed header. dims must hold up to 8 entries.
int64_t dl4j_idx_header(const uint8_t* buf, int64_t len, int32_t* dtype,
                        int32_t* ndim, int64_t* dims) {
    if (len < 4 || buf[0] != 0 || buf[1] != 0) return -1;
    *dtype = buf[2];
    int32_t nd = buf[3];
    if (nd <= 0 || nd > 8) return -1;
    if (len < 4 + 4 * nd) return -1;
    for (int32_t d = 0; d < nd; ++d) {
        const uint8_t* q = buf + 4 + 4 * d;
        dims[d] = ((int64_t)q[0] << 24) | ((int64_t)q[1] << 16) |
                  ((int64_t)q[2] << 8) | (int64_t)q[3];
    }
    *ndim = nd;
    return 4 + 4 * nd;
}

// uint8 → float32 with scale (e.g. 1/255 pixel normalisation).
void dl4j_u8_to_f32(const uint8_t* in, int64_t n, float scale, float* out) {
    for (int64_t i = 0; i < n; ++i) out[i] = in[i] * scale;
}

// One-hot encode int labels into a zeroed [n, k] float32 matrix.
void dl4j_one_hot(const int32_t* labels, int64_t n, int32_t k, float* out) {
    memset(out, 0, sizeof(float) * (size_t)(n * k));
    for (int64_t i = 0; i < n; ++i) {
        int32_t c = labels[i];
        if (c >= 0 && c < k) out[i * k + c] = 1.0f;
    }
}

}  // extern "C"
