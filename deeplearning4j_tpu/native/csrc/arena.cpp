// Host staging arena: bump allocator over one contiguous block.
//
// TPU-native equivalent of ND4J MemoryWorkspace (SURVEY.md §2.8 item 1 —
// "memory workspaces ... used pervasively, e.g. MultiLayerNetwork.java:
// 1078-1122"). On TPU the device side of workspaces is subsumed by XLA
// buffer donation; what remains is the HOST staging problem: batch arrays
// assembled by the input pipeline should reuse one arena instead of churning
// the Python allocator, so device feeds come from stable, aligned memory.

#include <cstdint>
#include <cstdlib>
#include <new>

namespace {
struct Arena {
    uint8_t* base;
    int64_t capacity;
    int64_t offset;
    int64_t high_water;
};
}  // namespace

extern "C" {

void* dl4j_arena_create(int64_t capacity) {
    void* mem = nullptr;
    if (posix_memalign(&mem, 128, (size_t)capacity) != 0) return nullptr;
    Arena* a = new (std::nothrow) Arena{static_cast<uint8_t*>(mem), capacity, 0, 0};
    if (!a) { free(mem); return nullptr; }
    return a;
}

void dl4j_arena_destroy(void* handle) {
    Arena* a = static_cast<Arena*>(handle);
    if (!a) return;
    free(a->base);
    delete a;
}

// Aligned bump allocation; returns nullptr when the arena is exhausted.
void* dl4j_arena_alloc(void* handle, int64_t size, int64_t align) {
    Arena* a = static_cast<Arena*>(handle);
    if (!a || align <= 0 || (align & (align - 1)) != 0) return nullptr;
    int64_t off = (a->offset + align - 1) & ~(align - 1);
    if (off + size > a->capacity) return nullptr;
    a->offset = off + size;
    if (a->offset > a->high_water) a->high_water = a->offset;
    return a->base + off;
}

// Cycle the workspace: previous allocations are invalidated, memory reused.
void dl4j_arena_reset(void* handle) {
    Arena* a = static_cast<Arena*>(handle);
    if (a) a->offset = 0;
}

int64_t dl4j_arena_used(void* handle) {
    Arena* a = static_cast<Arena*>(handle);
    return a ? a->offset : -1;
}

int64_t dl4j_arena_high_water(void* handle) {
    Arena* a = static_cast<Arena*>(handle);
    return a ? a->high_water : -1;
}

}  // extern "C"
