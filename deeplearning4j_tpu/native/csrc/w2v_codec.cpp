// Google word2vec binary-format body parser.
//
// Reference seam: WordVectorSerializer.loadGoogleModel(binary=true)
// (deeplearning4j-nlp/.../loader/WordVectorSerializer.java) — the
// reference reads GB-scale pretrained embedding files through a buffered
// JVM stream; here the host-side hot path is one C++ scan over the
// mapped bytes with bulk memcpy of the vectors (floats are stored
// little-endian; this parser assumes a little-endian host, which the
// ctypes binding asserts).

#include <cstdint>
#include <cstring>

extern "C" {

// Parse n_words records of [word bytes] ' ' [dim x f32] [optional '\n']
// from buf/len (the file body after the "V D\n" header).
//   vecs:          out, n_words * dim floats
//   words:         out, concatenated word bytes (caller-sized words_cap)
//   word_offsets:  out, n_words + 1 prefix offsets into words
// Returns bytes consumed, or -1 on truncated/malformed input or word
// buffer overflow.
int64_t dl4j_w2v_parse(const uint8_t* buf, int64_t len, int64_t n_words,
                       int64_t dim, float* vecs, uint8_t* words,
                       int64_t words_cap, int64_t* word_offsets) {
    int64_t p = 0, w = 0;
    const int64_t vec_bytes = dim * 4;
    for (int64_t i = 0; i < n_words; ++i) {
        while (p < len && (buf[p] == '\n' || buf[p] == '\r')) ++p;
        word_offsets[i] = w;
        const int64_t start = p;
        while (p < len && buf[p] != ' ') ++p;
        if (p >= len) return -1;                 // no space -> truncated
        const int64_t wl = p - start;
        if (wl == 0 || w + wl > words_cap) return -1;
        std::memcpy(words + w, buf + start, wl);
        w += wl;
        ++p;                                     // the separating space
        if (p + vec_bytes > len) return -1;      // truncated vector
        std::memcpy(vecs + static_cast<size_t>(i) * dim, buf + p,
                    vec_bytes);
        p += vec_bytes;
    }
    word_offsets[n_words] = w;
    return p;
}

}  // extern "C"
