"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of Deeplearning4j
(reference: marcelomata/deeplearning4j). Where the reference runs an eager,
op-at-a-time JVM runtime over libnd4j/cuDNN, this framework expresses every
model as pure-functional layer graphs compiled into ONE jitted, sharded XLA
computation per training step, with parallelism expressed as `jax.sharding`
annotations over a device mesh rather than threads/Spark/Aeron.

Top-level subpackages
---------------------
- ``nn``        layer/vertex configs + pure-functional implementations
                (reference: deeplearning4j-nn `nn/conf`, `nn/layers`)
- ``models``    MultiLayerNetwork / ComputationGraph runtimes
                (reference: `nn/multilayer/MultiLayerNetwork.java`,
                `nn/graph/ComputationGraph.java`)
- ``optim``     updaters, solver loop, listeners
                (reference: `nn/updater`, `optimize/`)
- ``eval``      Evaluation / ROC / regression metrics (reference: `eval/`)
- ``data``      DataSet, iterators, async prefetch, canned datasets
                (reference: deeplearning4j-core `datasets/`)
- ``parallel``  mesh/data/tensor/pipeline/sequence parallelism + inference
                (reference: deeplearning4j-scaleout — redesigned over ICI)
- ``nlp``       SequenceVectors/Word2Vec-class embedding training
                (reference: deeplearning4j-nlp-parent)
- ``zoo``       model catalog (reference: deeplearning4j-zoo)
- ``keras_import``  Keras .h5 importer (reference: deeplearning4j-modelimport)
- ``ops``       Pallas TPU kernels + custom XLA ops
- ``utils``     serde, pytree/param-view helpers, dtype policy
"""

__version__ = "0.2.0"

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.losses import LossFunction
from deeplearning4j_tpu.nn.initializers import WeightInit


_LAZY = {
    "NeuralNetConfiguration": ("deeplearning4j_tpu.nn.config",
                               "NeuralNetConfiguration"),
    "MultiLayerNetwork": ("deeplearning4j_tpu.models", "MultiLayerNetwork"),
    "ComputationGraph": ("deeplearning4j_tpu.models", "ComputationGraph"),
    "Evaluation": ("deeplearning4j_tpu.eval", "Evaluation"),
    "save_model": ("deeplearning4j_tpu.models.serialize", "save_model"),
    "load_model": ("deeplearning4j_tpu.models.serialize", "load_model"),
}


def __getattr__(name):
    """Lazy convenience access to the workhorse classes — avoids importing
    the heavier models/eval/serialize modules (and their transitive deps)
    until first use; resolved attributes are cached in the module dict so
    repeat accesses are plain lookups."""
    if name in _LAZY:
        import importlib

        mod, attr = _LAZY[name]
        value = getattr(importlib.import_module(mod), attr)
        globals()[name] = value
        return value
    raise AttributeError(f"module 'deeplearning4j_tpu' has no "
                         f"attribute {name!r}")


__all__ = [
    "InputType",
    "Activation",
    "LossFunction",
    "WeightInit",
    "NeuralNetConfiguration",
    "MultiLayerNetwork",
    "ComputationGraph",
    "Evaluation",
    "save_model",
    "load_model",
    "__version__",
]
