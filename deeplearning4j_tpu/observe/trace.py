"""Async-dispatch-safe tracing spans with a JSONL event log.

`span("fit.step")` times HOST-side work only. The contract that makes it
safe to leave enabled in the training hot loop (PERF_NOTES):

- A span never calls `float()` / `block_until_ready()` / `repr()` on a
  device value. Attributes are sanitized to plain JSON scalars; anything
  else (including a jax array) is recorded as its type name, NOT its
  value — recording the value would be a hidden host sync.
- When no span log is installed, `span()` is a no-op context manager
  (one global read + a null yield), so instrumented code paths cost
  nothing in production runs that don't trace.

Events are JSON lines: {"name", "ts", "dur_ms", "span_id", "parent_id",
"thread", "attrs"} — greppable, tailable by
`python -m deeplearning4j_tpu.observe.dump`, and correlatable with
`jax.profiler` trace windows: `ProfilerListener` emits a
"jax.profiler.trace" span bracketing each capture window into the same
log, so a wall-clock region in the span log can be matched to the
device timeline in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

_ids = itertools.count(1)
_tls = threading.local()
_active_log: Optional["SpanLog"] = None
_install_lock = threading.Lock()

# FlightRecorder ring (observe/flight.py) — when installed, every span
# event is ALSO appended to the crash ring, even with no SpanLog active.
# Set via _set_flight_sink (flight.py wires it) so this module never
# imports flight (no cycle).
_flight_sink = None


def _set_flight_sink(sink) -> None:
    global _flight_sink
    _flight_sink = sink

_PLAIN = (str, int, float, bool, type(None))


def _sanitize(attrs: Dict[str, Any]) -> Dict[str, Any]:
    """JSON scalars pass through; everything else degrades to its type
    name so serializing an attribute can never force a device sync."""
    out = {}
    for k, v in attrs.items():
        out[str(k)] = v if isinstance(v, _PLAIN) else type(v).__name__
    return out


class SpanLog:
    """Thread-safe append-only JSONL writer (line-buffered: each event
    is one `write` of one line, so concurrent spans never interleave
    within a line)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a")
        self.events = 0

    def emit(self, event: dict) -> None:
        line = json.dumps(event) + "\n"
        with self._lock:
            if self._f is None:
                return
            self._f.write(line)
            self._f.flush()
            self.events += 1

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def install_span_log(path_or_log) -> SpanLog:
    """Enable span recording process-wide; returns the active SpanLog."""
    global _active_log
    log = (path_or_log if isinstance(path_or_log, SpanLog)
           else SpanLog(path_or_log))
    with _install_lock:
        _active_log = log
    return log


def uninstall_span_log() -> None:
    global _active_log
    with _install_lock:
        log, _active_log = _active_log, None
    if log is not None:
        log.close()


def tracing_enabled() -> bool:
    return _active_log is not None


def _stack() -> List[int]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


@contextlib.contextmanager
def span(name: str, /, **attrs) -> Iterator[Optional[dict]]:
    """Time a host-side region. Yields the (mutable) attrs dict when
    tracing is enabled so callers can add results discovered inside the
    span (host values only), or None when disabled (a flight ring alone
    keeps the None yield — the no-SpanLog contract is pinned)."""
    log = _active_log
    fr = _flight_sink
    if log is None and fr is None:
        yield None
        return
    sid = next(_ids)
    st = _stack()
    parent = st[-1] if st else None
    st.append(sid)
    ts = time.time()
    t0 = time.perf_counter()
    try:
        yield attrs if log is not None else None
    finally:
        dur = (time.perf_counter() - t0) * 1e3
        st.pop()
        event = {"name": name, "ts": round(ts, 6),
                 "dur_ms": round(dur, 4), "span_id": sid,
                 "parent_id": parent,
                 "thread": threading.current_thread().name,
                 "attrs": _sanitize(attrs)}
        if log is not None:
            log.emit(event)
        if fr is not None:
            fr.record_event("span", event)


def emit_manual_span(name: str, t_start: float, t_end: float, /,
                     **attrs) -> None:
    """Record a span whose bounds were measured elsewhere (wall-clock
    seconds, e.g. a jax.profiler capture window bracketed by listener
    callbacks)."""
    log = _active_log
    fr = _flight_sink
    if log is None and fr is None:
        return
    st = _stack()
    event = {"name": name, "ts": round(t_start, 6),
             "dur_ms": round((t_end - t_start) * 1e3, 4),
             "span_id": next(_ids),
             "parent_id": st[-1] if st else None,
             "thread": threading.current_thread().name,
             "attrs": _sanitize(attrs)}
    if log is not None:
        log.emit(event)
    if fr is not None:
        fr.record_event("span", event)


def read_spans(path: str) -> List[dict]:
    """Load a span JSONL back into dicts (round-trip/test helper)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
