"""DonationWitness — the runtime cross-check for GL801 use-after-donate.

analysis/shardflow.py proves donation discipline statically: a value
passed at a `donate_argnums` position of a jitted call is dead by
contract, and any later read is GL801. This module witnesses the same
contract dynamically. `instrument()` wraps a donating jitted entry
point; after each call the witness marks every array leaf of the
donated arguments as dead (holding a strong reference so the id can
never be reused by a new allocation), and before each call it checks
the incoming arguments against the dead set — passing a stale donated
buffer back in is exactly the bug XLA turns into garbage reads.
`touch()` lets host code assert the same thing at arbitrary points.

Events carry the graft-lint rule id via RUNTIME_RULE_HINTS — the same
static↔runtime cross-check lockmon provides for GL702 — and buffer
names use the static pass's identity scheme (the argument/variable
name, e.g. `state` or `self.params`), so a runtime event is
string-comparable against a static GL801 finding;
`tools/donatemon_smoke.py` asserts exactly that equivalence.

Opt-in via `DL4J_TPU_DONATEMON=1` (or `force=True` in tests). When
disabled, `instrument()` returns the function UNCHANGED — not a
wrapper — so the production step path pays zero Python overhead, zero
extra compiles, and zero extra syncs (the perf gate pins this). When
enabled, the wrapper adds one Python call and an id() sweep over the
argument pytrees per step — hammer-suite pricing, not production
pricing. The witness never reads buffer *contents*: marking and
touching are id()-based, so it adds no device→host syncs even when on.

    w = get_donation_witness(force=True)
    step = instrument(jit_step, (0,), name="train_step",
                      arg_names=("state", "batch"), witness=w)
    state2 = step(state, batch)
    step(state, batch)          # stale! -> GL801 event (or raise)
    w.report()["events"]        # [{"rule": "GL801", "buffer": "state", ...}]
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

_ENV_FLAG = "DL4J_TPU_DONATEMON"

_lock = threading.Lock()
_witness: Optional["DonationWitness"] = None


def donatemon_enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "") == "1"


def get_donation_witness(*, force: bool = False,
                         ) -> Optional["DonationWitness"]:
    """The process-global witness when donatemon is enabled (env flag
    or `force=True`), else None — callers instrument unconditionally
    and pay nothing when disabled."""
    global _witness
    if not (force or donatemon_enabled()):
        return None
    with _lock:
        if _witness is None:
            _witness = DonationWitness()
        return _witness


def reset_donation_witness() -> None:
    global _witness
    with _lock:
        _witness = None


def _static_rules() -> Dict[str, str]:
    try:
        from deeplearning4j_tpu.analysis.rules import runtime_hint
        return {"use_after_donate": runtime_hint("use_after_donate"),
                "device_serialized": runtime_hint("device_serialized")}
    except Exception:
        return {}


def _call_site(depth: int = 3) -> str:
    try:
        f = sys._getframe(depth)
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:
        return "?"


def _leaves(obj: Any, name: str) -> Iterator[Tuple[Any, str]]:
    """(leaf, path-name) pairs for the stdlib pytree containers the
    step APIs actually pass (dict / list / tuple, nested). Only
    array-like leaves (shape+dtype) are yielded — scalars and strings
    are not donate-able buffers and their ids are reuse-prone."""
    if isinstance(obj, dict):
        for k in obj:
            yield from _leaves(obj[k], f"{name}[{k!r}]")
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            yield from _leaves(v, f"{name}[{i}]")
    elif hasattr(obj, "shape") and hasattr(obj, "dtype"):
        yield obj, name


class UseAfterDonateError(RuntimeError):
    """Raised by a witness in raise_on_use mode when a donated buffer
    is touched; carries the GL801 event dict as `.event`."""

    def __init__(self, event: dict):
        self.event = event
        super().__init__(
            f"GL801 use-after-donate: buffer '{event['buffer']}' was "
            f"donated to '{event['callee']}' at {event['donate_site']} "
            f"and touched again at {event['touch_site']}")


class DonationWitness:
    """Dead-buffer ledger keyed by id(), with strong refs pinning ids."""

    def __init__(self, *, raise_on_use: bool = False) -> None:
        self._lock = threading.Lock()
        self.raise_on_use = raise_on_use
        #: id(leaf) -> {"obj": leaf, "buffer", "callee", "site"}
        self._dead: Dict[int, dict] = {}
        self.donations = 0
        self.events: List[dict] = []
        self._seen: set = set()

    # ------------------------------------------------------------ marking
    def mark_donated(self, obj: Any, name: str, callee: str,
                     site: Optional[str] = None) -> int:
        """Mark every array leaf of `obj` dead. The strong reference we
        keep means the CPython id cannot be handed to a fresh array, so
        a later id() hit is always a genuine stale access."""
        site = site or _call_site()
        n = 0
        with self._lock:
            for leaf, path in _leaves(obj, name):
                self._dead[id(leaf)] = {"obj": leaf, "buffer": path,
                                        "root": name, "callee": callee,
                                        "site": site}
                n += 1
            self.donations += n
        return n

    # ----------------------------------------------------------- touching
    def touch(self, obj: Any, name: str,
              site: Optional[str] = None) -> List[dict]:
        """Check `obj`'s leaves against the dead set; one GL801 event
        per (buffer, touch-name) pair. Returns the new events."""
        site = site or _call_site()
        out: List[dict] = []
        with self._lock:
            for leaf, path in _leaves(obj, name):
                rec = self._dead.get(id(leaf))
                if rec is None:
                    continue
                key = (id(leaf), path)
                if key in self._seen:
                    continue
                self._seen.add(key)
                ev = {"rule": "GL801",
                      "buffer": rec["root"],
                      "leaf": rec["buffer"],
                      "touched_as": path,
                      "callee": rec["callee"],
                      "donate_site": rec["site"],
                      "touch_site": site,
                      "thread": threading.current_thread().name}
                self.events.append(ev)
                out.append(ev)
        if out and self.raise_on_use:
            raise UseAfterDonateError(out[0])
        return out

    # ------------------------------------------------------------- report
    def report(self) -> dict:
        """Everything the smoke/chaos suites assert on, plus the static
        rule ids (the runtime → static cross-check: an event here means
        graft-lint GL801 should have flagged the read at review time)."""
        with self._lock:
            return {"donations": self.donations,
                    "dead_buffers": len(self._dead),
                    "events": [dict(ev) for ev in self.events],
                    "static_rules": _static_rules()}


def instrument(fn, donate_argnums: Sequence[int] = (), *,
               name: Optional[str] = None,
               arg_names: Optional[Sequence[str]] = None,
               witness: Optional[DonationWitness] = None):
    """Wrap a donating jitted callable with the donation witness.

    With donatemon disabled (no env flag, no explicit witness) the
    function is returned UNCHANGED — zero overhead, zero extra
    compiles, and the static pass treats `instrument(...)` as a
    transparent wrapper so donation facts flow through either way.

    When enabled: before each call every positional argument is
    touched (a stale donated buffer passed back in fires GL801), and
    after each call the arguments at `donate_argnums` positions are
    marked dead. `arg_names` supplies the static pass's buffer
    identities (e.g. ``("params", "opt_state")``); unnamed positions
    fall back to ``arg<i>``.
    """
    if witness is None:
        witness = get_donation_witness()
    if witness is None:
        return fn
    label = name or getattr(fn, "__name__", "jit_fn")
    donate = tuple(donate_argnums)

    def _name(i: int) -> str:
        if arg_names is not None and i < len(arg_names):
            return arg_names[i]
        return f"arg{i}"

    def wrapper(*args, **kwargs):
        site = _call_site(2)
        for i, a in enumerate(args):
            witness.touch(a, _name(i), site)
        out = fn(*args, **kwargs)
        for i in donate:
            if i < len(args):
                witness.mark_donated(args[i], _name(i), label, site)
        return out

    wrapper.__name__ = f"donatemon[{label}]"
    wrapper.__wrapped__ = fn
    return wrapper
