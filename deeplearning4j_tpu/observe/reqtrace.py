"""Request-scoped causal tracing across the batching/session/sharding seams.

Dapper-style trace trees built ON TOP of the sync-free span machinery in
`observe/trace.py` (same span-id counter, same attribute discipline): a
`TraceContext` is minted at the HTTP edge, rides through scheduler
admission, **fans in** to shared batched dispatches (one dispatch span
per participating trace, all listing the co-batched trace ids), and
threads through decode-session steps and training dispatch windows.

Contracts (PERF_NOTES):

- **Never a host sync.** Span attributes are host scalars; anything else
  degrades to its type name exactly like `trace._sanitize` — recording a
  device value's *content* would be a hidden sync. Shallow lists/tuples
  of scalars are allowed (co-batched trace-id lists), capped at
  `_MAX_LIST` items.
- **Sampled-off is zero-allocation.** With `DL4J_TPU_TRACE_SAMPLE`
  unset/0, `new_trace()` returns None before allocating anything and
  every call site is a single `is None` check; no span object, dict, or
  TraceContext is created on the HTTP→dispatch→session path.
- **Anomalies always trace.** Shed / expired / deadline-missed /
  worker-crash requests get a forced error trace regardless of the
  sampling rate (`error_trace`), so the tail is always attributable.

Head-based sampling is deterministic (every round(1/rate)-th eligible
request), not random — reproducible under the perf gate and chaos
harness. The store is bounded (`DL4J_TPU_TRACE_CAP` traces, oldest
evicted) so an unbounded request stream cannot grow memory.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.observe import trace as _trace

ENV_SAMPLE = "DL4J_TPU_TRACE_SAMPLE"
ENV_CAP = "DL4J_TPU_TRACE_CAP"

_PLAIN = (str, int, float, bool, type(None))
_MAX_LIST = 32
_MAX_SPANS_PER_TRACE = 1000

_trace_seq = itertools.count(1)
_sample_seq = itertools.count()
_tls = threading.local()

# Implicit carrier for the admission seam: the HTTP edge sets it, and
# `ContinuousBatchingScheduler.submit` falls back to it when no explicit
# trace is passed. Fan-OUT only — the fan-in seam (one dispatch, N
# traces) uses the worker-thread dispatch handoff below instead, because
# a single contextvar cannot represent N parents.
_current: contextvars.ContextVar = contextvars.ContextVar(
    "dl4j_tpu_trace", default=None)


def _attr(v: Any) -> Any:
    """Same degradation rule as trace._sanitize, plus shallow scalar
    lists (co-batched trace ids) — never serializes a device value."""
    if isinstance(v, _PLAIN):
        return v
    if isinstance(v, (list, tuple)):
        return [x if isinstance(x, _PLAIN) else type(x).__name__
                for x in list(v)[:_MAX_LIST]]
    return type(v).__name__


def _attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    return {str(k): _attr(v) for k, v in attrs.items()}


class TraceContext:
    """One sampled request: trace id + root span id + sampling decision.

    `span_id` is the ROOT span's id, preallocated at mint time so child
    spans (queue wait, dispatch, session steps) can parent on it before
    the root itself is recorded by `finish_root`."""

    __slots__ = ("trace_id", "span_id", "sampled", "name", "ts", "_t0")

    def __init__(self, trace_id: str, name: str):
        self.trace_id = trace_id
        self.span_id = next(_trace._ids)
        self.sampled = True
        self.name = name
        self.ts = time.time()
        self._t0 = time.perf_counter()

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id}, root={self.span_id})"


class TraceStore:
    """Bounded process-wide span store keyed by trace id.

    `spans_recorded` counts every span ever added — the disabled-fast-path
    test pins it at 0 after an untraced request storm."""

    def __init__(self, cap: Optional[int] = None):
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, List[dict]]" = OrderedDict()
        self.cap = int(cap if cap is not None
                       else os.environ.get(ENV_CAP, "256"))
        self.spans_recorded = 0

    def add_span(self, trace_id: str, event: dict) -> None:
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                spans = self._traces[trace_id] = []
                while len(self._traces) > max(1, self.cap):
                    self._traces.popitem(last=False)
            if len(spans) < _MAX_SPANS_PER_TRACE:
                spans.append(event)
            self.spans_recorded += 1

    def spans(self, trace_id: str) -> List[dict]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def __contains__(self, trace_id: str) -> bool:
        with self._lock:
            return trace_id in self._traces

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def tree(self, trace_id: str) -> Optional[dict]:
        """Reconstructed span tree: {"trace_id", "spans", "depth",
        "tree": [roots]} or None for an unknown trace."""
        spans = self.spans(trace_id)
        if not spans:
            return None
        nodes = {}
        for ev in spans:
            nodes[ev["span_id"]] = dict(ev, children=[])
        roots = []
        for sid, node in nodes.items():
            parent = nodes.get(node.get("parent_id"))
            if parent is not None and parent is not node:
                parent["children"].append(node)
            else:
                roots.append(node)
        for node in nodes.values():
            node["children"].sort(key=lambda n: n.get("ts", 0))
        roots.sort(key=lambda n: n.get("ts", 0))

        def depth(node, d=1):
            kids = node["children"]
            return max([depth(c, d + 1) for c in kids], default=d)

        return {"trace_id": trace_id, "spans": len(spans),
                "depth": max([depth(r) for r in roots], default=0),
                "tree": roots}

    def last_trees(self, k: int) -> List[dict]:
        with self._lock:
            ids = list(self._traces)[-max(0, int(k)):]
        return [t for t in (self.tree(tid) for tid in ids)
                if t is not None]


_store = TraceStore()
_store_lock = threading.Lock()


def get_trace_store() -> TraceStore:
    return _store


def set_trace_store(store: TraceStore) -> TraceStore:
    """Swap the process-wide store; returns the previous one (tests)."""
    global _store
    with _store_lock:
        prev, _store = _store, store
    return prev


# ------------------------------------------------------------- sampling

def sample_rate() -> float:
    try:
        return float(os.environ.get(ENV_SAMPLE, "0") or "0")
    except ValueError:
        return 0.0


def _sampled() -> bool:
    rate = sample_rate()
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    period = max(1, round(1.0 / rate))
    return next(_sample_seq) % period == 0


def _new_tid() -> str:
    return f"t{os.getpid():x}-{next(_trace_seq):06x}"


# ------------------------------------------------------------ recording

def record_span(trace_id: str, name: str, *, span_id: Optional[int] = None,
                parent_id: Optional[int] = None, ts: Optional[float] = None,
                dur_ms: float = 0.0, **attrs) -> int:
    """Append one span to a trace (and to the active SpanLog, so request
    spans land in the same JSONL as fit/epoch spans). Host values only —
    attributes degrade like trace._sanitize. Returns the span id."""
    sid = span_id if span_id is not None else next(_trace._ids)
    if ts is None:
        ts = time.time() - dur_ms / 1e3
    event = {"name": name, "ts": round(ts, 6),
             "dur_ms": round(float(dur_ms), 4), "span_id": sid,
             "parent_id": parent_id, "trace_id": trace_id,
             "thread": threading.current_thread().name,
             "attrs": _attrs(attrs)}
    _store.add_span(trace_id, event)
    log = _trace._active_log
    if log is not None:
        log.emit(event)
    return sid


def new_trace(name: str) -> Optional[TraceContext]:
    """Head-sampling gate at the request edge. Returns None (and
    allocates nothing) when the request is not sampled. Root-span
    attributes go on `finish_root`."""
    if not _sampled():
        return None
    return TraceContext(_new_tid(), name)


def finish_root(ctx: Optional[TraceContext], **attrs) -> None:
    """Record the root span covering the whole request; idempotent-ish
    (a second call appends a duplicate root — call once, in `finally`)."""
    if ctx is None:
        return
    record_span(ctx.trace_id, ctx.name, span_id=ctx.span_id,
                parent_id=None, ts=ctx.ts,
                dur_ms=(time.perf_counter() - ctx._t0) * 1e3, **attrs)


def error_trace(name: str, *, ctx: Optional[TraceContext] = None,
                **attrs) -> str:
    """Force-sample an anomaly (shed/expired/deadline/worker-crash).

    If the request already carries a sampled trace, the error span joins
    it; otherwise a single-span trace is minted regardless of the
    sampling rate. Returns the trace id (attach it to the raised
    exception so the HTTP error payload can surface it)."""
    if ctx is not None:
        record_span(ctx.trace_id, name, parent_id=ctx.span_id,
                    error=True, **attrs)
        return ctx.trace_id
    tid = _new_tid()
    record_span(tid, name, error=True, **attrs)
    return tid


def error_extra(exc: BaseException) -> Dict[str, str]:
    """HttpError kwargs for an exception stamped by error_trace."""
    tid = getattr(exc, "trace_id", None)
    return {"trace_id": tid} if tid else {}


# ------------------------------------------------- implicit propagation

def current_trace() -> Optional[TraceContext]:
    return _current.get()


def set_current(ctx: Optional[TraceContext]):
    """Bind the contextvar carrier (the scheduler's per-request
    `contextvars.copy_context()` snapshot picks it up). Returns the
    reset token."""
    return _current.set(ctx)


def reset_current(token) -> None:
    _current.reset(token)


# ------------------------------------------------------ fan-in dispatch

class _DispatchTrace:
    """One shared batched dispatch joining N sampled traces.

    `span_ids` preallocates a dispatch span id per trace so session-step
    spans recorded INSIDE run_batch (same worker thread) can parent on
    their trace's dispatch span before it is closed."""

    __slots__ = ("span_ids", "parents", "co_traces", "ts", "_t0")

    def __init__(self, traces: List[TraceContext]):
        self.span_ids = {c.trace_id: next(_trace._ids) for c in traces}
        self.parents = {c.trace_id: c.span_id for c in traces}
        self.co_traces = sorted(self.span_ids)
        self.ts = time.time()
        self._t0 = time.perf_counter()


def begin_dispatch(traces: List[TraceContext]) -> Optional["_DispatchTrace"]:
    """Open the fan-in window on THIS thread (the scheduler worker that
    is about to call run_batch). Returns None when nothing is sampled."""
    if not traces:
        return None
    dt = _DispatchTrace(traces)
    _tls.dispatch = dt
    return dt


def active_dispatch() -> Optional["_DispatchTrace"]:
    """The dispatch window opened on this thread, if any — how
    `run_batch` implementations attribute per-row work to traces."""
    return getattr(_tls, "dispatch", None)


def end_dispatch(dt: Optional["_DispatchTrace"], **attrs) -> None:
    """Close the fan-in window: one dispatch span PER participating
    trace (same wall bounds, each listing every co-batched trace id)."""
    if dt is None:
        return
    _tls.dispatch = None
    dur = (time.perf_counter() - dt._t0) * 1e3
    for tid, sid in dt.span_ids.items():
        record_span(tid, "dispatch", span_id=sid,
                    parent_id=dt.parents[tid], ts=dt.ts, dur_ms=dur,
                    co_traces=dt.co_traces, **attrs)


# ------------------------------------------- cross-process graft (fleet)

def pid_of_trace_id(trace_id: str) -> Optional[int]:
    """Recover the minting process's pid from a trace id (the
    `t{pid:x}-{seq:06x}` scheme) — how stitched trees label process
    boundaries without an extra endpoint."""
    try:
        if not trace_id or trace_id[0] != "t":
            return None
        return int(trace_id[1:].split("-", 1)[0], 16)
    except (ValueError, IndexError):
        return None


def tree_stats(doc: dict) -> dict:
    """Recompute span count / depth / distinct-pid count over a (possibly
    stitched) tree doc in place; returns the doc."""
    pids = set()
    count = [0]

    def walk(node, d):
        count[0] += 1
        tid = node.get("trace_id")
        pid = pid_of_trace_id(tid) if isinstance(tid, str) else None
        if pid is not None:
            pids.add(pid)
        return max([walk(c, d + 1) for c in node.get("children", ())],
                   default=d)

    doc["depth"] = max([walk(r, 1) for r in doc.get("tree", ())],
                       default=0)
    doc["spans"] = count[0]
    doc["processes"] = len(pids) or 1
    return doc


def graft_subtree(hop_node: dict, subdoc: dict, *, skew_s: float = 0.0,
                  **boundary_attrs) -> int:
    """Graft a remote trace tree under a hop span of a local tree.

    `subdoc` is another process's `TraceStore.tree()` document; its roots
    become children of `hop_node`. Every grafted timestamp is shifted by
    `-skew_s` (the estimated remote-minus-local clock offset) so the
    waterfall lines up on the LOCAL clock; each grafted root is stamped
    with `boundary="process"` plus `boundary_attrs` (replica name, pid,
    skew) so renderers can draw the process-boundary rule. Returns the
    number of spans grafted. Purely host-side tree surgery — no network,
    no locks, no device access."""
    roots = subdoc.get("tree") or []
    n = [0]

    def shift(node):
        n[0] += 1
        if skew_s and isinstance(node.get("ts"), (int, float)):
            node["ts"] = round(node["ts"] - skew_s, 6)
        for c in node.get("children", ()):
            shift(c)

    for root in roots:
        shift(root)
        attrs = dict(root.get("attrs") or {})
        attrs["boundary"] = "process"
        attrs.update(boundary_attrs)
        root["attrs"] = attrs
        hop_node.setdefault("children", []).append(root)
    hop_node["children"].sort(key=lambda c: c.get("ts", 0))
    return n[0]
