"""FlightRecorder — a crash-safe black box for training and serving.

The observability spine (registry / spans / watchdog / syncmon) answers
"what is happening now?" through live surfaces that die with the
process. When a run crashes at 3am, what you actually need is the last
few seconds BEFORE the crash: the spans that were open, the compiles
and sync events that fired, and what device memory looked like. The
FlightRecorder keeps exactly that — a bounded ring of recent telemetry
events — and dumps it to a JSON artifact the moment something goes
wrong:

- unhandled exception escaping `TrainingExecutor.run` (training crash),
- a `ContinuousBatchingScheduler` worker thread dying (serving outage),
- the RecompileWatchdog crossing its churn threshold (the silent-10x
  signal, captured with full context instead of one log line).

Sources feeding the ring:
- every `span()` / `emit_manual_span()` event (wired through
  `trace._set_flight_sink`, so the ring fills even when no SpanLog is
  installed — recording costs one deque append);
- watchdog compile + cost + threshold events;
- device-memory samples from `observe.devicemon`;
- serving dispatch errors.

The dump is self-contained JSON: ring events, the triggering exception,
plus best-effort registry / watchdog / syncmon snapshots and a
crash-time device-memory sample. Render with `tools/flight_view.py`.

Env knobs:
  DL4J_TPU_FLIGHT=0           disable entirely (record/dump no-ops)
  DL4J_TPU_FLIGHT_CAP=256     ring capacity (events)
  DL4J_TPU_FLIGHT_DIR=<dir>   dump directory (default: tempdir)
  DL4J_TPU_FLIGHT_KEEP=20     retained dumps in the dir (newest N kept;
                              always-on crash dumps can't fill the disk)
  DL4J_TPU_FLIGHT_TRACES=8    sampled request traces embedded per dump

Stdlib-only at import time (the observe package contract); jax-touching
enrichment (device sample) is imported lazily inside `dump()` and is
best-effort.
"""

from __future__ import annotations

import json
import logging
import os
import re
import tempfile
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")

DEFAULT_CAPACITY = 256
DEFAULT_KEEP = 20        # retained dumps per directory (newest kept)
DEFAULT_TRACES = 8       # request-trace trees embedded in each dump
_PLAIN = (str, int, float, bool, type(None))
_MAX_DEPTH = 4          # payload sanitizer bounds: a flight event must
_MAX_ITEMS = 32         # stay cheap to record and safe to json.dumps
_SLUG_RE = re.compile(r"[^a-zA-Z0-9_.-]+")


def _plain(v: Any, depth: int = 0) -> Any:
    """Recursive analogue of trace._sanitize: JSON scalars pass, small
    dict/list structure is kept (device-memory samples are nested),
    anything else — including a jax array — degrades to its type name so
    recording an event can never force a device sync."""
    if isinstance(v, _PLAIN):
        return v
    if depth >= _MAX_DEPTH:
        return type(v).__name__
    if isinstance(v, dict):
        return {str(k): _plain(x, depth + 1)
                for k, x in list(v.items())[:_MAX_ITEMS]}
    if isinstance(v, (list, tuple)):
        return [_plain(x, depth + 1) for x in list(v)[:_MAX_ITEMS]]
    return type(v).__name__


class FlightRecorder:
    """Bounded ring of recent telemetry events + crash-dump writer.

    `record()` is the hot path: sanitize + one lock + one deque append
    (the deque evicts the oldest event itself). `dump()` is the cold
    path and NEVER raises — it runs inside exception handlers where a
    secondary failure would mask the real crash.
    """

    def __init__(self, *, capacity: Optional[int] = None,
                 dump_dir: Optional[str] = None,
                 enabled: Optional[bool] = None):
        if capacity is None:
            capacity = int(os.environ.get("DL4J_TPU_FLIGHT_CAP",
                                          str(DEFAULT_CAPACITY)))
        if enabled is None:
            enabled = os.environ.get("DL4J_TPU_FLIGHT", "1") != "0"
        self.capacity = max(1, int(capacity))
        self.enabled = bool(enabled)
        self.dump_dir = (dump_dir
                         or os.environ.get("DL4J_TPU_FLIGHT_DIR")
                         or tempfile.gettempdir())
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._dump_seq = 0
        self.dumps: List[str] = []

    # ---------------------------------------------------------- recording
    def record(self, kind: str, **payload) -> None:
        """Append one event to the ring (sanitized payload)."""
        if not self.enabled:
            return
        self.record_event(kind, _plain(payload))

    def record_event(self, kind: str, data: Dict[str, Any]) -> None:
        """Fast path for pre-sanitized payloads (span events arrive here
        already scrubbed by trace._sanitize)."""
        if not self.enabled:
            return
        ev = {"kind": kind, "ts": round(time.time(), 6), "data": data}
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)

    # ---------------------------------------------------------- reporting
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def snapshot(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "capacity": self.capacity,
                    "recorded_total": self._seq,
                    "events": list(self._events),
                    "dumps": list(self.dumps)}

    # ------------------------------------------------------------ dumping
    def dump(self, reason: str, exc: Optional[BaseException] = None,
             path: Optional[str] = None) -> Optional[str]:
        """Write the black box to a JSON artifact; returns the path, or
        None when disabled or the write failed. Never raises."""
        if not self.enabled:
            return None
        try:
            doc: Dict[str, Any] = {
                "reason": reason,
                "ts": round(time.time(), 6),
                "pid": os.getpid(),
                "exception": None,
                "events": self.events(),
            }
            if exc is not None:
                doc["exception"] = {
                    "type": type(exc).__name__,
                    "message": str(exc)[:2000],
                    "traceback": "".join(traceback.format_exception(
                        type(exc), exc, exc.__traceback__))[-8000:],
                }
            for key, fn in (("registry", self._registry_snapshot),
                            ("watchdog", self._watchdog_snapshot),
                            ("syncmon", self._syncmon_snapshot),
                            ("commsmon", self._commsmon_snapshot),
                            ("devices", self._device_sample),
                            ("traces", self._traces_snapshot)):
                try:
                    doc[key] = fn()
                except Exception:
                    doc[key] = None
            if path is None:
                with self._lock:
                    self._dump_seq += 1
                    n = self._dump_seq
                slug = _SLUG_RE.sub("-", reason)[:48] or "dump"
                path = os.path.join(
                    self.dump_dir,
                    f"flight_{os.getpid()}_{n:03d}_{slug}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)
            os.replace(tmp, path)     # atomic: a reader never sees half
            with self._lock:
                self.dumps.append(path)
            self._prune_dumps()
            self.record("flight_dump", reason=reason, path=path)
            logger.info("FlightRecorder: wrote %d events to %s "
                        "(reason: %s)", len(doc["events"]), path, reason)
            return path
        except Exception:
            logger.debug("FlightRecorder: dump failed", exc_info=True)
            return None

    def _prune_dumps(self) -> None:
        """Dump-dir hygiene: keep the newest DL4J_TPU_FLIGHT_KEEP
        `flight_*.json` artifacts (any process), delete the rest. Runs
        after every successful dump; best-effort like dump() itself."""
        try:
            keep = int(os.environ.get("DL4J_TPU_FLIGHT_KEEP",
                                      str(DEFAULT_KEEP)))
        except ValueError:
            keep = DEFAULT_KEEP
        if keep <= 0:
            return
        try:
            names = os.listdir(self.dump_dir)
        except OSError:
            return
        cands = []
        for n in names:
            if not (n.startswith("flight_") and n.endswith(".json")):
                continue
            p = os.path.join(self.dump_dir, n)
            try:
                cands.append((os.path.getmtime(p), n, p))
            except OSError:
                continue   # raced with another pruner
        # name is the tiebreak for same-second dumps: the seq counter in
        # the filename sorts newer dumps later
        cands.sort(reverse=True)
        for _, _, p in cands[keep:]:
            try:
                os.remove(p)
            except OSError:
                continue   # raced with another pruner

    # dump enrichment — each is best-effort and individually guarded
    @staticmethod
    def _traces_snapshot():
        from deeplearning4j_tpu.observe.reqtrace import get_trace_store
        try:
            k = int(os.environ.get("DL4J_TPU_FLIGHT_TRACES",
                                   str(DEFAULT_TRACES)))
        except ValueError:
            k = DEFAULT_TRACES
        trees = get_trace_store().last_trees(k)
        return trees or None

    @staticmethod
    def _registry_snapshot():
        from deeplearning4j_tpu.observe.registry import get_registry
        return get_registry().snapshot()

    @staticmethod
    def _watchdog_snapshot():
        from deeplearning4j_tpu.observe.watchdog import get_watchdog
        return get_watchdog().snapshot()

    @staticmethod
    def _syncmon_snapshot():
        from deeplearning4j_tpu.observe.syncmon import current_monitor
        mon = current_monitor()
        return mon.snapshot() if mon is not None else None

    @staticmethod
    def _commsmon_snapshot():
        # the comm ledger (per-owner collective totals from compiled
        # programs) + the reshard witness report when it is live
        from deeplearning4j_tpu.observe.commsmon import get_reshard_witness
        from deeplearning4j_tpu.observe.watchdog import get_watchdog
        wit = get_reshard_witness()
        return {"comm_totals": get_watchdog().comm_totals(),
                "reshard": wit.report() if wit is not None else None}

    @staticmethod
    def _device_sample():
        # crash-time device truth: what memory looked like at the end
        from deeplearning4j_tpu.observe.devicemon import (
            device_memory_summary,
        )
        return device_memory_summary()


def read_dump(path: str) -> dict:
    """Load a flight dump back (test / flight_view helper)."""
    with open(path) as f:
        return json.load(f)


def latest_dump(dump_dir: Optional[str] = None) -> Optional[str]:
    """Path of the newest flight dump on disk (any process), or None.

    The recovery breadcrumb: a restart is a NEW process, so the crashed
    run's `FlightRecorder.dumps` list is gone — but its artifact is
    still in the dump directory. `RecoveryPlan` records this path on
    resume so the restarted run carries its predecessor's black box."""
    d = dump_dir or get_flight().dump_dir
    best, best_mtime = None, -1.0
    try:
        names = os.listdir(d)
    except OSError:
        return None
    for n in names:
        if not (n.startswith("flight_") and n.endswith(".json")):
            continue
        p = os.path.join(d, n)
        try:
            m = os.path.getmtime(p)
        except OSError:
            continue       # raced with cleanup — not a candidate
        if m > best_mtime:
            best, best_mtime = p, m
    return best


# ------------------------------------------------------------ process-wide
_flight: Optional[FlightRecorder] = None
_install_lock = threading.Lock()


def _wire(fr: Optional[FlightRecorder]) -> None:
    """Point the span emitters at the ring (None detaches)."""
    from deeplearning4j_tpu.observe import trace
    trace._set_flight_sink(fr if (fr is not None and fr.enabled) else None)


def get_flight() -> FlightRecorder:
    """The process-wide recorder (created — and wired into the span
    path — on first use)."""
    global _flight
    if _flight is None:
        with _install_lock:
            if _flight is None:
                fr = FlightRecorder()
                _wire(fr)
                _flight = fr
    return _flight


def set_flight(fr: FlightRecorder) -> Optional[FlightRecorder]:
    """Swap the process-wide recorder (tests point dump_dir at a tmp
    path this way); returns the previous one."""
    global _flight
    with _install_lock:
        prev, _flight = _flight, fr
    _wire(fr)
    return prev
