"""DeviceMonitor — device-truth memory & live-array telemetry.

The registry/spans layer measures the HOST clock; nothing in the spine
sees HBM. On TPU the failure mode this leaves invisible is the slow
creep toward an OOM hundreds of steps away — the same pressure that
motivates cross-replica sharding of updater state to fit HBM (Xu et
al., arXiv:2004.13336). The monitor polls `device.memory_stats()`
(bytes_in_use / peak_bytes_in_use / bytes_limit) and counts live
`jax.Array`s per device into labeled gauges:

  device_memory_bytes_in_use{device="tpu:0"}
  device_memory_peak_bytes{device="tpu:0"}
  device_memory_limit_bytes{device="tpu:0"}
  device_memory_used_fraction{device="tpu:0"}
  device_live_arrays{device="tpu:0"}

and warns ONCE per device when used_fraction crosses the headroom
threshold (DL4J_TPU_HBM_WARN_FRACTION, default 0.9) — before XLA's
allocator turns the creep into a crash.

Backends that report nothing (the CPU backend returns None from
`memory_stats()`) degrade gracefully: the sample carries
`"memory_stats": None` and only the live-array gauge is published, so
every test in this repo exercises the real code path.

Polling is pull-based: `sample_once()` costs one runtime query per
device and runs (a) on demand from the `/devices` endpoint, bench.py,
and StatsListener reports, and (b) optionally on a background thread
(`start()`, or DL4J_TPU_DEVICEMON=1 + `maybe_start_monitor()` which the
TrainingExecutor calls at every fit). Each sample also lands in the
FlightRecorder ring, so a crash dump always carries recent device
memory.

Stdlib-only at import time; jax is imported inside `sample_once()`.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")

DEFAULT_INTERVAL_S = float(os.environ.get("DL4J_TPU_DEVICEMON_INTERVAL",
                                          "10"))
DEFAULT_WARN_FRACTION = float(os.environ.get("DL4J_TPU_HBM_WARN_FRACTION",
                                             "0.9"))

# memory_stats key -> registry gauge name
_STAT_GAUGES = (
    ("bytes_in_use", "device_memory_bytes_in_use"),
    ("peak_bytes_in_use", "device_memory_peak_bytes"),
    ("bytes_limit", "device_memory_limit_bytes"),
)


def _label(device) -> str:
    return f"{getattr(device, 'platform', '?')}:{getattr(device, 'id', '?')}"


class DeviceMonitor:
    """Poll per-device memory + live-array counts into the registry."""

    def __init__(self, *, interval_s: Optional[float] = None,
                 warn_fraction: Optional[float] = None,
                 registry=None, record_flight: bool = True):
        self.interval_s = (DEFAULT_INTERVAL_S if interval_s is None
                           else float(interval_s))
        self.warn_fraction = (DEFAULT_WARN_FRACTION if warn_fraction is None
                              else float(warn_fraction))
        self._registry = registry     # None -> resolve per sample, so a
        self.record_flight = record_flight    # test registry swap is seen
        self._lock = threading.Lock()
        self._warned: set = set()
        self._last: List[dict] = []
        self.polls = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ sampling
    def sample_once(self, devices=None) -> List[dict]:
        """One poll over `devices` (default: all jax devices). Returns
        the per-device sample list it also publishes as gauges."""
        import jax   # lazy: the observe package stays jax-free to import

        reg = self._registry
        if reg is None:
            from deeplearning4j_tpu.observe.registry import get_registry
            reg = get_registry()
        if devices is None:
            # telemetry observes every addressable device regardless of
            # which spine (if any) is active — not a placement decision
            devices = jax.devices()  # graft: allow(GL501): observer enumerates all devices, no placement
        live = self._live_array_counts()
        samples = []
        for d in devices:
            label = _label(d)
            sample: Dict = {"device": label,
                            "kind": getattr(d, "device_kind", "?"),
                            "live_arrays": live.get(label, 0)}
            reg.gauge("device_live_arrays",
                      device=label).set(sample["live_arrays"])
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                # backend reports nothing (e.g. the CPU runtime): keep
                # the sample shape stable so consumers see the absence
                sample["memory_stats"] = None
            else:
                for key, gname in _STAT_GAUGES:
                    v = stats.get(key)
                    if v is not None:
                        sample[key] = int(v)
                        reg.gauge(gname, device=label).set(v)
                in_use, limit = stats.get("bytes_in_use"), \
                    stats.get("bytes_limit")
                if in_use and limit:
                    frac = in_use / float(limit)
                    sample["used_fraction"] = round(frac, 4)
                    reg.gauge("device_memory_used_fraction",
                              device=label).set(frac)
                    self._maybe_warn(label, frac, in_use, limit)
            samples.append(sample)
        with self._lock:
            self._last = samples
            self.polls += 1
        if self.record_flight:
            try:
                from deeplearning4j_tpu.observe.flight import get_flight
                get_flight().record("device_memory", devices=samples)
            # graft: allow(GL403): ring breadcrumb is best-effort; the
            # gauges above are the authoritative surface
            except Exception:
                pass
        return samples

    @staticmethod
    def _live_array_counts() -> Dict[str, int]:
        """Count live jax.Arrays per device — pure host-side metadata
        (shape/placement), never the values, so counting cannot sync."""
        import jax

        counts: Dict[str, int] = {}
        try:
            for a in jax.live_arrays():
                try:
                    devs = a.devices()
                # graft: allow(GL403): an array deleted mid-iteration is
                # expected churn; skip it, keep counting
                except Exception:
                    continue
                for d in devs:
                    lbl = _label(d)
                    counts[lbl] = counts.get(lbl, 0) + 1
        # graft: allow(GL403): live_arrays is a debug API — if the
        # runtime refuses, the sample degrades to zero counts
        except Exception:
            pass
        return counts

    def _maybe_warn(self, label: str, frac: float, in_use: int,
                    limit: int) -> None:
        if frac < self.warn_fraction:
            return
        with self._lock:
            if label in self._warned:
                return
            self._warned.add(label)
        logger.warning(
            "DeviceMonitor: HBM headroom low on %s — %.1f%% of %.0f MiB "
            "in use (%.0f MiB, warn threshold %.0f%%). The next "
            "allocation spike (optimizer state, activation peak, a new "
            "compile's temp buffers) may OOM; shard updater state across "
            "replicas or shrink the batch before XLA does it for you.",
            label, frac * 100.0, limit / 2**20, in_use / 2**20,
            self.warn_fraction * 100.0)
        try:
            from deeplearning4j_tpu.observe.flight import get_flight
            get_flight().record("hbm_headroom_warning", device=label,
                                used_fraction=round(frac, 4),
                                bytes_in_use=int(in_use),
                                bytes_limit=int(limit))
        # graft: allow(GL403): the ring breadcrumb is best-effort; the
        # warning above already reached the log
        except Exception:
            pass

    # ---------------------------------------------------------- background
    @property
    def running(self) -> bool:
        with self._lock:
            t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        """Start background polling (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, name="dl4j-tpu-devicemon", daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        # pin this run's Event: start() replaces self._stop on restart,
        # and a straggling old loop must keep waiting on its own event
        with self._lock:
            stop = self._stop
        while not stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                logger.debug("DeviceMonitor: sample failed", exc_info=True)

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
            self._stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)

    # ---------------------------------------------------------- reporting
    def last_samples(self) -> List[dict]:
        with self._lock:
            return list(self._last)

    def snapshot(self) -> dict:
        with self._lock:
            return {"interval_s": self.interval_s,
                    "warn_fraction": self.warn_fraction,
                    "polls": self.polls,
                    "running": self.running,
                    "devices": list(self._last)}


# ------------------------------------------------------------ process-wide
_monitor: Optional[DeviceMonitor] = None
_install_lock = threading.Lock()


def get_device_monitor() -> DeviceMonitor:
    global _monitor
    if _monitor is None:
        with _install_lock:
            if _monitor is None:
                _monitor = DeviceMonitor()
    return _monitor


def set_device_monitor(mon: DeviceMonitor) -> Optional[DeviceMonitor]:
    """Swap the process-wide monitor (tests pin intervals/registries);
    returns the previous one."""
    global _monitor
    with _install_lock:
        prev, _monitor = _monitor, mon
    return prev


def tree_device_bytes(tree) -> Dict[str, int]:
    """Per-device resident bytes for a pytree of jax.Arrays, summed from
    addressable shards. Works where memory_stats() reports nothing (the
    CPU runtime) and attributes bytes to the devices a sharded array
    actually occupies — a replicated leaf counts its full nbytes on every
    device, a sharded leaf only its shard. Pure host-side metadata
    (shape/sharding), never values, so sampling cannot sync."""
    per: Dict[str, int] = {}
    for leaf in _tree_leaves(tree):
        for sh in getattr(leaf, "addressable_shards", ()) or ():
            label = _label(sh.device)
            data = sh.data
            if data is not None:
                per[label] = per.get(label, 0) + int(data.nbytes)
    return per


def _tree_leaves(tree):
    import jax   # lazy: the observe package stays jax-free to import
    return jax.tree_util.tree_leaves(tree)


def device_memory_summary() -> Optional[List[dict]]:
    """One best-effort sample for embedding in reports (StatsListener,
    bench.py, flight dumps); None when jax is unavailable or broken."""
    try:
        return get_device_monitor().sample_once()
    except Exception:
        return None


def maybe_start_monitor() -> bool:
    """Start background polling iff DL4J_TPU_DEVICEMON is truthy
    (default off — on-demand sampling is free; a poll thread is a
    choice). Idempotent; the TrainingExecutor calls this at every fit."""
    if os.environ.get("DL4J_TPU_DEVICEMON", "0").lower() in (
            "0", "", "false"):
        return False
    get_device_monitor().start()
    return True
