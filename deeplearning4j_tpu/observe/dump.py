"""Pretty-print observability artifacts.

    python -m deeplearning4j_tpu.observe.dump snapshot.json
    python -m deeplearning4j_tpu.observe.dump spans.jsonl --tail 20
    python -m deeplearning4j_tpu.observe.dump --live

Three inputs, auto-detected:
- a registry snapshot (`MetricsRegistry.snapshot()` saved as JSON, or a
  BENCH_*.json blob embedding one under "registry") → aligned table;
- a span/metric JSONL log (`SpanLog` / `export_jsonl`) → one formatted
  line per event, `--tail N` for the last N;
- `--live` → the current process-wide registry (for use from a REPL or
  under `python -c`).

Import cost is stdlib-only so this works on machines without jax.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def format_snapshot(snap: dict) -> str:
    """Aligned text table for a MetricsRegistry.snapshot() dict."""
    series = snap.get("series", snap)
    rows: List[tuple] = []
    for name in sorted(series):
        for s in series[name]:
            labels = ",".join(f"{k}={v}"
                              for k, v in sorted(s.get("labels", {}).items()))
            kind = s.get("type", "?")
            if kind == "histogram":
                val = (f"count={s.get('count')} sum={_fmt(s.get('sum'))} "
                       f"p50={_fmt(s.get('p50'))} p95={_fmt(s.get('p95'))} "
                       f"p99={_fmt(s.get('p99'))}")
            else:
                val = _fmt(s.get("value"))
            rows.append((name, kind, labels, val))
    if not rows:
        return "(no series)"
    w0 = max(len(r[0]) for r in rows)
    w1 = max(len(r[1]) for r in rows)
    w2 = max(len(r[2]) for r in rows)
    return "\n".join(f"{n:<{w0}}  {k:<{w1}}  {l:<{w2}}  {v}"
                     for n, k, l, v in rows)


def format_span(ev: dict) -> str:
    attrs = ev.get("attrs") or {}
    extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    parent = ev.get("parent_id")
    ind = "  " if parent else ""
    return (f"{ev.get('ts', 0):.3f} {ind}{ev.get('name', '?'):<24} "
            f"{ev.get('dur_ms', 0):>10.3f} ms  "
            f"[{ev.get('span_id')}<-{parent}] {extra}").rstrip()


def format_jsonl_line(ev: dict) -> str:
    if "dur_ms" in ev:                       # span event
        return format_span(ev)
    labels = ",".join(f"{k}={v}"
                      for k, v in sorted((ev.get("labels") or {}).items()))
    val = (f"count={ev.get('count')} sum={_fmt(ev.get('sum'))}"
           if ev.get("type") == "histogram" else _fmt(ev.get("value")))
    return f"{ev.get('name', '?'):<32} {ev.get('type', '?'):<9} " \
           f"{labels:<24} {val}"


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def dump_file(path: str, tail: Optional[int] = None) -> str:
    if path.endswith(".jsonl"):
        with open(path) as f:
            events = [json.loads(ln) for ln in f if ln.strip()]
        if tail:
            events = events[-tail:]
        return "\n".join(format_jsonl_line(e) for e in events)
    with open(path) as f:
        blob = json.load(f)
    # BENCH blobs embed the snapshot under "registry"
    if "registry" in blob and isinstance(blob["registry"], dict):
        blob = blob["registry"]
    return format_snapshot(blob)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.observe.dump",
        description="Pretty-print a metrics registry snapshot or tail a "
                    "span/metrics JSONL log.")
    ap.add_argument("path", nargs="?",
                    help="snapshot .json (or BENCH blob) / span .jsonl")
    ap.add_argument("--tail", type=int, default=None, metavar="N",
                    help="only the last N JSONL events")
    ap.add_argument("--live", action="store_true",
                    help="dump the current process-wide registry")
    args = ap.parse_args(argv)
    if args.live:
        from deeplearning4j_tpu.observe.registry import get_registry
        print(format_snapshot(get_registry().snapshot()))
        return 0
    if not args.path:
        ap.error("need a path (or --live)")
    try:
        print(dump_file(args.path, args.tail))
    except BrokenPipeError:      # `dump ... | head` is a normal usage
        sys.stderr.close()
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
