"""SLO engine: declarative objectives over the telemetry series, with
multi-window burn-rate alerting (SRE-workbook style) and a runtime
anomaly watch.

An objective is "this series stays on the right side of a threshold"
(p99 TTFT ≤ 500 ms, shed+error ratio ≤ 1%, recompiles ≤ N/min, worker
restart streak ≤ 3). Evaluation runs on the series-sampler thread — one
pass over bounded windows of host-side floats, O(windows) work, never
on a request or step path and never touching a device value.

Burn rate follows the SRE workbook's multi-window form: the violating
fraction of the window divided by the error budget, evaluated over a
FAST window (default 5 min — is it bad *now*?) and a SLOW window
(default 1 h — has it been bad long enough to matter?). An SLO fires
only when BOTH exceed the burn threshold, which is what keeps a 30 s
blip from paging while a sustained breach fires within two evaluation
ticks (windows clamp to the samples that exist, so a fresh process
doesn't need an hour of history to alert).

Firing transitions close the loop into the existing machinery:
- a FlightRecorder dump tagged `slo_breach`, with the offending series
  windows embedded in the triggering ring event;
- a forced trace exemplar via `reqtrace.error_trace()` (sampling rate
  ignored), so the breach joins the trace store and /trace/{id};
- `slo_burn_rate{slo=...}` / `slo_firing{slo=...}` gauges and an
  `slo_breaches_total{slo=...}` counter published back into the
  registry (and therefore into /metrics and the next sampler tick);
- the serving `/healthz` handler folds `firing()` into its degraded
  verdict with the breach list in the body.

`AnomalyWatch` is the runtime complement to the static lint pack: a
recompile-storm detector (jit_compiles climbing again after the process
reached steady state, blamed on the responsible jit owner) and a
sync-regression detector (host syncs/step trending up against the run's
own baseline). Stdlib-only, like the rest of the observe package.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.observe.series import SeriesStore

logger = logging.getLogger("deeplearning4j_tpu")

DEFAULT_FAST_WINDOW_S = 300.0      # "is it bad now"
DEFAULT_SLOW_WINDOW_S = 3600.0     # "has it been bad long enough"
DEFAULT_BURN_THRESHOLD = 14.4      # SRE workbook fast-burn page factor
DEFAULT_BUDGET = 0.01              # 99% objective


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class SLO:
    """One declarative objective.

    kind:
      "value"        — each sampled point of the matched series is
                       compared to `threshold` (`op` side is the
                       violation); burn = violating fraction / budget.
      "ratio"        — Δ(bad counters) / Δ(all counters) over the
                       window; burn = ratio / budget.
      "rate_per_min" — counter increase per minute over the window;
                       burn = rate / threshold (budget unused).
    `series` is the metric name; `labels` restricts the match (subset
    semantics, so unlabeled matches every model). For "ratio",
    `num`/`den` are lists of label-dicts summed over the same series
    name."""

    __slots__ = ("name", "kind", "series", "labels", "op", "threshold",
                 "budget", "fast_s", "slow_s", "burn_threshold", "num",
                 "den", "description")

    def __init__(self, name: str, *, kind: str = "value",
                 series: str = "", labels: Optional[dict] = None,
                 op: str = ">", threshold: float = 0.0,
                 budget: float = DEFAULT_BUDGET,
                 fast_s: Optional[float] = None,
                 slow_s: Optional[float] = None,
                 burn_threshold: Optional[float] = None,
                 num: Optional[List[dict]] = None,
                 den: Optional[List[dict]] = None,
                 description: str = ""):
        if kind not in ("value", "ratio", "rate_per_min"):
            raise ValueError(f"unknown SLO kind: {kind!r}")
        if op not in (">", "<"):
            raise ValueError("op must be '>' or '<'")
        self.name = name
        self.kind = kind
        self.series = series
        self.labels = dict(labels or {})
        self.op = op
        self.threshold = float(threshold)
        self.budget = max(1e-9, float(budget))
        self.fast_s = float(fast_s if fast_s is not None else
                            _env_float("DL4J_TPU_SLO_FAST_S",
                                       DEFAULT_FAST_WINDOW_S))
        self.slow_s = float(slow_s if slow_s is not None else
                            _env_float("DL4J_TPU_SLO_SLOW_S",
                                       DEFAULT_SLOW_WINDOW_S))
        if burn_threshold is None:
            burn_threshold = (1.0 if kind == "rate_per_min" else
                              _env_float("DL4J_TPU_SLO_BURN",
                                         DEFAULT_BURN_THRESHOLD))
        self.burn_threshold = float(burn_threshold)
        self.num = [dict(d) for d in (num or [])]
        self.den = [dict(d) for d in (den or [])]
        self.description = description

    # ------------------------------------------------------- evaluation
    def _violates(self, v: float) -> bool:
        return v > self.threshold if self.op == ">" else v < self.threshold

    def burn(self, store: SeriesStore, window_s: float,
             now: float) -> tuple:
        """(burn_rate, observed_value, worst_window_points) over one
        window. Missing series → (0, None, []) — absent telemetry never
        fires an alert."""
        if self.kind == "value":
            worst_frac, worst_val, worst_pts = 0.0, None, []
            for ring in store.match(self.series, **self.labels):
                pts = ring.window(window_s, now)
                if not pts:
                    continue
                bad = sum(1 for _, v in pts if self._violates(v))
                frac = bad / len(pts)
                if frac >= worst_frac:
                    worst_frac = frac
                    worst_val = pts[-1][1]
                    worst_pts = pts
            return worst_frac / self.budget, worst_val, worst_pts
        if self.kind == "ratio":
            # a num/den label-dict may carry the reserved "__series__"
            # key to draw from a DIFFERENT series name — fleet-scope
            # objectives ratio across counters (failed handoffs over
            # handoffs) that live in distinct series.
            def _delta(lab: dict) -> float:
                lab = dict(lab)
                name = lab.pop("__series__", self.series)
                return store.delta(name, window_s, now, **lab)

            num = sum(_delta(lab) for lab in self.num)
            den = sum(_delta(lab) for lab in self.den)
            ratio = (num / den) if den > 0 else 0.0
            return ratio / self.budget, ratio, []
        # rate_per_min
        rate = store.rate(self.series, window_s, now,
                          **self.labels) * 60.0
        if self.threshold <= 0:
            return 0.0, rate, []
        return rate / self.threshold, rate, []

    def describe(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "series": self.series, "labels": self.labels,
                "op": self.op, "threshold": self.threshold,
                "budget": self.budget,
                "windows_s": [self.fast_s, self.slow_s],
                "burn_threshold": self.burn_threshold,
                "description": self.description}


def default_slos() -> List[SLO]:
    """The serving objective set, thresholds overridable via
    DL4J_TPU_SLO_* env knobs (ms where named so)."""
    e = _env_float
    return [
        SLO("latency-p99", series="serving_latency_seconds:p99",
            threshold=e("DL4J_TPU_SLO_P99_MS", 500.0) / 1e3,
            description="end-to-end request p99 within bound"),
        SLO("ttft-p99", series="serving_ttft_ms:p99",
            threshold=e("DL4J_TPU_SLO_TTFT_MS", 1000.0),
            description="decode time-to-first-token p99 within bound"),
        SLO("itl-p99", series="serving_itl_ms:p99",
            threshold=e("DL4J_TPU_SLO_ITL_MS", 250.0),
            description="decode inter-token latency p99 within bound"),
        SLO("availability", kind="ratio", series="serving_requests_total",
            num=[{"outcome": "failed"}, {"outcome": "shed"},
                 {"outcome": "expired"}],
            den=[{"outcome": "admitted"}, {"outcome": "shed"}],
            budget=e("DL4J_TPU_SLO_ERROR_BUDGET", 0.01),
            description="failed+shed+expired stay inside the error "
                        "budget"),
        SLO("queue-wait-p99", series="serving_queue_wait_ms:p99",
            threshold=e("DL4J_TPU_SLO_QUEUE_MS", 250.0),
            description="admission-queue wait p99 within bound"),
        SLO("recompile-rate", kind="rate_per_min", series="jit_compiles",
            threshold=e("DL4J_TPU_SLO_RECOMPILES_PER_MIN", 12.0),
            description="jit compiles per minute at steady state"),
        SLO("worker-restart-streak",
            series="serving_worker_restart_streak",
            threshold=e("DL4J_TPU_SLO_RESTART_STREAK", 3.0),
            description="consecutive slot-worker crash streak bounded"),
    ]


class SLOEngine:
    """Evaluates the objective set against the series store; runs as a
    sampler callback. All state transitions happen here, on the sampler
    thread — `firing()`/`snapshot()` are cheap reads for /healthz and
    /slo."""

    def __init__(self, store: SeriesStore, *, registry=None,
                 slos: Optional[List[SLO]] = None, flight=None):
        if registry is None:
            from deeplearning4j_tpu.observe.registry import get_registry
            registry = get_registry()
        self.store = store
        self.registry = registry
        self.slos = list(slos) if slos is not None else default_slos()
        self._flight = flight
        self._lock = threading.Lock()
        # graft: guarded-by(_lock)
        self._state: Dict[str, dict] = {
            s.name: {"firing": False, "since": None, "breaches": 0,
                     "trace_id": None} for s in self.slos}
        self._last: Optional[dict] = None
        self.evaluations = 0

    def _get_flight(self):
        if self._flight is not None:
            return self._flight
        from deeplearning4j_tpu.observe.flight import get_flight
        return get_flight()

    # ------------------------------------------------------- evaluation
    def evaluate(self, now: Optional[float] = None) -> dict:
        """One pass over every objective; returns (and caches) the /slo
        payload. O(windows) host work: each objective reads two bounded
        windows of floats."""
        now = now if now is not None else time.time()
        # graft: allow(GL301): single writer — evaluate() runs on the
        # sampler thread only
        self.evaluations += 1
        results = []
        for slo in self.slos:
            burn_fast, value, fast_pts = slo.burn(
                self.store, slo.fast_s, now)
            burn_slow, _, _ = slo.burn(self.store, slo.slow_s, now)
            firing = (burn_fast >= slo.burn_threshold
                      and burn_slow >= slo.burn_threshold)
            transition = None
            with self._lock:
                st = self._state[slo.name]
                if firing and not st["firing"]:
                    st["firing"] = True
                    st["since"] = now
                    st["breaches"] += 1
                    transition = "fired"
                elif not firing and st["firing"]:
                    st["firing"] = False
                    st["since"] = None
                    transition = "resolved"
            self.registry.gauge("slo_burn_rate", slo=slo.name).set(
                round(burn_fast, 4))
            self.registry.gauge("slo_firing", slo=slo.name).set(
                1.0 if firing else 0.0)
            if transition == "fired":
                self._on_breach(slo, now, burn_fast, burn_slow, value,
                                fast_pts, st)
            elif transition == "resolved":
                self._on_resolve(slo, now)
            results.append({
                **slo.describe(),
                "firing": st["firing"],
                "since": st["since"],
                "breaches": st["breaches"],
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "value": value,
                "trace_id": st["trace_id"] if st["firing"] else None,
            })
        payload = {"ts": round(now, 3), "evaluations": self.evaluations,
                   "firing": [r["name"] for r in results if r["firing"]],
                   "slos": results}
        with self._lock:
            self._last = payload
        return payload

    # ------------------------------------------------------ transitions
    def _on_breach(self, slo: SLO, now: float, burn_fast: float,
                   burn_slow: float, value, fast_pts, st: dict) -> None:
        self.registry.counter("slo_breaches_total", slo=slo.name).inc()
        # forced trace exemplar: the breach joins the trace store even
        # with sampling off, so /trace/{id} can show breach context
        from deeplearning4j_tpu.observe import reqtrace
        tid = reqtrace.error_trace(
            "slo.breach", slo=slo.name, value=value,
            threshold=slo.threshold, burn_fast=round(burn_fast, 3),
            burn_slow=round(burn_slow, 3))
        with self._lock:
            st["trace_id"] = tid
        try:
            fr = self._get_flight()
            # the offending windows ride the triggering ring event into
            # the dump (bounded: the recorder caps embedded lists)
            fr.record("slo_breach", slo=slo.name, value=value,
                      threshold=slo.threshold, op=slo.op,
                      burn_fast=round(burn_fast, 3),
                      burn_slow=round(burn_slow, 3), trace_id=tid,
                      windows={"fast_s": slo.fast_s,
                               "points": [[round(t, 3), v]
                                          for t, v in fast_pts[-24:]]})
            fr.dump(f"slo_breach_{slo.name}")
        # graft: allow(GL403): the black box is best-effort; the firing
        # state, gauges and trace above are the alert payload
        except Exception:
            pass
        logger.warning(
            "SLO %s FIRING: value=%s threshold=%s%s burn fast/slow="
            "%.1f/%.1f (trace %s)", slo.name, value, slo.op,
            slo.threshold, burn_fast, burn_slow, tid)

    def _on_resolve(self, slo: SLO, now: float) -> None:
        try:
            self._get_flight().record("slo_resolved", slo=slo.name)
        # graft: allow(GL403): resolution breadcrumb is best-effort
        except Exception:
            pass
        logger.info("SLO %s resolved", slo.name)

    # ----------------------------------------------------------- reads
    def firing(self) -> List[str]:
        with self._lock:
            return [n for n, st in self._state.items() if st["firing"]]

    def breaches(self) -> List[dict]:
        """Compact firing detail for the /healthz body."""
        with self._lock:
            last = self._last
        if not last:
            return []
        return [{"slo": r["name"], "value": r["value"],
                 "threshold": r["threshold"],
                 "burn_fast": r["burn_fast"]}
                for r in last["slos"] if r["firing"]]

    def snapshot(self) -> dict:
        with self._lock:
            last = self._last
        if last is not None:
            return last
        return self.evaluate()


class AnomalyWatch:
    """Runtime detectors over the series — the dynamic complement to
    graft-lint's static rules. Runs as a sampler callback; each
    detector warns once per (kind, owner) while the condition holds and
    re-arms when it clears.

    - recompile storm: a `jit_compiles{owner=...}` series climbing again
      AFTER the process reached steady state (a preceding quiet window),
      blamed on the responsible jit owner — shape churn that static
      analysis (GL20x) could not see.
    - sync regression: `train_host_syncs_per_step` trending above the
      run's own earlier baseline — a new accidental device→host sync on
      the step path (the runtime face of GL1xx)."""

    def __init__(self, store: SeriesStore, *, registry=None,
                 recent_s: float = 60.0, storm_compiles: int = 3,
                 sync_margin: float = 0.75):
        if registry is None:
            from deeplearning4j_tpu.observe.registry import get_registry
            registry = get_registry()
        self.store = store
        self.registry = registry
        self.recent_s = float(recent_s)
        self.storm_compiles = int(storm_compiles)
        self.sync_margin = float(sync_margin)
        self._active: Dict[tuple, bool] = {}
        self.warnings: List[dict] = []

    def _warn(self, key: tuple, message: str, **detail) -> None:
        if self._active.get(key):
            return                       # already warned; still active
        self._active[key] = True
        kind, owner = key
        self.registry.counter("anomaly_warnings_total", kind=kind).inc()
        self.warnings.append({"kind": kind, "owner": owner,
                              "ts": round(time.time(), 3), **detail})
        try:
            from deeplearning4j_tpu.observe.flight import get_flight
            get_flight().record("anomaly", kind=kind, owner=owner,
                                **detail)
        # graft: allow(GL403): ring breadcrumb is best-effort
        except Exception:
            pass
        logger.warning("anomaly watch: %s", message)

    def _clear(self, key: tuple) -> None:
        if self._active.get(key):
            self._active[key] = False    # re-arm

    def check(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        self._check_recompile_storm(now)
        self._check_sync_regression(now)

    def _check_recompile_storm(self, now: float) -> None:
        for ring in self.store.match("jit_compiles"):
            owner = ring.labels.get("owner", "?")
            key = ("recompile_storm", owner)
            pts = ring.points()
            if len(pts) < 3 or pts[0][0] > now - 2 * self.recent_s:
                continue                 # not at steady state yet
            recent = [v for t, v in pts if t >= now - self.recent_s]
            earlier = [v for t, v in pts if t < now - self.recent_s]
            if not recent or not earlier:
                continue
            burst = recent[-1] - earlier[-1]
            if burst >= self.storm_compiles:
                self._warn(
                    key,
                    f"recompile storm: jit owner {owner!r} compiled "
                    f"{burst:.0f} new programs in the last "
                    f"{self.recent_s:.0f}s after steady state — likely "
                    f"shape churn; see GL200/GL201 and the watchdog "
                    f"per-owner signatures",
                    owner=owner, burst=burst)
            else:
                self._clear(key)

    def _check_sync_regression(self, now: float) -> None:
        for ring in self.store.match("train_host_syncs_per_step"):
            key = ("sync_regression", ring.key)
            pts = ring.points()
            recent = [v for t, v in pts if t >= now - self.recent_s]
            earlier = sorted(v for t, v in pts
                             if t < now - self.recent_s)
            if not recent or len(earlier) < 3:
                continue
            baseline = earlier[len(earlier) // 2]    # median
            if recent[-1] >= baseline + self.sync_margin:
                owner = self._likely_sync_owner()
                self._warn(
                    key,
                    f"sync regression: host syncs/step rose to "
                    f"{recent[-1]:.2f} from a {baseline:.2f} baseline — "
                    f"a new device→host materialization on the step "
                    f"path (runtime face of GL100/GL102); most recently "
                    f"compiled jit owner: {owner}",
                    owner=owner, value=recent[-1], baseline=baseline)
            else:
                self._clear(key)

    @staticmethod
    def _likely_sync_owner() -> str:
        """Best-effort suspect: the jit owner with the most compiles in
        the watchdog — new dispatch paths usually compile first."""
        try:
            from deeplearning4j_tpu.observe.watchdog import get_watchdog
            per = get_watchdog().snapshot().get("per_owner") or {}
            if not per:
                return "unknown"
            return max(per.items(), key=lambda kv: kv[1]["compiles"])[0]
        # graft: allow(GL403): attribution is advisory; the warning
        # itself is the payload
        except Exception:
            return "unknown"
