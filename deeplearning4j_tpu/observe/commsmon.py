"""Collective-traffic observability: the comm ledger + reshard witness.

ROADMAP item 1's acceptance line — "no per-token collectives beyond
what GSPMD inserts" — was unmeasurable: the spine saw syncs, compiles
and FLOPs but was blind to all-reduce/all-gather traffic and to the
implicit resharding GSPMD inserts at cross-spec combines (GL802).
This module is the static↔runtime pair that closes the gap, in the
same pattern lockmon (GL702) and donatemon (GL801) already use:

**Compile-time comm ledger** — `parse_hlo_collectives()` walks a
compiled module's HLO text (`fn.lower(...).compile().as_text()`, fed
by the watchdog's `_CostProbe` seam) and extracts every collective:
op kind (all-reduce / all-gather / reduce-scatter / collective-permute
/ all-to-all, async `-start` forms counted once), payload bytes from
the operand/result shapes, and replica-group attribution (explicit
`{{0,1},{2,3}}` and iota `[2,4]<=[8]` forms). Per-op `wire_bytes` is
the per-device interconnect estimate under the one-pass ring
convention: `payload * (g-1)/g` for the group collectives (so a
data-parallel gradient all-reduce reconciles with the familiar
`4 * param_count * (n-1)/n`), full payload for collective-permute; a
bidirectional all-reduce costs 2x the ledger figure — the convention
is documented, fixed, and what every budget row uses. Degenerate
groups (g <= 1, single-participant) are kept in the per-op list but
excluded from totals and counters, so "zero collectives" is assertable
on a 1-replica mesh even if XLA emits a vestigial op. The ledger
publishes `jit_collective_ops_total{owner,kind}` /
`jit_collective_bytes_total{owner,kind}` counters and lands in
`RecompileWatchdog.snapshot()["per_owner"][tag]["collectives"]`.

**Runtime reshard witness** — opt-in via `DL4J_TPU_COMMSMON=1` (or
`force=True` in tests). `instrument()` wraps a jitted-dispatch entry
point; before each call the witness compares the COMMITTED sharding of
every array argument against the active `MeshContext` spine's declared
spec for that argument. A divergence is exactly the condition under
which GSPMD inserts an implicit resharding collective at dispatch —
the runtime face of a static GL802 finding, and events carry that rule
id via RUNTIME_RULE_HINTS so the two are string-comparable
(`tools/commsmon_smoke.py` asserts the equivalence). Each divergence
counts in `reshard_events_total{owner}` and the FIRST occurrence per
owner forces an `error_trace` exemplar, so a production reshard storm
is one trace id away from the exact arguments.

When disabled, `instrument()` returns the function UNCHANGED — not a
wrapper — so hot paths pay zero Python overhead, zero extra compiles,
zero extra syncs (the perf gate pins this, like donatemon). When
enabled, the check reads `.sharding`/`.spec` metadata only — committed
shardings are host-side metadata, so the witness adds no device→host
syncs even when on.
"""

from __future__ import annotations

import os
import re
import sys
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

_ENV_FLAG = "DL4J_TPU_COMMSMON"

#: Canonical collective kinds the ledger classifies (HLO opcode order
#: matters: longest-prefix first so `all-reduce-start` is not read as
#: `all-reduce` + junk, and `reduce-scatter` is not shadowed).
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

_BYTES_PER_ELEM = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1, "f8e5m2": 1,
    "f8e4m3": 1, "f8e3m4": 1, "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16,
}

# `f32[128,64]{1,0}` / `bf16[16]` / `f32[]` — dtype + dims, layout
# suffix ignored. Tuple shapes recurse through _shape_bytes.
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

# one HLO instruction: `%name = <shape> <opcode>(<operands>), attr=...`
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\(?[^=]*?\)?)\s*"
    r"((?:all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?)\(",
)

# replica_groups={{0,1},{2,3}}  (explicit) — groups counted by `{`
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{(\{[^}]*\}"
                                 r"(?:,\{[^}]*\})*)\}")
# replica_groups=[2,4]<=[8]     (iota: 2 groups of 4 over 8 devices)
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


def _component_bytes(shape_text: str) -> List[int]:
    """Byte size of each array shape mentioned in `shape_text` (tuple
    shapes yield one entry per component)."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        elem = _BYTES_PER_ELEM.get(dtype)
        if elem is None:
            continue                    # token/opaque types carry no bytes
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * elem)
    return out


def _shape_bytes(shape_text: str) -> int:
    """Total bytes of every array shape in `shape_text`."""
    return sum(_component_bytes(shape_text))


def _group_info(line: str) -> Tuple[int, int]:
    """(group_count, group_size) from a collective's replica_groups
    attribute; (1, 0) when absent/unparseable (size 0 = unknown)."""
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return max(int(m.group(1)), 1), int(m.group(2))
    m = _GROUPS_EXPLICIT_RE.search(line)
    if m:
        groups = m.group(1).split("},{")
        sizes = [len([t for t in g.strip("{}").split(",") if t.strip()])
                 for g in groups]
        return max(len(groups), 1), max(sizes) if sizes else 0
    return 1, 0


def wire_bytes(kind: str, payload: int, group_size: int) -> int:
    """Estimated per-device interconnect bytes for one collective under
    the one-pass ring convention (see module docstring). Unknown group
    size conservatively charges the full payload."""
    g = group_size
    if g <= 1:
        return 0 if g == 1 else payload
    if kind == "collective-permute":
        return payload
    return int(payload * (g - 1) / g)


def parse_hlo_collectives(text: str) -> List[dict]:
    """Walk compiled-module HLO text; one dict per collective op:

        {"kind", "payload_bytes", "wire_bytes", "group_count",
         "group_size", "degenerate", "name"}

    Tolerant by construction: lines that look collective-ish but do not
    parse are skipped (never raise — this runs inside the compile-cost
    seam), async `-done` halves are not double-counted (the `-start`
    carries the shape), and unknown ops simply do not match."""
    ops: List[dict] = []
    for line in text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        shape_text, opcode = m.group(1), m.group(2)
        kind = opcode[:-6] if opcode.endswith("-start") else opcode
        if kind not in COLLECTIVE_KINDS:
            continue
        # payload: the op's RESULT shape — for all-gather that is the
        # gathered (full) tensor, for all-reduce the reduced tensor;
        # reduce-scatter's result is the shard, so the full pre-scatter
        # payload is result * group_size (below). Async `-start` forms
        # print a tuple (operand, result, ...): the largest component
        # is the payload (the `-done` half never matches the opcode
        # pattern, so async ops count exactly once).
        if opcode.endswith("-start"):
            comps = _component_bytes(shape_text)
            payload = max(comps) if comps else 0
        else:
            payload = _shape_bytes(shape_text)
        gc, gs = _group_info(line)
        if kind == "reduce-scatter" and gs > 1:
            payload *= gs
        name_m = re.match(r"^\s*(?:ROOT\s+)?(%?[\w.\-]+)", line)
        ops.append({
            "kind": kind,
            "payload_bytes": int(payload),
            "wire_bytes": wire_bytes(kind, payload, gs),
            "group_count": gc,
            "group_size": gs,
            "degenerate": gs == 1,
            "name": name_m.group(1) if name_m else "?",
        })
    return ops


def summarize_collectives(ops: Sequence[dict]) -> dict:
    """Aggregate a parsed op list into the ledger block the watchdog
    snapshot and span attrs carry. Degenerate (single-participant)
    collectives are listed but excluded from totals, so `ops == 0`
    really means "no cross-device traffic"."""
    by_kind: Dict[str, dict] = {}
    total_ops = 0
    total_wire = 0
    total_payload = 0
    for op in ops:
        if op.get("degenerate"):
            continue
        k = op["kind"]
        row = by_kind.setdefault(
            k, {"ops": 0, "payload_bytes": 0, "wire_bytes": 0,
                "max_group_size": 0})
        row["ops"] += 1
        row["payload_bytes"] += op["payload_bytes"]
        row["wire_bytes"] += op["wire_bytes"]
        row["max_group_size"] = max(row["max_group_size"],
                                    op["group_size"])
        total_ops += 1
        total_wire += op["wire_bytes"]
        total_payload += op["payload_bytes"]
    return {"ops": total_ops,
            "payload_bytes": int(total_payload),
            "wire_bytes": int(total_wire),
            "degenerate_ops": sum(1 for op in ops
                                  if op.get("degenerate")),
            "by_kind": by_kind}


def publish_collectives(owner_class: str, summary: dict,
                        registry=None) -> None:
    """Bump the per-owner/kind ledger counters for one compiled
    program's collective inventory (bounded cardinality: owner CLASS x
    five kinds, same scheme as `jit_compiles`)."""
    if not summary.get("ops"):
        return
    if registry is None:
        from deeplearning4j_tpu.observe.registry import get_registry
        registry = get_registry()
    for kind, row in summary.get("by_kind", {}).items():
        registry.counter("jit_collective_ops_total", owner=owner_class,
                         kind=kind).inc(row["ops"])
        registry.counter("jit_collective_bytes_total", owner=owner_class,
                         kind=kind).inc(row["wire_bytes"])


# ===================================================== runtime witness

def commsmon_enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "") == "1"


_lock = threading.Lock()
_witness: Optional["ReshardWitness"] = None


def get_reshard_witness(*, force: bool = False,
                        ) -> Optional["ReshardWitness"]:
    """The process-global witness when commsmon is enabled (env flag or
    `force=True`), else None — callers instrument unconditionally and
    pay nothing when disabled (the donatemon contract)."""
    global _witness
    if not (force or commsmon_enabled()):
        return None
    with _lock:
        if _witness is None:
            _witness = ReshardWitness()
        return _witness


def reset_reshard_witness() -> None:
    global _witness
    with _lock:
        _witness = None


def _static_rules() -> Dict[str, str]:
    try:
        from deeplearning4j_tpu.analysis.rules import runtime_hint
        return {"reshard": runtime_hint("reshard")}
    except Exception:
        return {}


def _call_site(depth: int = 3) -> str:
    try:
        f = sys._getframe(depth)
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:
        return "?"


def _leaves(obj: Any, name: str) -> Iterator[Tuple[Any, str]]:
    """(leaf, path-name) pairs over the stdlib pytree containers the
    dispatch seams pass; only array-like leaves are yielded."""
    if isinstance(obj, dict):
        for k in obj:
            yield from _leaves(obj[k], f"{name}[{k!r}]")
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            yield from _leaves(v, f"{name}[{i}]")
    elif hasattr(obj, "shape") and hasattr(obj, "dtype"):
        yield obj, name


def _committed_spec(leaf) -> Optional[str]:
    """The leaf's committed PartitionSpec as a canonical string, or
    None when the leaf carries no NamedSharding metadata (host arrays,
    single-device values — nothing to diverge). Metadata only: this
    never materializes the buffer."""
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return "".join(str(tuple(spec)).split())


def canonical_spec(spec) -> str:
    """A PartitionSpec (or tuple) as the witness's canonical string —
    whitespace-free repr of the tuple form, matching the static pass's
    spec normalization."""
    return "".join(str(tuple(spec)).split())


class ReshardWitness:
    """Compares committed argument shardings against the spine's
    declared specs; counts divergences and forces a trace exemplar on
    the first event per owner."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.checks = 0
        self.events: List[dict] = []
        self._traced_owners: set = set()
        self._seen: set = set()

    # ----------------------------------------------------------- checks
    def check(self, obj: Any, name: str, expected, *, owner: str,
              site: Optional[str] = None) -> List[dict]:
        """Check every array leaf of `obj` against `expected` (a
        PartitionSpec / spec tuple, or a callable leaf -> spec for
        shape-dependent specs like batch sharding). A leaf with no
        committed NamedSharding is skipped — there is nothing for GSPMD
        to reshard. One GL802 event per (owner, leaf-path) pair."""
        site = site or _call_site()
        out: List[dict] = []
        first_for_owner = False
        with self._lock:
            self.checks += 1
            for leaf, path in _leaves(obj, name):
                actual = _committed_spec(leaf)
                if actual is None:
                    continue
                exp = expected(leaf) if callable(expected) else expected
                exp_s = canonical_spec(exp)
                if actual == exp_s:
                    continue
                key = (owner, path)
                if key in self._seen:
                    continue
                self._seen.add(key)
                ev = {"rule": "GL802",
                      "owner": owner,
                      "arg": path,
                      "root": name,
                      "expected": exp_s,
                      "actual": actual,
                      "site": site,
                      "thread": threading.current_thread().name}
                self.events.append(ev)
                out.append(ev)
                if owner not in self._traced_owners:
                    self._traced_owners.add(owner)
                    first_for_owner = True
        if out:
            self._publish(out, first_for_owner)
        return out

    def _publish(self, events: List[dict], force_trace: bool) -> None:
        """Counters + flight breadcrumbs + (first per owner) a forced
        trace exemplar — all best-effort, never load-bearing."""
        try:
            from deeplearning4j_tpu.observe.registry import get_registry
            reg = get_registry()
            for ev in events:
                reg.counter("reshard_events_total",
                            owner=ev["owner"]).inc()
        # graft: allow(GL403): the counter is the reporting channel;
        # the event list above is the source of truth either way
        except Exception:
            pass
        try:
            from deeplearning4j_tpu.observe.flight import get_flight
            fr = get_flight()
            for ev in events:
                fr.record("reshard_event", **ev)
        # graft: allow(GL403): breadcrumbs are optional by design
        except Exception:
            pass
        if force_trace:
            try:
                from deeplearning4j_tpu.observe import reqtrace
                ev = events[0]
                tid = reqtrace.error_trace(
                    "commsmon.reshard", rule=ev["rule"],
                    owner=ev["owner"], arg=ev["arg"],
                    expected=ev["expected"], actual=ev["actual"],
                    site=ev["site"])
                with self._lock:
                    ev["trace_id"] = tid
            # graft: allow(GL403): the forced exemplar is best-effort —
            # the event and counter already recorded the divergence
            except Exception:
                pass

    # ------------------------------------------------------------ report
    def report(self) -> dict:
        """What the smoke/chaos suites assert on, plus the static rule
        id (the runtime → static cross-check: an event here means
        graft-lint GL802 should have flagged the combine/placement at
        review time)."""
        with self._lock:
            return {"checks": self.checks,
                    "events": [dict(ev) for ev in self.events],
                    "static_rules": _static_rules()}


def instrument(fn, *, name: Optional[str] = None,
               arg_specs: Optional[Sequence] = None,
               arg_names: Optional[Sequence[str]] = None,
               witness: Optional[ReshardWitness] = None):
    """Wrap a jitted-dispatch entry point with the reshard witness.

    `arg_specs[i]` is the spine-declared PartitionSpec (or a callable
    leaf -> spec) for positional argument i; None positions are not
    checked. With commsmon disabled (no env flag, no explicit witness)
    the function is returned UNCHANGED — zero overhead on any hot path
    (pinned like donatemon's identity contract)."""
    if witness is None:
        witness = get_reshard_witness()
    if witness is None:
        return fn
    label = name or getattr(fn, "__name__", "jit_fn")
    specs = tuple(arg_specs or ())

    def _name(i: int) -> str:
        if arg_names is not None and i < len(arg_names):
            return arg_names[i]
        return f"arg{i}"

    def wrapper(*args, **kwargs):
        site = _call_site(2)
        for i, a in enumerate(args):
            if i < len(specs) and specs[i] is not None:
                witness.check(a, _name(i), specs[i], owner=label,
                              site=site)
        return fn(*args, **kwargs)

    wrapper.__name__ = f"commsmon[{label}]"
    wrapper.__wrapped__ = fn
    return wrapper


def check_dispatch_args(owner: str, named_args: Dict[str, tuple],
                        witness: Optional[ReshardWitness] = None) -> None:
    """In-place witness seam for dispatch loops that cannot wrap their
    callable (the executor's step closure, the session window): each
    entry is name -> (value, expected_spec). No-op when commsmon is
    off; callers guard with a cached `get_reshard_witness()` so the
    disabled path is one attribute read."""
    if witness is None:
        witness = get_reshard_witness()
    if witness is None:
        return
    site = _call_site(2)
    for arg_name, (value, expected) in named_args.items():
        witness.check(value, arg_name, expected, owner=owner, site=site)
