"""RecompileWatchdog — make jit-cache churn loud before it eats a run.

Every model-level compiled program in this framework lives in a
`_jit_cache` dict behind the `SeqCtxJitCache` mixin
(`parallel/ring_attention.py`): `MultiLayerNetwork` / `ComputationGraph`
train-step caches, `ParallelInference`'s per-bucket forwards,
`ParallelWrapper`'s sharded steps. A compile happens exactly when a NEW
key is inserted into one of those dicts — so `WatchedJitCache`
(installed by the mixin) reports every first-time insertion here, and
the watchdog:

- counts compiles per owning object and per owner class (the class-level
  count feeds the `jit_compiles` registry counter — bounded label
  cardinality);
- records each cache key's shape signature (the repr of the cache key,
  which embeds batch/feature/timestep shapes for the shape-keyed
  caches), so `snapshot()` shows exactly WHICH shapes churned;
- warns ONCE per owner when its compile count crosses the churn
  threshold — the signal that input shapes are unbucketed and every
  batch is paying a trace+compile (the classic silent 10x).

Counting costs one lock acquisition per COMPILE (not per step): compiles
are rare by construction, so the watchdog is always on.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")

DEFAULT_THRESHOLD = int(os.environ.get("DL4J_TPU_RECOMPILE_THRESHOLD", "10"))
_MAX_SIGNATURES = 64   # per-owner bound on recorded shape signatures


def _flight():
    """The crash ring, or None — watchdog events are breadcrumbs, never
    load-bearing, so any flight failure is swallowed here."""
    try:
        from deeplearning4j_tpu.observe.flight import get_flight
        return get_flight()
    # graft: allow(GL403): breadcrumbs are optional by design — compile
    # accounting must survive a broken flight recorder
    except Exception:
        return None


def _static_rules() -> str:
    """The graft-lint rules that flag recompile-churn patterns at review
    time — every watchdog warning names its static counterpart so the
    fix loop is 'run the linter', not 'read the runtime trace'."""
    try:
        from deeplearning4j_tpu.analysis.rules import runtime_hint
        return runtime_hint("recompile")
    except Exception:
        return ""


class RecompileWatchdog:
    """Counts jit compiles per owner; warn-once past `threshold`."""

    def __init__(self, *, threshold: int = DEFAULT_THRESHOLD,
                 metrics=None):
        self.threshold = max(1, int(threshold))
        self._metrics = metrics
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._signatures: Dict[str, List[str]] = {}
        self._costs: Dict[str, Dict[str, dict]] = {}
        self._collectives: Dict[str, Dict[str, dict]] = {}
        self._warned: set = set()

    def _registry(self):
        with self._lock:
            if self._metrics is None:
                from deeplearning4j_tpu.observe.registry import (
                    get_registry,
                )
                self._metrics = get_registry()
            return self._metrics

    def record_compile(self, owner_tag: str, owner_class: str,
                       key) -> None:
        """One first-time jit-cache insertion on `owner_tag` (a
        per-instance id) of class `owner_class` under cache key `key`."""
        warn_count = None
        with self._lock:
            n = self._counts.get(owner_tag, 0) + 1
            self._counts[owner_tag] = n
            sigs = self._signatures.setdefault(owner_tag, [])
            if len(sigs) < _MAX_SIGNATURES:
                sigs.append(repr(key))
            if n >= self.threshold and owner_tag not in self._warned:
                self._warned.add(owner_tag)
                warn_count = n
        self._registry().counter("jit_compiles", owner=owner_class).inc()
        fr = _flight()
        if fr is not None:
            # compiles are rare by construction — a ring breadcrumb each
            fr.record("jit_compile", owner=owner_class, tag=owner_tag,
                      key=repr(key)[:160])
        if warn_count is not None:
            with self._lock:
                recent = self._signatures.get(owner_tag, [])[-5:]
            logger.warning(
                "RecompileWatchdog: %s has compiled %d distinct jit "
                "programs (threshold %d) — likely shape churn (dynamic "
                "batch/sequence sizes defeating the jit cache). Recent "
                "cache keys: %s. Bucket input shapes (pad to fixed "
                "batch/length buckets, as ParallelInference does) or "
                "raise DL4J_TPU_RECOMPILE_THRESHOLD if this workload "
                "legitimately needs many programs. graft-lint rules %s "
                "flag the source patterns (python -m "
                "deeplearning4j_tpu.analysis).",
                owner_tag, warn_count, self.threshold, recent,
                _static_rules() or "n/a")
            if fr is not None:
                # threshold trip = the black-box moment: dump the ring so
                # the churned signatures survive the run
                fr.record("recompile_threshold_trip", owner=owner_class,
                          tag=owner_tag, compiles=warn_count,
                          threshold=self.threshold)
                fr.dump("recompile_threshold")

    def record_cost(self, owner_tag: str, owner_class: str, key,
                    cost: dict) -> None:
        """Attach an XLA cost report (flops / bytes_accessed /
        peak_memory_bytes, absent keys omitted) to a compile — fed by
        the `_CostProbe` the WatchedJitCache installs, or by
        `utils.profiling.step_cost` on the AOT path."""
        entry = {k: v for k, v in cost.items() if v is not None}
        with self._lock:
            costs = self._costs.setdefault(owner_tag, {})
            sig = repr(key)
            if len(costs) < _MAX_SIGNATURES or sig in costs:
                costs[sig] = entry
        reg = self._registry()
        if entry.get("flops"):
            reg.counter("jit_compile_flops_total",
                        owner=owner_class).inc(entry["flops"])
        if entry.get("bytes_accessed"):
            reg.counter("jit_compile_bytes_total",
                        owner=owner_class).inc(entry["bytes_accessed"])
        fr = _flight()
        if fr is not None:
            fr.record("compile_cost", owner=owner_class, tag=owner_tag,
                      key=repr(key)[:160], **entry)

    def record_collectives(self, owner_tag: str, owner_class: str, key,
                           summary: dict) -> None:
        """Attach a compiled-module collective inventory (the comm
        ledger block from `commsmon.summarize_collectives`) to a
        compile — fed by the `_CostProbe`'s compiled-artifact walk.
        Publishes the `jit_collective_ops_total` /
        `jit_collective_bytes_total{owner,kind}` counters (owner-CLASS
        label, bounded cardinality like `jit_compiles`)."""
        with self._lock:
            rows = self._collectives.setdefault(owner_tag, {})
            sig = repr(key)
            if len(rows) < _MAX_SIGNATURES or sig in rows:
                rows[sig] = dict(summary)
        from deeplearning4j_tpu.observe.commsmon import (
            publish_collectives,
        )
        publish_collectives(owner_class, summary,
                            registry=self._registry())
        if summary.get("ops"):
            fr = _flight()
            if fr is not None:
                fr.record("compile_collectives", owner=owner_class,
                          tag=owner_tag, key=repr(key)[:160],
                          ops=summary["ops"],
                          wire_bytes=summary["wire_bytes"])

    # --------------------------------------------------------- reporting
    def warned(self, owner_tag: str) -> bool:
        """Has this owner tripped the churn threshold? The deploy-gate
        seam: `ModelRegistry.deploy` checks the fresh runner's tag after
        warmup and rolls back instead of flipping a version that would
        recompile per-request."""
        with self._lock:
            return owner_tag in self._warned

    def compiles(self, owner_tag: Optional[str] = None) -> int:
        with self._lock:
            if owner_tag is not None:
                return self._counts.get(owner_tag, 0)
            return sum(self._counts.values())

    def owner_comm_totals(self, owner_tag: str) -> Optional[dict]:
        """Collective totals across every program this owner compiled
        ({"programs", "ops", "wire_bytes"}), or None when the comm
        ledger recorded nothing — the cheap host-side read the dispatch
        spans attach. Zero really means zero: degenerate
        single-participant ops never count (commsmon contract)."""
        with self._lock:
            rows = self._collectives.get(owner_tag)
            if rows is None:
                return None
            return {"programs": len(rows),
                    "ops": sum(r.get("ops", 0) for r in rows.values()),
                    "wire_bytes": sum(r.get("wire_bytes", 0)
                                      for r in rows.values())}

    def comm_totals(self) -> dict:
        """Whole-process comm rollup keyed by owner tag (flight dumps
        embed this next to the per-owner snapshot)."""
        with self._lock:
            tags = list(self._collectives)
        out = {}
        for tag in tags:
            tot = self.owner_comm_totals(tag)
            if tot is not None:
                out[tag] = tot
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "threshold": self.threshold,
                "static_rules": _static_rules(),
                "total_compiles": sum(self._counts.values()),
                "per_owner": {
                    tag: {"compiles": n,
                          "signatures": list(self._signatures.get(tag, ())),
                          "costs": dict(self._costs.get(tag, {})),
                          "collectives": {
                              sig: dict(row) for sig, row in
                              self._collectives.get(tag, {}).items()},
                          "warned": tag in self._warned}
                    for tag, n in self._counts.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._signatures.clear()
            self._costs.clear()
            self._collectives.clear()
            self._warned.clear()


def _cost_probe_enabled() -> bool:
    return os.environ.get("DL4J_TPU_COMPILE_COST", "1") != "0"


def _comm_ledger_enabled() -> bool:
    """The compile-time collective ledger (commsmon's static leg).
    Default ON like the cost probe — it prices one extra AOT compile
    per first-seen program, never a hot-path call. `DL4J_TPU_COMPILE_COMM=0`
    drops back to the cost-analysis-only ledger."""
    return os.environ.get("DL4J_TPU_COMPILE_COMM", "1") != "0"


_cost_failure_logged = False


def note_cost_analysis_failure(detail: str) -> None:
    """Cost analysis breaking must be visible, not silent (before this,
    `step_flops` swallowed every exception and MFU just disappeared):
    DEBUG-log the first failure, count every one — and never raise on a
    training path."""
    global _cost_failure_logged
    try:
        from deeplearning4j_tpu.observe.registry import get_registry
        get_registry().counter("profiling_cost_analysis_failures").inc()
    # graft: allow(GL403): the counter is the reporting channel — if the
    # registry itself is broken, the DEBUG log below still fires
    except Exception:
        pass
    if not _cost_failure_logged:
        _cost_failure_logged = True
        logger.debug(
            "compile cost analysis unavailable (%s); further failures "
            "are counted in profiling_cost_analysis_failures", detail)


def _arg_specs(args, kw):
    """ShapeDtypeStructs for the array arguments of a jit call (non-array
    leaves pass through untouched, so static args keep their values).
    Committed shardings ride along: without them the probe's lowering is
    an unsharded program, GSPMD inserts no collectives, and the comm
    ledger would read zero on every sharded owner."""
    try:
        import jax

        def spec(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                sharding = getattr(x, "sharding", None)
                if sharding is not None and getattr(
                        x, "_committed", True):
                    try:
                        return jax.ShapeDtypeStruct(
                            x.shape, x.dtype, sharding=sharding)
                    # graft: allow(GL403): a sharding ShapeDtypeStruct
                    # rejects (e.g. non-XLA-compatible sharding) →
                    # degrade to the unsharded spec below; the ledger
                    # then under-reports collectives rather than
                    # poisoning the dispatch path
                    except Exception:
                        pass
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return x

        return jax.tree_util.tree_map(spec, (args, kw))
    except Exception:
        note_cost_analysis_failure("argument spec capture failed")
        return None


def _record_lowered_cost(fn, specs, owner_tag, owner_class, key) -> None:
    lowered = None
    try:
        spec_args, spec_kw = specs
        lowered = fn.lower(*spec_args, **spec_kw)
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        cost = cost or {}
        get_watchdog().record_cost(owner_tag, owner_class, key, {
            "flops": float(cost.get("flops") or 0.0),
            "bytes_accessed": float(cost.get("bytes accessed") or 0.0),
        })
    except Exception as e:
        note_cost_analysis_failure(
            f"lowering cost analysis failed: {type(e).__name__}")
    # the comm ledger rides the same lowering; a failed cost_analysis
    # does not forfeit the collective walk (and vice versa)
    if lowered is not None and _comm_ledger_enabled():
        _record_compiled_comm(lowered, owner_tag, owner_class, key)


def _record_compiled_comm(lowered, owner_tag, owner_class, key) -> None:
    """Walk the compiled artifact for the collective inventory.

    Degradation contract (commsmon): a backend that cannot AOT-compile,
    or a jax version whose `as_text()` shape differs, degrades to the
    cost-analysis-only ledger — the failure logs once via
    `note_cost_analysis_failure`, counts in
    `profiling_cost_analysis_failures`, and NEVER raises into the jit
    cache seam. An artifact that compiles but yields unparseable text
    records an EMPTY inventory (parse tolerance lives in the parser)."""
    try:
        text = lowered.compile().as_text()
    except Exception as e:
        note_cost_analysis_failure(
            f"compiled-HLO comm walk failed: {type(e).__name__}")
        return
    try:
        from deeplearning4j_tpu.observe.commsmon import (
            parse_hlo_collectives, summarize_collectives,
        )
        if not isinstance(text, str):       # as_text() shape drifted
            raise TypeError(type(text).__name__)
        summary = summarize_collectives(parse_hlo_collectives(text))
        get_watchdog().record_collectives(owner_tag, owner_class, key,
                                          summary)
    except Exception as e:
        note_cost_analysis_failure(
            f"collective inventory failed: {type(e).__name__}")


class _CostProbe:
    """Transparent wrapper around a cached jit callable that, on its
    FIRST invocation, AOT-lowers the same function against the call's
    shape specs and records the XLA cost report with the watchdog — so
    every first-time compile the watchdog counts also carries what it
    costs.

    Why at call time, not insert time: insertion sees only the callable;
    lowering needs the concrete argument avals. Why specs are captured
    BEFORE the call runs: donated input buffers are deleted by the call
    itself. `Lowered.cost_analysis()` traces but does not compile, so
    the cost leg costs one extra trace. The comm-ledger leg
    (`DL4J_TPU_COMPILE_COMM`, default on) additionally AOT-compiles the
    lowering to walk the post-GSPMD module for collectives — one extra
    background compile per FIRST-seen program, never counted as a jit
    cache insertion and never on a steady-state path; nothing either
    leg touches can force a device sync."""

    __slots__ = ("fn", "_owner_tag", "_owner_class", "_key", "_done",
                 "_lock")

    def __init__(self, fn, owner_tag, owner_class, key):
        self.fn = fn
        self._owner_tag = owner_tag
        self._owner_class = owner_class
        self._key = key
        self._done = False
        self._lock = threading.Lock()

    def __call__(self, *args, **kw):
        with self._lock:
            probe, self._done = (not self._done), True
        specs = _arg_specs(args, kw) if probe else None
        out = self.fn(*args, **kw)
        if specs is not None:
            _record_lowered_cost(self.fn, specs, self._owner_tag,
                                 self._owner_class, self._key)
        return out

    def __getattr__(self, name):
        return getattr(self.fn, name)


class WatchedJitCache(dict):
    """A jit-cache dict that reports first-time insertions (= compiles)
    to the watchdog, wrapping jit callables in a one-shot `_CostProbe`
    so the compile's XLA cost is recorded too. Holds only the owner's
    tag strings, never the owner itself — a cache must not keep its
    model alive."""

    __slots__ = ("owner_tag", "owner_class")

    def __init__(self, owner=None, *, owner_tag: Optional[str] = None,
                 owner_class: Optional[str] = None):
        super().__init__()
        cls = owner_class or (type(owner).__name__ if owner is not None
                              else "unknown")
        self.owner_class = cls
        self.owner_tag = owner_tag or (
            f"{cls}@{id(owner):#x}" if owner is not None else cls)

    def __setitem__(self, key, value):
        if key not in self:
            get_watchdog().record_compile(
                self.owner_tag, self.owner_class, key)
            if (_cost_probe_enabled() and callable(value)
                    and hasattr(value, "lower")
                    and not isinstance(value, _CostProbe)):
                value = _CostProbe(value, self.owner_tag,
                                   self.owner_class, key)
        super().__setitem__(key, value)

    def setdefault(self, key, default=None):
        if key not in self:
            self[key] = default      # route through __setitem__
        return self[key]             # the stored (possibly probed) value

    def update(self, *args, **kw):
        for k, v in dict(*args, **kw).items():
            self[k] = v


# ------------------------------------------------------------ process-wide
_default_watchdog = RecompileWatchdog()
_lock = threading.Lock()


def get_watchdog() -> RecompileWatchdog:
    return _default_watchdog


def set_watchdog(watchdog: RecompileWatchdog) -> RecompileWatchdog:
    """Swap the process-wide watchdog (tests pin thresholds this way);
    returns the previous one."""
    global _default_watchdog
    with _lock:
        prev, _default_watchdog = _default_watchdog, watchdog
    return prev
