"""RecompileWatchdog — make jit-cache churn loud before it eats a run.

Every model-level compiled program in this framework lives in a
`_jit_cache` dict behind the `SeqCtxJitCache` mixin
(`parallel/ring_attention.py`): `MultiLayerNetwork` / `ComputationGraph`
train-step caches, `ParallelInference`'s per-bucket forwards,
`ParallelWrapper`'s sharded steps. A compile happens exactly when a NEW
key is inserted into one of those dicts — so `WatchedJitCache`
(installed by the mixin) reports every first-time insertion here, and
the watchdog:

- counts compiles per owning object and per owner class (the class-level
  count feeds the `jit_compiles` registry counter — bounded label
  cardinality);
- records each cache key's shape signature (the repr of the cache key,
  which embeds batch/feature/timestep shapes for the shape-keyed
  caches), so `snapshot()` shows exactly WHICH shapes churned;
- warns ONCE per owner when its compile count crosses the churn
  threshold — the signal that input shapes are unbucketed and every
  batch is paying a trace+compile (the classic silent 10x).

Counting costs one lock acquisition per COMPILE (not per step): compiles
are rare by construction, so the watchdog is always on.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")

DEFAULT_THRESHOLD = int(os.environ.get("DL4J_TPU_RECOMPILE_THRESHOLD", "10"))
_MAX_SIGNATURES = 64   # per-owner bound on recorded shape signatures


def _static_rules() -> str:
    """The graft-lint rules that flag recompile-churn patterns at review
    time — every watchdog warning names its static counterpart so the
    fix loop is 'run the linter', not 'read the runtime trace'."""
    try:
        from deeplearning4j_tpu.analysis.rules import runtime_hint
        return runtime_hint("recompile")
    except Exception:
        return ""


class RecompileWatchdog:
    """Counts jit compiles per owner; warn-once past `threshold`."""

    def __init__(self, *, threshold: int = DEFAULT_THRESHOLD,
                 metrics=None):
        self.threshold = max(1, int(threshold))
        self._metrics = metrics
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._signatures: Dict[str, List[str]] = {}
        self._warned: set = set()

    def _registry(self):
        with self._lock:
            if self._metrics is None:
                from deeplearning4j_tpu.observe.registry import (
                    get_registry,
                )
                self._metrics = get_registry()
            return self._metrics

    def record_compile(self, owner_tag: str, owner_class: str,
                       key) -> None:
        """One first-time jit-cache insertion on `owner_tag` (a
        per-instance id) of class `owner_class` under cache key `key`."""
        warn_count = None
        with self._lock:
            n = self._counts.get(owner_tag, 0) + 1
            self._counts[owner_tag] = n
            sigs = self._signatures.setdefault(owner_tag, [])
            if len(sigs) < _MAX_SIGNATURES:
                sigs.append(repr(key))
            if n >= self.threshold and owner_tag not in self._warned:
                self._warned.add(owner_tag)
                warn_count = n
        self._registry().counter("jit_compiles", owner=owner_class).inc()
        if warn_count is not None:
            with self._lock:
                recent = self._signatures.get(owner_tag, [])[-5:]
            logger.warning(
                "RecompileWatchdog: %s has compiled %d distinct jit "
                "programs (threshold %d) — likely shape churn (dynamic "
                "batch/sequence sizes defeating the jit cache). Recent "
                "cache keys: %s. Bucket input shapes (pad to fixed "
                "batch/length buckets, as ParallelInference does) or "
                "raise DL4J_TPU_RECOMPILE_THRESHOLD if this workload "
                "legitimately needs many programs. graft-lint rules %s "
                "flag the source patterns (python -m "
                "deeplearning4j_tpu.analysis).",
                owner_tag, warn_count, self.threshold, recent,
                _static_rules() or "n/a")

    # --------------------------------------------------------- reporting
    def compiles(self, owner_tag: Optional[str] = None) -> int:
        with self._lock:
            if owner_tag is not None:
                return self._counts.get(owner_tag, 0)
            return sum(self._counts.values())

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "threshold": self.threshold,
                "static_rules": _static_rules(),
                "total_compiles": sum(self._counts.values()),
                "per_owner": {
                    tag: {"compiles": n,
                          "signatures": list(self._signatures.get(tag, ())),
                          "warned": tag in self._warned}
                    for tag, n in self._counts.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._signatures.clear()
            self._warned.clear()


class WatchedJitCache(dict):
    """A jit-cache dict that reports first-time insertions (= compiles)
    to the watchdog. Holds only the owner's tag strings, never the owner
    itself — a cache must not keep its model alive."""

    __slots__ = ("owner_tag", "owner_class")

    def __init__(self, owner=None, *, owner_tag: Optional[str] = None,
                 owner_class: Optional[str] = None):
        super().__init__()
        cls = owner_class or (type(owner).__name__ if owner is not None
                              else "unknown")
        self.owner_class = cls
        self.owner_tag = owner_tag or (
            f"{cls}@{id(owner):#x}" if owner is not None else cls)

    def __setitem__(self, key, value):
        if key not in self:
            get_watchdog().record_compile(
                self.owner_tag, self.owner_class, key)
        super().__setitem__(key, value)

    def setdefault(self, key, default=None):
        if key not in self:
            self[key] = default      # route through __setitem__
            return default
        return self[key]

    def update(self, *args, **kw):
        for k, v in dict(*args, **kw).items():
            self[k] = v


# ------------------------------------------------------------ process-wide
_default_watchdog = RecompileWatchdog()
_lock = threading.Lock()


def get_watchdog() -> RecompileWatchdog:
    return _default_watchdog


def set_watchdog(watchdog: RecompileWatchdog) -> RecompileWatchdog:
    """Swap the process-wide watchdog (tests pin thresholds this way);
    returns the previous one."""
    global _default_watchdog
    with _lock:
        prev, _default_watchdog = _default_watchdog, watchdog
    return prev
