"""Unified observability core: one telemetry spine for training + serving.

Before this package, observability was four disconnected islands
(`optim/listeners.py` counters, `utils/profiling.py` traces, `ui/stats.py`
reports, `serving/metrics.py`'s private aggregator) and the two costs that
silently destroy TPU utilization — jit-cache recompiles from shape churn
and accidental host syncs in the deferred-dispatch pipeline — were
invisible at runtime. This package is the one instrumentation contract
every layer shares:

- `MetricsRegistry` (`registry.py`) — process-wide counters, gauges, and
  histograms with bounded reservoirs and labeled series; thread-safe;
  snapshot + Prometheus-text + JSONL exporters. The serving `/metrics`
  endpoint and the training listeners are renderers over this registry.
- `span()` (`trace.py`) — async-dispatch-safe host-side tracing spans.
  Spans time HOST work only and never call `float()` /
  `block_until_ready()` on device values, so enabling tracing cannot
  stall the dispatch pipeline (pinned by the ≤1-sync-per-epoch test).
- `RecompileWatchdog` (`watchdog.py`) — counts every jit-cache compile
  across the per-model `_jit_cache` seams and warns once per model when
  compiles cross a churn threshold (the classic silent 10x).
- `HostSyncMonitor` (`syncmon.py`) — opt-in runtime generalization of the
  test-only dispatch-depth guard: counts device→host materializations so
  `PerformanceListener` can report syncs/step in production.
- `LockWitness` (`lockmon.py`) — opt-in (`DL4J_TPU_LOCKMON=1`) runtime
  cross-check for the GL7xx lockset rules: named-lock wrappers record
  per-thread acquisition orders (lock-order inversions → GL702) and
  guarded-field access races (→ GL701) during the thread-hammer suites.
- `DonationWitness` (`donatemon.py`) — opt-in (`DL4J_TPU_DONATEMON=1`)
  runtime cross-check for the GL8xx sharding/donation rules:
  `instrument()` wraps donating jitted entry points, marks donated
  buffers dead (id-pinned by strong refs), and emits GL801-tagged
  events when a stale buffer is passed back in; with the flag off the
  step function is returned unchanged (zero overhead, perf-gate
  pinned).
- comm ledger + `ReshardWitness` (`commsmon.py`) — collective-traffic
  observability: the watchdog's compile probe walks every compiled
  program's HLO for all-reduce/all-gather/reduce-scatter/
  collective-permute/all-to-all inventory
  (`jit_collective_{ops,bytes}_total{owner,kind}`, snapshot
  `collectives` blocks), and an opt-in (`DL4J_TPU_COMMSMON=1`) runtime
  witness compares committed argument shardings against the mesh
  spine's declared specs at the dispatch seams — divergences are
  GL802-tagged (`reshard_events_total{owner}`), string-comparable with
  static shardflow findings; off means the dispatch path is unchanged.
- `python -m deeplearning4j_tpu.observe.dump` (`dump.py`) — pretty-print
  a registry snapshot or tail a span JSONL.
- `reqtrace.py` — request-scoped causal trace trees (TraceContext at the
  HTTP edge, fan-in dispatch spans, per-step session spans, training
  dispatch windows) with head-based sampling and a bounded TraceStore;
  served by `GET /trace/{id}` and embedded in flight dumps.
- `series.py` — bounded time-series history over the registry: a
  fixed-capacity ring per metric key fed by a background sampler thread
  (`DL4J_TPU_SERIES_INTERVAL`), with sliding-window rates for counters
  and windowed p50/95/99 for histograms. Host-side only: zero device
  syncs, zero compiles, zero allocation per sample (perf-gate pinned).
- `slo.py` — declarative objectives over those series with multi-window
  burn-rate alerting (fast 5m + slow 1h); firing SLOs dump the flight
  ring (`slo_breach`), mint a forced trace exemplar, degrade /healthz
  and publish `slo_burn_rate`/`slo_breaches_total`; plus the runtime
  AnomalyWatch (recompile-storm + sync-regression detectors).

The package imports only the stdlib (no jax) so the dump tool and the
registry work anywhere; jax seams are bound lazily at install time.
"""

from deeplearning4j_tpu.observe.registry import (
    MetricsRegistry, get_registry, set_registry,
)
from deeplearning4j_tpu.observe.trace import (
    SpanLog, emit_manual_span, install_span_log, read_spans, span,
    tracing_enabled, uninstall_span_log,
)
from deeplearning4j_tpu.observe.watchdog import (
    RecompileWatchdog, WatchedJitCache, get_watchdog, set_watchdog,
)
from deeplearning4j_tpu.observe.syncmon import HostSyncMonitor, current_monitor
from deeplearning4j_tpu.observe.lockmon import (
    LockWitness, MonitoredLock, get_witness, lockmon_enabled,
    reset_witness,
)
from deeplearning4j_tpu.observe.donatemon import (
    DonationWitness, UseAfterDonateError, donatemon_enabled,
    get_donation_witness, instrument, reset_donation_witness,
)
from deeplearning4j_tpu.observe.commsmon import (
    ReshardWitness, commsmon_enabled, get_reshard_witness,
    parse_hlo_collectives, reset_reshard_witness, summarize_collectives,
)
from deeplearning4j_tpu.observe.flight import (
    FlightRecorder, get_flight, latest_dump, read_dump, set_flight,
)
from deeplearning4j_tpu.observe.devicemon import (
    DeviceMonitor, device_memory_summary, get_device_monitor,
    maybe_start_monitor, set_device_monitor,
)
from deeplearning4j_tpu.observe.attribution import (
    StepAttribution, attribution_enabled,
)
from deeplearning4j_tpu.observe.reqtrace import (
    TraceContext, TraceStore, active_dispatch, begin_dispatch,
    current_trace, end_dispatch, error_extra, error_trace, finish_root,
    get_trace_store, new_trace, record_span, set_trace_store,
)
from deeplearning4j_tpu.observe.series import (
    SeriesRing, SeriesSampler, SeriesStore, series_key,
)
from deeplearning4j_tpu.observe.slo import (
    SLO, AnomalyWatch, SLOEngine, default_slos,
)

__all__ = [
    "MetricsRegistry", "get_registry", "set_registry",
    "SpanLog", "span", "install_span_log", "uninstall_span_log",
    "tracing_enabled", "read_spans", "emit_manual_span",
    "RecompileWatchdog", "WatchedJitCache", "get_watchdog", "set_watchdog",
    "HostSyncMonitor", "current_monitor",
    "LockWitness", "MonitoredLock", "get_witness", "lockmon_enabled",
    "reset_witness",
    "DonationWitness", "UseAfterDonateError", "donatemon_enabled",
    "get_donation_witness", "instrument", "reset_donation_witness",
    "ReshardWitness", "commsmon_enabled", "get_reshard_witness",
    "reset_reshard_witness", "parse_hlo_collectives",
    "summarize_collectives",
    "FlightRecorder", "get_flight", "set_flight", "latest_dump", "read_dump",
    "DeviceMonitor", "device_memory_summary", "get_device_monitor",
    "maybe_start_monitor", "set_device_monitor",
    "StepAttribution", "attribution_enabled",
    "TraceContext", "TraceStore", "get_trace_store", "set_trace_store",
    "new_trace", "finish_root", "record_span", "error_trace", "error_extra",
    "current_trace", "begin_dispatch", "active_dispatch", "end_dispatch",
    "SeriesRing", "SeriesSampler", "SeriesStore", "series_key",
    "SLO", "AnomalyWatch", "SLOEngine", "default_slos",
]
