"""Federated metrics — merge N replica registry snapshots into one
fleet-wide view (the data model behind the router's `/fleet/metrics`).

This is the cross-PROCESS half of the observability spine: each replica
keeps its own `MetricsRegistry` (registry.py) and the router's poll loop
pulls `/metrics?format=registry` snapshots and feeds them here. The
merge rules are the whole point, and they are pinned by
tests/test_fedmon.py:

- **Counters: summed with per-replica monotonic delta tracking.** A
  replica restart resets its raw counter to 0; the federation notices
  the raw value going backwards, re-bases the delta at 0, and keeps the
  pre-restart total — the fleet counter NEVER goes negative and never
  double-counts.
- **Histograms: merged bucket-wise.** Every process buckets on the same
  `registry.BUCKET_EDGES` ladder, so the fleet histogram over replicas
  is exactly the histogram of the union of observations (count / sum /
  min / max / per-bin counts all loss-free; quantiles estimated from
  the merged cumulative distribution). Restart-safe via the same
  delta scheme as counters.
- **Gauges: labeled, not summed.** A gauge is a point-in-time reading
  per process; the fleet view fans it out under a `replica=` label.
- **Staleness is explicit.** A replica that fails a scrape (or has not
  been scraped within the TTL) gets `fleet_scrape_stale{replica=} = 1`
  and keeps its last-known series — operators see "stale", never a
  silent gap or a phantom zero.

Strictly pull-based and host-side: stdlib only, no network (the scraper
in serving/fleet/obsplane.py does the fetching), no locks shared with
any replica, no device access — federation can never add a sync or a
compile to a dispatch path (PERF_NOTES contract, perf-gate pinned).
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.observe.registry import (
    BUCKET_EDGES, BUCKET_VERSION,
)

# replica counted stale when its last successful scrape is older
ENV_STALE_S = "DL4J_TPU_FLEET_STALE_S"
DEFAULT_STALE_S = 15.0

_NBINS = len(BUCKET_EDGES) + 1


def quantile_from_buckets(buckets: List[int], q: float) -> Optional[float]:
    """Estimate the q-quantile from per-bin counts over BUCKET_EDGES
    (linear interpolation inside the covering bin; the +Inf overflow
    bin clamps to the last edge)."""
    total = sum(buckets)
    if total <= 0:
        return None
    rank = max(1, math.ceil(q * total))
    cum = 0
    for i, c in enumerate(buckets):
        cum += c
        if cum >= rank and c > 0:
            lo = BUCKET_EDGES[i - 1] if i > 0 else 0.0
            hi = BUCKET_EDGES[i] if i < len(BUCKET_EDGES) \
                else BUCKET_EDGES[-1]
            frac = (rank - (cum - c)) / c
            return round(lo + frac * (hi - lo), 6)
    return float(BUCKET_EDGES[-1])


def _labels_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _CounterState:
    """Per-(series, replica) monotonic delta tracker."""

    __slots__ = ("raw", "total")

    def __init__(self):
        self.raw = 0.0
        self.total = 0.0

    def update(self, raw: float) -> None:
        raw = float(raw)
        # raw went backwards => the replica restarted: the new raw IS
        # the count since the reset, so the delta re-bases at 0 and the
        # pre-restart total is preserved (never negative).
        self.total += raw - self.raw if raw >= self.raw else raw
        self.raw = raw


class _HistState:
    """Per-(series, replica) bucket-wise delta tracker."""

    __slots__ = ("raw_count", "raw_sum", "raw_buckets",
                 "count", "sum", "buckets", "min", "max")

    def __init__(self):
        self.raw_count = 0
        self.raw_sum = 0.0
        self.raw_buckets = [0] * _NBINS
        self.count = 0
        self.sum = 0.0
        self.buckets = [0] * _NBINS
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def update(self, entry: dict) -> None:
        count = int(entry.get("count") or 0)
        total = float(entry.get("sum") or 0.0)
        buckets = entry.get("buckets")
        if not isinstance(buckets, list) or len(buckets) != _NBINS \
                or entry.get("bucket_v") != BUCKET_VERSION:
            buckets = [0] * _NBINS
        if count >= self.raw_count:
            self.count += count - self.raw_count
            self.sum += total - self.raw_sum
            self.buckets = [
                b + max(0, n - o) for b, n, o in
                zip(self.buckets, buckets, self.raw_buckets)]
        else:                                    # replica restarted
            self.count += count
            self.sum += total
            self.buckets = [b + n for b, n in zip(self.buckets, buckets)]
        self.raw_count, self.raw_sum = count, total
        self.raw_buckets = list(buckets)
        for bound, cur in ((entry.get("min"), "min"),
                           (entry.get("max"), "max")):
            if isinstance(bound, (int, float)):
                prev = getattr(self, cur)
                pick = min if cur == "min" else max
                setattr(self, cur,
                        bound if prev is None else pick(prev, bound))


class FleetFederation:
    """The merged fleet view. `ingest()` on the scrape thread,
    `snapshot()`/`total()`/`merged()` from any reader."""

    def __init__(self, *, stale_after_s: Optional[float] = None):
        self.stale_after_s = float(
            stale_after_s if stale_after_s is not None
            else os.environ.get(ENV_STALE_S, DEFAULT_STALE_S))
        self._lock = threading.Lock()
        # (name, labels) -> {replica: state}
        # graft: guarded-by(_lock)
        self._counters: Dict[tuple, Dict[str, _CounterState]] = {}
        # graft: guarded-by(_lock)
        self._gauges: Dict[tuple, Dict[str, float]] = {}
        # graft: guarded-by(_lock)
        self._hists: Dict[tuple, Dict[str, _HistState]] = {}
        # graft: guarded-by(_lock)
        self._replicas: Dict[str, dict] = {}

    # -------------------------------------------------------- ingestion
    def ingest(self, replica: str, snapshot: dict,
               now: Optional[float] = None) -> None:
        """Merge one replica's registry snapshot (registry.snapshot()
        shape: {"ts", "series": {name: [entry, ...]}})."""
        now = time.time() if now is None else now
        series = snapshot.get("series") or {}
        with self._lock:
            row = self._replicas.setdefault(
                replica, {"scrapes": 0, "failures": 0, "ok": False,
                          "last_scrape_ts": None})
            row["scrapes"] += 1
            row["ok"] = True
            row["last_scrape_ts"] = now
            for name, entries in series.items():
                for entry in entries:
                    labels = dict(entry.get("labels") or {})
                    labels.pop("replica", None)
                    key = (name, _labels_key(labels))
                    kind = entry.get("type")
                    if kind == "counter":
                        self._counters.setdefault(key, {}).setdefault(
                            replica, _CounterState()).update(
                                entry.get("value") or 0.0)
                    elif kind == "gauge":
                        self._gauges.setdefault(key, {})[replica] = \
                            float(entry.get("value") or 0.0)
                    elif kind == "histogram":
                        self._hists.setdefault(key, {}).setdefault(
                            replica, _HistState()).update(entry)

    def mark_unreachable(self, replica: str,
                         now: Optional[float] = None) -> None:
        """Record a failed scrape — the replica keeps its last-known
        series but is flagged stale immediately."""
        with self._lock:
            row = self._replicas.setdefault(
                replica, {"scrapes": 0, "failures": 0, "ok": False,
                          "last_scrape_ts": None})
            row["failures"] += 1
            row["ok"] = False

    def forget(self, replica: str) -> None:
        """Drop a removed replica's per-replica state (its contribution
        to counter/histogram totals is already banked and stays)."""
        with self._lock:
            self._replicas.pop(replica, None)
            for table in (self._counters, self._gauges, self._hists):
                for per_rep in table.values():
                    per_rep.pop(replica, None)

    # ---------------------------------------------------------- readers
    def replicas(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Scrape-health rows: age / staleness per replica."""
        now = time.time() if now is None else now
        out = {}
        with self._lock:
            for name, row in self._replicas.items():
                ts = row["last_scrape_ts"]
                age = None if ts is None else max(0.0, now - ts)
                out[name] = {
                    "last_scrape_ts": ts,
                    "age_s": None if age is None else round(age, 3),
                    "scrapes": row["scrapes"],
                    "failures": row["failures"],
                    "stale": (not row["ok"]) or age is None
                             or age > self.stale_after_s,
                }
        return out

    def total(self, name: str, labels: Optional[dict] = None) -> float:
        """Fleet-wide counter total (sum of restart-safe per-replica
        totals; `labels` subset-matches, None matches every label set)."""
        out = 0.0
        with self._lock:
            for (nm, lk), per_rep in self._counters.items():
                if nm != name:
                    continue
                if labels and not set(_labels_key(labels)) <= set(lk):
                    continue
                out += sum(st.total for st in per_rep.values())
        return out

    def merged(self, name: str,
               labels: Optional[dict] = None) -> Optional[dict]:
        """Bucket-wise merged fleet histogram for one series name —
        equal to a histogram of the union of every replica's
        observations."""
        count, total = 0, 0.0
        buckets = [0] * _NBINS
        lo: Optional[float] = None
        hi: Optional[float] = None
        found = False
        with self._lock:
            for (nm, lk), per_rep in self._hists.items():
                if nm != name:
                    continue
                if labels and not set(_labels_key(labels)) <= set(lk):
                    continue
                for st in per_rep.values():
                    found = True
                    count += st.count
                    total += st.sum
                    buckets = [a + b for a, b in zip(buckets, st.buckets)]
                    if st.min is not None:
                        lo = st.min if lo is None else min(lo, st.min)
                    if st.max is not None:
                        hi = st.max if hi is None else max(hi, st.max)
        if not found:
            return None
        return {"count": count, "sum": round(total, 6), "min": lo,
                "max": hi, "buckets": buckets,
                "p50": quantile_from_buckets(buckets, 0.5),
                "p95": quantile_from_buckets(buckets, 0.95),
                "p99": quantile_from_buckets(buckets, 0.99)}

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Registry-snapshot-shaped merged view: every series fanned out
        under a `replica=` label, plus scrape-health gauges
        (`fleet_scrape_stale{replica=}` / `fleet_scrape_age_seconds`)
        and per-name fleet aggregates (counter sums, bucket-merged
        histograms) under entries WITHOUT a replica label."""
        now = time.time() if now is None else now
        reps = self.replicas(now)
        out: Dict[str, list] = {}

        def add(name, entry):
            out.setdefault(name, []).append(entry)

        with self._lock:
            for (name, lk), per_rep in self._counters.items():
                agg = 0.0
                for rep, st in sorted(per_rep.items()):
                    agg += st.total
                    add(name, {"type": "counter",
                               "labels": dict(lk, replica=rep),
                               "value": round(st.total, 6)})
                add(name, {"type": "counter", "labels": dict(lk),
                           "value": round(agg, 6)})
            for (name, lk), per_rep in self._gauges.items():
                for rep, v in sorted(per_rep.items()):
                    add(name, {"type": "gauge",
                               "labels": dict(lk, replica=rep),
                               "value": v})
            for (name, lk), per_rep in self._hists.items():
                agg = _HistState()
                for rep, st in sorted(per_rep.items()):
                    add(name, {
                        "type": "histogram",
                        "labels": dict(lk, replica=rep),
                        "count": st.count, "sum": round(st.sum, 6),
                        "min": st.min, "max": st.max,
                        "p50": quantile_from_buckets(st.buckets, 0.5),
                        "p95": quantile_from_buckets(st.buckets, 0.95),
                        "p99": quantile_from_buckets(st.buckets, 0.99),
                    })
                    agg.count += st.count
                    agg.sum += st.sum
                    agg.buckets = [a + b for a, b in
                                   zip(agg.buckets, st.buckets)]
                    for v, cur, pick in ((st.min, "min", min),
                                         (st.max, "max", max)):
                        if v is not None:
                            prev = getattr(agg, cur)
                            setattr(agg, cur,
                                    v if prev is None else pick(prev, v))
                add(name, {
                    "type": "histogram", "labels": dict(lk),
                    "count": agg.count, "sum": round(agg.sum, 6),
                    "min": agg.min, "max": agg.max,
                    "buckets": agg.buckets,
                    "p50": quantile_from_buckets(agg.buckets, 0.5),
                    "p95": quantile_from_buckets(agg.buckets, 0.95),
                    "p99": quantile_from_buckets(agg.buckets, 0.99),
                })
        for rep, row in sorted(reps.items()):
            add("fleet_scrape_stale",
                {"type": "gauge", "labels": {"replica": rep},
                 "value": 1.0 if row["stale"] else 0.0})
            if row["age_s"] is not None:
                add("fleet_scrape_age_seconds",
                    {"type": "gauge", "labels": {"replica": rep},
                     "value": row["age_s"]})
        return {"ts": round(now, 3), "series": out, "replicas": reps}

    def series_points(self) -> List[Tuple[str, dict, str, float]]:
        """(name, labels, kind, value) rows for a SeriesStore recorder —
        the scrape tick IS the fleet sampler: per-replica counters and
        gauges as values, merged histograms as `:count` plus
        bucket-estimated quantile keys (the SeriesSampler convention, so
        SLOs written against `name:p99` work unchanged on the fleet
        store)."""
        rows: List[Tuple[str, dict, str, float]] = []
        with self._lock:
            counters = [(k, dict(v)) for k, v in self._counters.items()]
            gauges = [(k, dict(v)) for k, v in self._gauges.items()]
            hist_keys = list(self._hists)
        for (name, lk), per_rep in counters:
            for rep, st in per_rep.items():
                rows.append((name, dict(lk, replica=rep),
                             "counter", st.total))
        for (name, lk), per_rep in gauges:
            for rep, v in per_rep.items():
                rows.append((name, dict(lk, replica=rep), "gauge", v))
        for name, lk in hist_keys:
            doc = self.merged(name, dict(lk))
            if not doc:
                continue
            rows.append((f"{name}:count", dict(lk), "counter",
                         float(doc["count"])))
            for q in ("p50", "p95", "p99"):
                if doc[q] is not None:
                    rows.append((f"{name}:{q}", dict(lk), "quantile",
                                 float(doc[q])))
        return rows


# ------------------------------------------------------------- fleet SLOs

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_fleet_slos() -> list:
    """Fleet-scope objective set, evaluated over the MERGED view (the
    obsplane series store), not any single process. Thresholds
    overridable via DL4J_TPU_FLEET_SLO_* env knobs."""
    from deeplearning4j_tpu.observe.slo import SLO
    e = _env_float
    return [
        SLO("fleet-ttft-p99", series="serving_ttft_ms:p99",
            threshold=e("DL4J_TPU_FLEET_SLO_TTFT_MS", 2000.0),
            description="fleet-merged decode TTFT p99 within bound "
                        "(bucket-merged across every replica)"),
        SLO("fleet-handoff-failures",
            kind="ratio", series="fleet_handoffs_total",
            num=[{"__series__": "fleet_handoff_failures_total"}],
            den=[{}, {"__series__": "fleet_handoff_failures_total"}],
            budget=e("DL4J_TPU_FLEET_SLO_HANDOFF_BUDGET", 0.1),
            description="failed KV handoffs stay inside the budget "
                        "fleet-wide (attempts = handoffs + failures)"),
    ]
