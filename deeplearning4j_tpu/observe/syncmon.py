"""HostSyncMonitor — the dispatch-depth guard as a runtime metric.

`tests/test_perf_guard.py::TestDispatchDepthGuard` proves the fit loop
performs ≤1 host sync per epoch by patching the two device→host
materialization seams (`ArrayImpl.__float__` and `block_until_ready`)
and counting. That technique is too useful to leave test-only: a
listener added in production (a `float(score)` every step) silently
re-serializes the whole dispatch pipeline, and nothing today would say
so. This monitor is the same patch as an OPT-IN runtime instrument:

    with HostSyncMonitor() as mon:
        net.fit(x, y, epochs=3)
    print(mon.syncs)          # total materializations

While installed, `PerformanceListener` reports syncs/step in its
periodic line and the `train_host_syncs_per_step` registry gauge. Opt-in
because the wrapper adds one Python call per materialization AND
monkey-patches a jax internal — not something a library turns on behind
your back. Install/uninstall are refcounted and idempotent; nesting
monitors shares one patch.
"""

from __future__ import annotations

import threading
from typing import Optional

_lock = threading.Lock()
_monitors: list = []          # install order; [-1] is `current_monitor()`
_originals: Optional[tuple] = None


def _static_rules() -> str:
    """graft-lint rules whose violations produce the syncs this monitor
    counts (the runtime → static cross-check; see analysis/rules.py)."""
    try:
        from deeplearning4j_tpu.analysis.rules import runtime_hint
        return runtime_hint("host_sync")
    except Exception:
        return ""


def current_monitor() -> Optional["HostSyncMonitor"]:
    """The innermost installed monitor, or None (the PerformanceListener
    seam: report syncs/step only when someone asked to measure)."""
    with _lock:
        return _monitors[-1] if _monitors else None


def _patch():
    """Install the counting wrappers (called with _lock held, once)."""
    global _originals
    from jax._src import array as _jarray

    orig_float = _jarray.ArrayImpl.__float__
    orig_block = _jarray.ArrayImpl.block_until_ready

    def counting_float(a):
        for m in _monitors:
            m._bump("float")
        return orig_float(a)

    def counting_block(a):
        for m in _monitors:
            m._bump("block")
        return orig_block(a)

    _jarray.ArrayImpl.__float__ = counting_float
    _jarray.ArrayImpl.block_until_ready = counting_block
    _originals = (_jarray.ArrayImpl, orig_float, orig_block)


def _unpatch():
    global _originals
    cls, orig_float, orig_block = _originals
    cls.__float__ = orig_float
    cls.block_until_ready = orig_block
    _originals = None


class HostSyncMonitor:
    """Counts device→host materializations while installed."""

    def __init__(self, metrics=None):
        self._metrics = metrics
        self._count_lock = threading.Lock()
        self.float_syncs = 0
        self.block_syncs = 0
        self._installed = False

    @property
    def syncs(self) -> int:
        with self._count_lock:
            return self.float_syncs + self.block_syncs

    def _bump(self, kind: str) -> None:
        with self._count_lock:
            if kind == "float":
                self.float_syncs += 1
            else:
                self.block_syncs += 1

    def take(self) -> int:
        """Syncs since the last take() — the per-report-window delta the
        PerformanceListener divides by its batch count."""
        with self._count_lock:
            n = self.float_syncs + self.block_syncs
            self.float_syncs = 0
            self.block_syncs = 0
        return n

    def snapshot(self) -> dict:
        """Counters plus the graft-lint rules that flag host-sync
        patterns at review time — when this monitor reports unexpected
        syncs, `static_rules` names what to lint for."""
        with self._count_lock:
            return {
                "float_syncs": self.float_syncs,
                "block_syncs": self.block_syncs,
                "total": self.float_syncs + self.block_syncs,
                "static_rules": _static_rules(),
            }

    # -------------------------------------------------------- lifecycle
    def install(self) -> "HostSyncMonitor":
        with _lock:
            if self._installed:
                return self
            if not _monitors:
                _patch()
            _monitors.append(self)
            self._installed = True
            if self._metrics is None:
                from deeplearning4j_tpu.observe.registry import (
                    get_registry,
                )
                self._metrics = get_registry()
        self._flight_mark("syncmon_installed")
        return self

    def uninstall(self) -> None:
        with _lock:
            if not self._installed:
                return
            self._installed = False
            if self in _monitors:
                _monitors.remove(self)
            if not _monitors and _originals is not None:
                _unpatch()
        self._flight_mark("syncmon_uninstalled")

    def _flight_mark(self, kind: str) -> None:
        """Lifecycle breadcrumb in the crash ring: a dump that shows
        sync counts should also show when counting was on."""
        try:
            from deeplearning4j_tpu.observe.flight import get_flight
            with self._count_lock:
                total = self.float_syncs + self.block_syncs
            get_flight().record(kind, total_syncs=total)
        # graft: allow(GL403): lifecycle breadcrumb must never break
        # monitor install/uninstall
        except Exception:
            pass

    def __enter__(self) -> "HostSyncMonitor":
        return self.install()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.uninstall()
