"""MetricsRegistry — process-wide metric series with bounded memory.

Design constraints (they shape every choice here):

- **Hot-path cheap**: call sites hold an instrument handle
  (`registry.counter("train.iterations")`) and bump it — one short lock
  per update, no allocation proportional to traffic. Percentiles and
  rendering are computed by the READER (`snapshot()` / `to_prometheus()`),
  the way `ServingStats` already priced its `/metrics` endpoint.
- **Bounded**: histograms keep a fixed-size reservoir (`deque(maxlen=N)`)
  plus running count/sum/min/max, so an unbounded request stream cannot
  grow memory.
- **Async-dispatch safe**: instruments accept plain host numbers only.
  Passing a jax device array is the caller's sync, not ours — the
  framework call sites only ever record host-side wall times and counts
  (PERF_NOTES contract).
- **stdlib only**: importable from the dump tool / a metrics consumer
  without pulling in jax.
"""

from __future__ import annotations

import bisect
import json
import math
import re
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

# Fixed bucket ladder shared by every process. Federation (observe/
# fedmon.py) merges replica histograms bucket-wise, which is only sound
# when all processes bucket identically — so the ladder is a module
# constant, never per-instrument. 1/2.5/5 per decade over 0.1..5e5
# (ms-ish dynamic range), plus an implicit +Inf overflow bin.
BUCKET_EDGES: Tuple[float, ...] = tuple(
    round(m * (10.0 ** e), 6)
    for e in range(-1, 6) for m in (1.0, 2.5, 5.0))
# bump when the ladder changes: merging two ladders is meaningless
BUCKET_VERSION = 1

# Prometheus exposition format version implemented by to_prometheus()
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _prom_name(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    esc = lambda v: str(v).replace("\\", r"\\").replace(
        '"', r"\"").replace("\n", r"\n")
    return ("{" + ",".join(
        f'{_prom_name(k)}="{esc(v)}"' for k, v in labels) + "}")


def _prom_value(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if f != int(f) else str(int(f))


class Counter:
    """Monotonic count. `inc(v)` with v >= 0."""

    kind = "counter"
    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render(self) -> dict:
        return {"value": self.value}


class Gauge:
    """Point-in-time value. `set(v)` / `inc()` / `dec()`."""

    kind = "gauge"
    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    def dec(self, v: float = 1.0) -> None:
        self.inc(-v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render(self) -> dict:
        return {"value": self.value}


class Histogram:
    """Distribution with a bounded reservoir.

    Keeps running count/sum/min/max exactly, plus the most recent
    `reservoir` observations for quantiles (a sliding window, which is
    what a latency percentile should be anyway — ancient requests must
    not pin p99 forever)."""

    kind = "histogram"
    __slots__ = ("name", "labels", "_lock", "_reservoir", "count", "sum",
                 "_min", "_max", "_exemplars", "_buckets")

    def __init__(self, name: str, labels, reservoir: int = 4096):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._reservoir: deque = deque(maxlen=max(8, int(reservoir)))
        self.count = 0
        self.sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        # per-bin (non-cumulative) counts over BUCKET_EDGES; the last
        # bin is the +Inf overflow. Exact forever (unlike the sliding
        # reservoir) so cross-process merges are loss-free.
        self._buckets = [0] * (len(BUCKET_EDGES) + 1)
        # OpenMetrics-style exemplars: recent observations that carry a
        # trace id, so a tail percentile can be joined back to the exact
        # request tree in the trace store (GET /trace/{id}).
        self._exemplars: deque = deque(maxlen=8)

    def observe(self, v: float, *, exemplar: Optional[str] = None) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self._reservoir.append(v)
            self._buckets[bisect.bisect_left(BUCKET_EDGES, v)] += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if exemplar is not None:
                self._exemplars.append({"value": v,
                                        "trace_id": str(exemplar),
                                        "ts": round(time.time(), 3)})

    def buckets(self) -> List[int]:
        """Copy of the per-bin counts (len(BUCKET_EDGES) + 1 bins)."""
        with self._lock:
            return list(self._buckets)

    def exemplars(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._exemplars]

    def tail_exemplar(self) -> Optional[dict]:
        """The exemplar with the largest value in the window — the one
        the p99 quantile line links to."""
        exs = self.exemplars()
        return max(exs, key=lambda e: e["value"]) if exs else None

    def values(self) -> List[float]:
        """Copy of the current reservoir (reader-side percentile math)."""
        with self._lock:
            return list(self._reservoir)

    def percentiles(self, qs: Iterable[float] = (0.5, 0.95, 0.99)) -> dict:
        vals = sorted(self.values())
        if not vals:
            return {f"p{int(q * 100)}": None for q in qs}
        n = len(vals)
        return {f"p{int(q * 100)}": vals[min(n - 1, int(q * n))] for q in qs}

    def _render(self) -> dict:
        with self._lock:
            count, total = self.count, self.sum
            lo, hi = self._min, self._max
            window = len(self._reservoir)
            buckets = list(self._buckets)
        out = {"count": count, "sum": total, "min": lo, "max": hi,
               "window": window, "buckets": buckets,
               "bucket_v": BUCKET_VERSION}
        out.update(self.percentiles())
        exs = self.exemplars()
        if exs:
            out["exemplars"] = exs
        return out


class MetricsRegistry:
    """Named, labeled metric series; one per process by default
    (`get_registry()`), private instances for isolation in tests or
    per-server scoping.

    Series identity is (name, sorted label items): asking twice returns
    the SAME instrument, so handles can be cached at call sites and
    shared across threads."""

    def __init__(self, *, reservoir: int = 4096):
        self._lock = threading.Lock()
        self._series: Dict[tuple, object] = {}
        self._reservoir = reservoir
        self.created_at = time.time()

    # ------------------------------------------------------- instruments
    def _get(self, cls, name: str, labels: dict, **kw):
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            inst = self._series.get(key)
            if inst is None:
                inst = self._series[key] = cls(name, key[1], **kw)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"requested {cls.kind}")
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, reservoir: Optional[int] = None,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels,
                         reservoir=reservoir or self._reservoir)

    def series(self) -> List[object]:
        with self._lock:
            return list(self._series.values())

    def reset(self) -> None:
        """Drop every series (test isolation helper)."""
        with self._lock:
            self._series.clear()

    # --------------------------------------------------------- exporters
    def snapshot(self) -> dict:
        """Plain-dict view of every series — the JSON `/metrics` payload
        body and the blob bench.py embeds in BENCH JSON."""
        out: Dict[str, list] = {}
        for inst in self.series():
            out.setdefault(inst.name, []).append({
                "type": inst.kind,
                "labels": dict(inst.labels),
                **inst._render(),
            })
        return {"ts": round(time.time(), 3), "series": out}

    def to_prometheus(self) -> str:
        """Prometheus text exposition (format version 0.0.4).

        Counters/gauges render natively; histograms render as summaries
        (quantiles from the bounded reservoir + exact _count/_sum).
        Histograms that carry exemplars append OpenMetrics-style
        `# {trace_id="..."} value ts` suffixes: the tail (max-value)
        exemplar on the 0.99 quantile line, the latest on _count."""
        by_name: Dict[str, list] = {}
        for inst in self.series():
            by_name.setdefault(inst.name, []).append(inst)
        lines: List[str] = []
        for name in sorted(by_name):
            insts = by_name[name]
            pname = _prom_name(name)
            kind = insts[0].kind
            lines.append(f"# TYPE {pname} "
                         f"{'summary' if kind == 'histogram' else kind}")
            for inst in insts:
                lab = inst.labels
                if inst.kind == "histogram":
                    tail = inst.tail_exemplar()
                    for q in (0.5, 0.95, 0.99):
                        p = inst.percentiles((q,))[f"p{int(q * 100)}"]
                        if p is None:
                            continue
                        qlab = lab + (("quantile", str(q)),)
                        line = f"{pname}{_prom_labels(qlab)} {_prom_value(p)}"
                        if q == 0.99 and tail is not None:
                            line += (f' # {{trace_id="{tail["trace_id"]}"}}'
                                     f' {_prom_value(tail["value"])}'
                                     f' {tail["ts"]}')
                        lines.append(line)
                    lines.append(f"{pname}_sum{_prom_labels(lab)} "
                                 f"{_prom_value(inst.sum)}")
                    count_line = (f"{pname}_count{_prom_labels(lab)} "
                                  f"{_prom_value(inst.count)}")
                    exs = inst.exemplars()
                    if exs:
                        last = exs[-1]
                        count_line += (
                            f' # {{trace_id="{last["trace_id"]}"}}'
                            f' {_prom_value(last["value"])} {last["ts"]}')
                    lines.append(count_line)
                else:
                    lines.append(
                        f"{pname}{_prom_labels(lab)} "
                        f"{_prom_value(inst.value)}")
        return "\n".join(lines) + "\n"

    def to_jsonl(self) -> str:
        """One JSON line per series — appendable to a log the dump tool
        tails."""
        ts = round(time.time(), 3)
        lines = []
        for inst in self.series():
            lines.append(json.dumps({
                "ts": ts, "name": inst.name, "type": inst.kind,
                "labels": dict(inst.labels), **inst._render()}))
        return "\n".join(lines) + ("\n" if lines else "")

    def export_jsonl(self, path: str) -> None:
        with open(path, "a") as f:
            f.write(self.to_jsonl())


# ------------------------------------------------------------ process-wide
_default_registry = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every framework seam records into by
    default. Pass an explicit registry to components that should be
    isolated (tests, one-registry-per-server deployments)."""
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-wide registry; returns the previous one."""
    global _default_registry
    with _default_lock:
        prev, _default_registry = _default_registry, registry
    return prev
