"""Sync-free telemetry time series: bounded history over the registry.

The `MetricsRegistry` answers "what is the value now"; nothing in the
process retained history, so windowed rates ("sheds per second over the
last 5 minutes") and windowed quantiles ("p99 TTFT over the last hour")
— the inputs every SLO decision needs — were uncomputable at runtime.
This module adds exactly that layer and nothing more:

- `SeriesRing` — fixed-capacity ring of (ts, value) pairs for ONE metric
  key, backed by two preallocated `array('d')` buffers: appending a
  sample writes two doubles in place, no allocation, no resize, ever.
- `SeriesStore` — the keyed collection of rings plus the derived views:
  sliding-window deltas/rates for counters and windowed value lists for
  quantile series. Label-aware matching (`match("serving_requests_total",
  outcome="shed")`) so consumers aggregate across models without string
  parsing.
- `SeriesSampler` — a daemon thread that walks the registry's
  instruments every `DL4J_TPU_SERIES_INTERVAL` seconds and appends one
  point per series: counters/gauges record their value; histograms
  record a cumulative `:count` plus derived `:p50/:p95/:p99` keys.

Contract (PERF_NOTES): the sampler reads HOST-side registry state only —
it never touches a jax value, never enters jit, and the per-sample hot
path allocates nothing (ring buffers are preallocated). A perf-gate leg
runs a training fit with the sampler + SLO engine live and pins
0 extra syncs/step and 0 extra compiles. Like the rest of the observe
package, this module imports only the stdlib.
"""

from __future__ import annotations

import os
import threading
import time
from array import array
from typing import Callable, Dict, List, Optional, Tuple

DEFAULT_INTERVAL_S = 1.0
DEFAULT_CAPACITY = 512
_QUANTILES = (0.5, 0.95, 0.99)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def series_key(name: str, labels: Dict[str, str]) -> str:
    """Canonical key: `name{k=v,...}` with sorted labels, bare name when
    unlabeled. Matches the identity the registry uses, so one metric
    series maps to exactly one ring."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class SeriesRing:
    """Fixed-capacity (ts, value) ring for one metric key.

    Two parallel `array('d')` buffers are preallocated at construction;
    `append` overwrites in place and wraps, so the oldest point is
    evicted implicitly and steady-state sampling allocates nothing."""

    __slots__ = ("name", "labels", "kind", "capacity",
                 "_ts", "_vals", "_next", "_count")

    def __init__(self, name: str, labels: Dict[str, str], kind: str,
                 capacity: int = DEFAULT_CAPACITY):
        self.name = name
        self.labels = dict(labels)
        self.kind = kind
        self.capacity = max(2, int(capacity))
        self._ts = array("d", bytes(8 * self.capacity))
        self._vals = array("d", bytes(8 * self.capacity))
        self._next = 0          # write cursor
        self._count = 0         # total points ever appended

    @property
    def key(self) -> str:
        return series_key(self.name, self.labels)

    def __len__(self) -> int:
        return min(self._count, self.capacity)

    def append(self, ts: float, value: float) -> None:
        i = self._next
        self._ts[i] = ts
        self._vals[i] = value
        self._next = (i + 1) % self.capacity
        self._count += 1

    def points(self) -> List[Tuple[float, float]]:
        """Oldest→newest copy of the live window."""
        n = len(self)
        if n == 0:
            return []
        start = (self._next - n) % self.capacity
        ts, vals, cap = self._ts, self._vals, self.capacity
        return [(ts[(start + i) % cap], vals[(start + i) % cap])
                for i in range(n)]

    def window(self, window_s: float,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Points with ts >= now - window_s, oldest→newest."""
        pts = self.points()
        if not pts:
            return []
        cutoff = (now if now is not None else pts[-1][0]) - window_s
        return [p for p in pts if p[0] >= cutoff]

    def last(self) -> Optional[Tuple[float, float]]:
        n = len(self)
        if n == 0:
            return None
        i = (self._next - 1) % self.capacity
        return (self._ts[i], self._vals[i])


class SeriesStore:
    """Keyed collection of rings + the derived windowed views."""

    def __init__(self, *, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(_env_float("DL4J_TPU_SERIES_CAP",
                                      DEFAULT_CAPACITY))
        self.capacity = max(2, int(capacity))
        self._lock = threading.Lock()
        self._rings: Dict[str, SeriesRing] = {}

    # ---------------------------------------------------------- writing
    def ring(self, name: str, labels: Optional[Dict[str, str]] = None,
             kind: str = "gauge") -> SeriesRing:
        """The ring for (name, labels), created on first sight. Call
        sites cache the handle; appends after that are allocation-free."""
        labels = labels or {}
        key = series_key(name, labels)
        with self._lock:
            r = self._rings.get(key)
            if r is None:
                r = self._rings[key] = SeriesRing(
                    name, labels, kind, self.capacity)
            return r

    def record(self, name: str, labels: Optional[Dict[str, str]],
               ts: float, value: float, kind: str = "gauge") -> None:
        self.ring(name, labels, kind).append(ts, value)

    # ---------------------------------------------------------- reading
    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def get(self, key: str) -> Optional[SeriesRing]:
        with self._lock:
            return self._rings.get(key)

    def match(self, name: str, **labels) -> List[SeriesRing]:
        """Rings named `name` whose labels are a superset of `labels` —
        e.g. every model's shed counter via
        `match("serving_requests_total", outcome="shed")`."""
        with self._lock:
            rings = list(self._rings.values())
        out = []
        for r in rings:
            if r.name != name:
                continue
            if all(r.labels.get(k) == str(v) for k, v in labels.items()):
                out.append(r)
        return out

    def delta(self, name: str, window_s: float,
              now: Optional[float] = None, **labels) -> float:
        """Increase of a cumulative counter over the window, summed
        across matching rings (counter resets clamp to 0, never
        negative)."""
        total = 0.0
        for r in self.match(name, **labels):
            pts = r.window(window_s, now)
            if len(pts) >= 2:
                total += max(0.0, pts[-1][1] - pts[0][1])
        return total

    def rate(self, name: str, window_s: float,
             now: Optional[float] = None, **labels) -> float:
        """Per-second sliding-window rate for cumulative counters,
        summed across matching rings. 0.0 until two points exist."""
        best_span = 0.0
        total = 0.0
        for r in self.match(name, **labels):
            pts = r.window(window_s, now)
            if len(pts) >= 2:
                total += max(0.0, pts[-1][1] - pts[0][1])
                best_span = max(best_span, pts[-1][0] - pts[0][0])
        return total / best_span if best_span > 0 else 0.0

    def snapshot(self, window_s: Optional[float] = None,
                 prefix: Optional[str] = None) -> dict:
        """The `GET /series` payload: every ring's live window as
        [[ts, value], ...] pairs (optionally time- and name-filtered)."""
        with self._lock:
            rings = list(self._rings.items())
        now = time.time()
        series = {}
        for key, r in sorted(rings):
            if prefix and not key.startswith(prefix):
                continue
            pts = (r.window(window_s, now) if window_s else r.points())
            if not pts:
                continue
            series[key] = {"kind": r.kind,
                           "points": [[round(t, 3), v] for t, v in pts]}
        return {"ts": round(now, 3), "capacity": self.capacity,
                "series": series}


class SeriesSampler:
    """Background thread appending one point per registry series per
    tick. Host-side only by construction: it reads instrument counters
    and reservoir copies — never a jax value — so sampling can run
    during training/serving without adding a single device sync."""

    def __init__(self, store: SeriesStore, *, registry=None,
                 interval: Optional[float] = None):
        if registry is None:
            from deeplearning4j_tpu.observe.registry import get_registry
            registry = get_registry()
        self.store = store
        self.registry = registry
        self.interval = (interval if interval is not None else
                         _env_float("DL4J_TPU_SERIES_INTERVAL",
                                    DEFAULT_INTERVAL_S))
        self.interval = max(0.01, float(self.interval))
        self.ticks = 0
        self._callbacks: List[Callable[[float], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -------------------------------------------------------- callbacks
    def add_callback(self, fn: Callable[[float], None]) -> None:
        """`fn(now)` runs on the sampler thread after each tick — the
        SLO engine and anomaly watch evaluate here, off every request
        and step path."""
        # graft: allow(GL301): registration happens before start(); the
        # tick loop reads a list() copy
        self._callbacks.append(fn)

    # --------------------------------------------------------- sampling
    def sample_once(self, now: Optional[float] = None) -> int:
        """One synchronous tick (the deterministic seam tests use);
        returns the number of points recorded."""
        now = now if now is not None else time.time()
        wrote = 0
        for inst in self.registry.series():
            labels = dict(inst.labels)
            kind = inst.kind
            if kind in ("counter", "gauge"):
                self.store.record(inst.name, labels, now, inst.value,
                                  kind=kind)
                wrote += 1
            elif kind == "histogram":
                self.store.record(f"{inst.name}:count", labels, now,
                                  inst.count, kind="counter")
                wrote += 1
                pcts = inst.percentiles(_QUANTILES)
                for q in _QUANTILES:
                    p = pcts[f"p{int(q * 100)}"]
                    if p is None:           # never observed: no point
                        continue
                    self.store.record(f"{inst.name}:p{int(q * 100)}",
                                      labels, now, p, kind="quantile")
                    wrote += 1
        # graft: allow(GL301): single writer — ticks only moves on the
        # sampler thread (or the test's synchronous sample_once caller)
        self.ticks += 1
        for fn in list(self._callbacks):
            try:
                fn(now)
            # graft: allow(GL403): a broken evaluator must not kill the
            # sampler thread; the next tick retries it
            except Exception:
                pass
        return wrote

    # -------------------------------------------------------- lifecycle
    def start(self) -> "SeriesSampler":
        """Idempotent: a running sampler is returned as-is."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="series-sampler")
            self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent: stopping a stopped sampler is a no-op."""
        with self._lock:
            t, self._thread = self._thread, None
            self._stop.set()
        if t is not None and t.is_alive():
            t.join(timeout=5)

    @property
    def running(self) -> bool:
        with self._lock:
            t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        # pin the Event once: the reference never changes after
        # __init__, and Event.wait/set are internally synchronized
        with self._lock:
            stop = self._stop
        while not stop.wait(self.interval):
            try:
                self.sample_once()
            # graft: allow(GL403): sampling races registry mutation in
            # pathological teardown orders; drop the tick, keep the
            # thread — telemetry must never take the process down
            except Exception:
                pass
