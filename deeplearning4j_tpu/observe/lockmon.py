"""LockWitness — the runtime cross-check for the GL7xx lockset rules.

analysis/locks.py proves lock discipline statically; this module
witnesses it dynamically. During the chaos / thread-hammer suites an
opt-in instrumented-lock wrapper records, per thread, the order in
which named locks are acquired and whether guarded fields are touched
with their guard held. Two event kinds come out:

- **lock-order inversions** (rule GL702): the witness maintains the
  global acquisition-order graph — an edge A→B each time B is acquired
  while A is held — and reports the first time an edge's reverse is
  also observed. The two orders need not happen concurrently (that
  would be the deadlock itself); seeing both orders at all is the
  hazard.
- **unguarded field accesses** (rule GL701): `witness_field()` checks
  the declared guard is in the calling thread's held set.

Each event carries the graft-lint rule id via RUNTIME_RULE_HINTS —
the same static↔runtime cross-check syncmon provides for GL2xx — and
lock *names* use the static pass's identity scheme (`Class.attr`,
e.g. `KVSlotPool._cv`), so a runtime inversion pair is
string-comparable against a static GL702 finding.

Opt-in via `DL4J_TPU_LOCKMON=1` (or `force=True` in tests): the
wrapper adds a Python call and a small critical section per
acquisition — hammer-suite pricing, not production pricing.

    witness = get_witness(force=True)
    a = MonitoredLock("Pair._a_lock", witness=witness)
    b = MonitoredLock("Pair._b_lock", witness=witness)
    ...
    witness.report()["inversions"]   # [{"locks": [...], "rule": "GL702"}]
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

_ENV_FLAG = "DL4J_TPU_LOCKMON"

_lock = threading.Lock()
_witness: Optional["LockWitness"] = None


def lockmon_enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "") == "1"


def get_witness(*, force: bool = False) -> Optional["LockWitness"]:
    """The process-global witness when lockmon is enabled (env flag or
    `force=True`), else None — callers instrument unconditionally and
    pay nothing when disabled."""
    global _witness
    if not (force or lockmon_enabled()):
        return None
    with _lock:
        if _witness is None:
            _witness = LockWitness()
        return _witness


def reset_witness() -> None:
    global _witness
    with _lock:
        _witness = None


def _static_rules() -> Dict[str, str]:
    try:
        from deeplearning4j_tpu.analysis.rules import runtime_hint
        return {"lock_order": runtime_hint("lock_order"),
                "guarded_field": runtime_hint("guarded_field")}
    except Exception:
        return {}


def _call_site(depth: int = 3) -> str:
    try:
        f = sys._getframe(depth)
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:
        return "?"


class LockWitness:
    """Per-thread acquisition stacks + the global order graph."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tls = threading.local()
        #: (held, acquired) -> {"count", "site", "threads"}
        self.edges: Dict[Tuple[str, str], dict] = {}
        self.inversions: List[dict] = []
        self.unguarded: List[dict] = []
        self.acquisitions = 0
        self._seen_pairs: Set[FrozenSet[str]] = set()
        self._seen_unguarded: Set[Tuple[str, str]] = set()

    # ----------------------------------------------------- thread state
    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held(self) -> Tuple[str, ...]:
        """The calling thread's currently-held named locks, outer-first."""
        return tuple(self._stack())

    # ------------------------------------------------------------ events
    def note_acquire(self, name: str, site: Optional[str] = None) -> None:
        stack = self._stack()
        site = site or _call_site()
        tname = threading.current_thread().name
        with self._lock:
            self.acquisitions += 1
            for h in stack:
                if h == name:
                    continue              # re-entrant RLock hold
                rec = self.edges.setdefault(
                    (h, name), {"count": 0, "site": site, "threads": []})
                rec["count"] += 1
                if tname not in rec["threads"]:
                    rec["threads"].append(tname)
                rev = self.edges.get((name, h))
                pair = frozenset((h, name))
                if rev is not None and pair not in self._seen_pairs:
                    self._seen_pairs.add(pair)
                    self.inversions.append({
                        "rule": "GL702",
                        "locks": sorted(pair),
                        "order_a": {"first": name, "then": h,
                                    "site": rev["site"]},
                        "order_b": {"first": h, "then": name,
                                    "site": site},
                    })
        stack.append(name)

    def note_release(self, name: str) -> None:
        stack = self._stack()
        # innermost matching hold; tolerate out-of-order release
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    def witness_field(self, owner: str, field: str, guard: str,
                      *, write: bool = False) -> None:
        """Record a guarded-field access; an event is emitted when the
        guard is NOT in the calling thread's held set."""
        if guard in self._stack():
            return
        key = (f"{owner}.{field}", guard)
        site = _call_site()
        with self._lock:
            if key in self._seen_unguarded:
                return
            self._seen_unguarded.add(key)
            self.unguarded.append({
                "rule": "GL701",
                "field": f"{owner}.{field}",
                "guard": guard,
                "write": bool(write),
                "site": site,
                "thread": threading.current_thread().name,
            })

    # ------------------------------------------------------------ report
    def report(self) -> dict:
        """Everything the hammer suites assert on, plus the static rule
        ids (the runtime → static cross-check: an inversion here means
        graft-lint GL702 should have flagged the pair at review time)."""
        with self._lock:
            locks = sorted({n for e in self.edges for n in e})
            return {
                "acquisitions": self.acquisitions,
                "locks": locks,
                "edges": [{"held": a, "acquired": b,
                           "count": rec["count"],
                           "threads": list(rec["threads"])}
                          for (a, b), rec in sorted(self.edges.items())],
                "inversions": [dict(ev) for ev in self.inversions],
                "unguarded": [dict(ev) for ev in self.unguarded],
                "static_rules": _static_rules(),
            }


class MonitoredLock:
    """Drop-in `threading.Lock`/`RLock` wrapper that reports every
    acquisition to a LockWitness under the static pass's lock name.

    With no witness (lockmon disabled) it degrades to one attribute
    indirection over the inner lock. `Condition` wait/notify users
    should monitor the *Condition's* underlying lock instead — wrap via
    `threading.Condition(MonitoredLock(...))` only in hammer suites."""

    __slots__ = ("name", "_inner", "_witness")

    def __init__(self, name: str, *, witness: Optional[LockWitness] = None,
                 rlock: bool = False, inner=None):
        self.name = name
        self._inner = inner if inner is not None else (
            threading.RLock() if rlock else threading.Lock())
        self._witness = witness if witness is not None else get_witness()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got and self._witness is not None:
            self._witness.note_acquire(self.name)
        return got

    def release(self) -> None:
        if self._witness is not None:
            self._witness.note_release(self.name)
        self._inner.release()

    def __enter__(self) -> "MonitoredLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if callable(locked) else False
