"""Step-time attribution: etl / dispatch / host / device segments.

`train_step_ms` says a step took 12 ms; it cannot say whether that was
the input pipeline, Python overhead, or the device actually computing —
and under async dispatch the naive fix (time the step call) measures
only the ENQUEUE, because the device runs behind the host on purpose.
Reading the device clock directly would mean forcing a sync, which is
exactly what the deferred-dispatch pipeline forbids (PyGraph's rule for
capture instrumentation, arXiv:2503.19779: near-zero steady-state
overhead or it lies to you).

But the pipeline already owns one guaranteed block point: the
LossTracker materialization at each epoch boundary (the ≤1-sync/epoch
contract). Attribution measures around it:

- per iteration (host clock, no syncs): `etl_ms` (batch wait),
  `dispatch_ms` (the step call — trace/enqueue), `host_ms` (listener
  fan-out + after_step);
- per window (materialize to materialize): `block_ms`, the time
  `float(loss)` actually waited for the device to drain the queue —
  measured at the boundary the tracker already owns.

Device-execute time for the window is then inferred:

    device_total = min(block + dispatch + host, wall - etl)

The device provably ran for `block` ms beyond everything the host did,
plus whatever it overlapped with host work — credited up to the
dispatch+host budget, capped by the wall time outside the input
pipeline. Device-bound runs converge to `wall - etl` (the queue never
drains early); host-bound runs are bounded by dispatch+host (an upper
bound: the device may have idled). Per-step device time is the window
total divided by its step count — published as the `device` segment of
`train_step_attribution_ms`, the `train_device_step_ms` gauge, a
`fit.attribution_window` span, and `last_device_step_ms()` which
PerformanceListener uses as the measured MFU denominator.

Env: DL4J_TPU_ATTRIBUTION=0 disables (the executor then skips all
timing aggregation).

Stdlib-only; one instance per `TrainingExecutor.run`, so instrument
handles bind to the registry active at fit start.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from deeplearning4j_tpu.observe.registry import get_registry
from deeplearning4j_tpu.observe.trace import emit_manual_span

SEGMENTS = ("etl", "dispatch", "host", "device")


def attribution_enabled() -> bool:
    return os.environ.get("DL4J_TPU_ATTRIBUTION", "1") != "0"


class StepAttribution:
    """Per-fit accumulator of step-time segments.

    `record_iteration` is the hot path: three histogram observes + one
    short lock. `on_device_block` runs once per materialization (≤1 per
    epoch steady-state) and closes the inference window.
    """

    def __init__(self, registry=None):
        reg = registry or get_registry()
        self._hist = {seg: reg.histogram("train_step_attribution_ms",
                                         segment=seg)
                      for seg in SEGMENTS}
        self._g_device = reg.gauge("train_device_step_ms")
        self._lock = threading.Lock()
        self.windows = 0
        self._last_device_ms: Optional[float] = None
        self._w_t0 = time.perf_counter()
        self._w_ts = time.time()
        self._steps = 0
        self._etl = self._dispatch = self._host = 0.0

    def _reset_window_locked(self, t0: float, ts: float) -> None:
        # the _locked suffix is the contract: every caller holds self._lock
        self._w_t0 = t0    # graft: allow(GL301): caller holds self._lock
        self._w_ts = ts    # graft: allow(GL301): caller holds self._lock
        self._steps = 0    # graft: allow(GL301): caller holds self._lock
        self._etl = self._dispatch = self._host = 0.0  # graft: allow(GL301): caller holds self._lock

    # ------------------------------------------------------------ hot path
    def record_iteration(self, etl_ms: float, dispatch_ms: float,
                         host_ms: float) -> None:
        with self._lock:
            self._steps += 1
            self._etl += etl_ms
            self._dispatch += dispatch_ms
            self._host += host_ms
        self._hist["etl"].observe(etl_ms)
        self._hist["dispatch"].observe(dispatch_ms)
        self._hist["host"].observe(host_ms)

    # -------------------------------------------------- the block boundary
    def on_device_block(self, block_ms: float) -> None:
        """LossTracker callback: a device loss just materialized after
        blocking for `block_ms`. Closes the attribution window."""
        now = time.perf_counter()
        ts = time.time()
        with self._lock:
            steps = self._steps
            wall = (now - self._w_t0) * 1e3
            etl, disp, host = self._etl, self._dispatch, self._host
            w_ts = self._w_ts
            self._reset_window_locked(now, ts)
        if steps == 0:
            return   # a re-read between windows (score_ accessed twice)
        device_total = min(block_ms + disp + host,
                           max(wall - etl, block_ms))
        per_step = device_total / steps
        with self._lock:
            self.windows += 1
            self._last_device_ms = per_step
        self._hist["device"].observe(per_step)
        self._g_device.set(per_step)
        emit_manual_span("fit.attribution_window", w_ts, ts,
                         steps=steps,
                         etl_ms=round(etl, 3),
                         dispatch_ms=round(disp, 3),
                         host_ms=round(host, 3),
                         block_ms=round(block_ms, 3),
                         device_ms_per_step=round(per_step, 4))

    # ---------------------------------------------------------- reporting
    def last_device_step_ms(self) -> Optional[float]:
        """Most recent window's inferred device time per step (the
        measured MFU denominator); None until a window has closed."""
        with self._lock:
            return self._last_device_ms

    def snapshot(self) -> dict:
        with self._lock:
            return {"windows": self.windows,
                    "last_device_step_ms": self._last_device_ms,
                    "open_window_steps": self._steps}
