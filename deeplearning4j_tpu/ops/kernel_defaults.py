"""Measured-data-driven kernel dispatch policy.

The framework hand-writes TPU kernels in two places (flash attention,
fused LSTM). Whether the hand-written kernel — and which tile
configuration of it — actually beats the XLA baseline is an empirical
question answered by `tools/kernel_bench.py` on real hardware, and the
answer has flipped more than once during development. This module makes
the dispatch *derive from the recorded measurements* instead of from
prose: `tools/update_kernel_defaults.py` regenerates the MEASURED table
below from `tools/kernel_bench_results.json`, and a suite guard
(`tests/test_kernel_defaults.py`) fails if a shipped default contradicts
the best recorded row — a default can never again ship on prose.

This is the same "earn your dispatch with measurements" discipline the
reference applied to its vendor kernels (cuDNN helpers are picked over
built-ins only where they win — `deeplearning4j-cuda/.../
CudnnConvolutionHelper.java:54`), applied to Pallas-vs-XLA.

Policy, in order:
  1. Env escape hatches always win (ops run in production; a lowering
     bug or perf regression must be routable around without a release):
       DL4J_TPU_ATTN           = auto|flash|banded|dense
       DL4J_TPU_ATTN_BACKWARD  = auto|pallas|dense
       DL4J_TPU_ATTN_BLOCK     = "512" or "512x256"   (block_q x block_k)
       DL4J_TPU_DENSE_MAX_T    = int (memory-necessity threshold)
       DL4J_TPU_DECODE_ATTN    = auto|banded|dense   (serving decode step)
       DL4J_TPU_DECODE_LOOP    = auto|fused|stepwise (serving decode loop)
       DL4J_TPU_DECODE_K       = int (fused decode window length; bucketed)
       DL4J_TPU_SPEC_DECODE    = auto|on|off  (draft-model speculative decode)
       DL4J_TPU_DRAFT_K        = int (draft proposal window; bucketed)
       DL4J_TPU_KV_DTYPE       = auto|native|int8|fp8 (KV-cache storage)
       DL4J_TPU_PREFIX_CACHE   = auto|on|off  (paged KV prefix reuse)
       DL4J_TPU_KV_PAGE        = int (KV page length; snapped to divisors)
       DL4J_TPU_FUSED_UPDATE   = auto|fused|xla      (optimizer update)
  2. Shape eligibility: flash needs the TPU backend and 128-lane-tileable
     sequence lengths; otherwise dense.
  3. Memory necessity: when Tq*Tk >= DENSE_MAX_T^2 (default 8192^2) the
     dense [Tq, Tk] score matrix is prohibitive regardless of speed (32
     heads of 8192^2 f32 scores = 8 GiB on a 16 GiB chip — and a
     Tq=4096 x Tk=16384 cross-attention is the same 8 GiB), so flash +
     the Pallas O(T) backward is mandatory.
  4. Otherwise the MEASURED verdict at the nearest benchmarked T decides,
     including the winning block sizes and backward implementation. With
     no winning measured row, the conservative default is the XLA dense
     path (it is the measured winner everywhere rows exist today).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

# --- BEGIN GENERATED (tools/update_kernel_defaults.py) ---
MEASURED: dict = {'attention': {'fwd': {1024: {'backward': 'n/a',
                              'block_k': 128,
                              'block_q': 128,
                              'dense_ms': 0.119,
                              'flash_ms': 0.629,
                              'winner': 'dense'},
                       2048: {'backward': 'n/a',
                              'block_k': 128,
                              'block_q': 128,
                              'dense_ms': 1.148,
                              'flash_ms': 2.302,
                              'winner': 'dense'},
                       4096: {'backward': 'n/a',
                              'block_k': 128,
                              'block_q': 128,
                              'dense_ms': 4.419,
                              'flash_ms': 11.742,
                              'winner': 'dense'}},
               'train': {1024: {'backward': 'dense',
                                'block_k': 128,
                                'block_q': 128,
                                'dense_ms': 0.475,
                                'flash_ms': 1.097,
                                'winner': 'dense'},
                         2048: {'backward': 'dense',
                                'block_k': 128,
                                'block_q': 128,
                                'dense_ms': 3.993,
                                'flash_ms': 5.953,
                                'winner': 'dense'},
                         4096: {'backward': 'dense',
                                'block_k': 128,
                                'block_q': 128,
                                'dense_ms': 14.989,
                                'flash_ms': 23.392,
                                'winner': 'dense'}}},
 'devices': ['TPU v5 lite0'],
 'lstm': {'train': {'fused_ms': 1.697,
                    'scan_ms': 3.991,
                    'winner': 'fused'}}}
# --- END GENERATED ---


class AttentionPolicy(NamedTuple):
    kind: str            # "flash" | "dense"
    block_q: int
    block_k: int
    backward: str        # "pallas" | "dense"
    reason: str          # why this choice (for logs/tests)


class BandedPolicy(NamedTuple):
    kind: str            # "banded" | "dense"
    block_q: int
    block_k: int
    reason: str


def record_dispatch(op: str, impl: str) -> None:
    """Count a dispatch-policy verdict on the shared metrics spine:
    `kernel_dispatch_total{op, impl}`. Policies are consulted at TRACE
    time, so each increment means "one compiled program serves `op` via
    `impl`" — a decode stack showing anything but one banded row per
    bucket, or a counter that keeps growing across steps (shape churn
    re-tracing), is diagnostic, not cosmetic. Visible in `/metrics` and
    in bench snapshots."""
    from deeplearning4j_tpu.observe import get_registry

    get_registry().counter("kernel_dispatch_total", op=op, impl=impl).inc()


def _env(name: str, default: str = "auto") -> str:
    v = os.environ.get(name, default).strip().lower()
    return v or default


def dense_max_t() -> int:
    """Sequence length at which the dense [T, T] path becomes a memory
    hazard and flash is used regardless of measured speed."""
    return int(os.environ.get("DL4J_TPU_DENSE_MAX_T", "8192"))


def _mem_hazard(tq: int, tk: int) -> bool:
    """The dense path materializes [Tq, Tk] scores per head, so the
    hazard scales with the PRODUCT: cross-attention over a long context
    (Tq=4096, Tk=16384) is exactly as dangerous as self-attention at
    sqrt(Tq*Tk). Threshold: product >= DENSE_MAX_T^2."""
    return tq * tk >= dense_max_t() ** 2


def _t_eff(tq: int, tk: int) -> int:
    """Effective length for measured-row lookup: the geometric mean, so
    a [Tq, Tk] problem maps to the self-attention T with the same score
    -matrix area (the measured rows are all self-attention)."""
    import math

    return max(128, int(round(math.sqrt(tq * tk))))


def _nearest_measured(table: dict, t: int) -> Optional[int]:
    """Benchmarked T closest to t in log-space (perf scales ~T^2, so the
    nearest decade is the right generalization)."""
    if not table:
        return None
    import math

    return min(table, key=lambda mt: abs(math.log(mt) - math.log(max(t, 1))))


def _blocks_from_env() -> Optional[tuple]:
    spec = os.environ.get("DL4J_TPU_ATTN_BLOCK", "").strip()
    if not spec:
        return None
    parts = spec.lower().replace("x", ",").split(",")
    bq = int(parts[0])
    bk = int(parts[1]) if len(parts) > 1 else bq
    return bq, bk


def _shape_eligible(tq: int, tk: int, *, min_t: int = 512) -> bool:
    # one canonical predicate for "can flash run here" — ops.attention.
    # min_t=128 is raw kernel capability (memory-necessity path); the
    # default 512 is the perf floor for measured-verdict consults.
    from deeplearning4j_tpu.ops.attention import flash_eligible

    return flash_eligible(tq, tk, min_t=min_t)


def attention_backward(tq: int, tk: Optional[int] = None) -> str:
    """Backward implementation for an already-chosen flash path: "dense"
    (whole-[Tq, Tk] XLA recompute — numerically the oracle, and the
    measured train winner wherever rows exist; ADVICE r4 medium) unless
    a winning measured pallas row or memory necessity says otherwise."""
    tk = tq if tk is None else tk
    forced = _env("DL4J_TPU_ATTN_BACKWARD")
    if forced in ("pallas", "dense"):
        return forced
    if _mem_hazard(tq, tk):
        return "pallas"       # the O(T)-memory backward is the point
    table = MEASURED.get("attention", {}).get("train", {})
    mt = _nearest_measured(table, _t_eff(tq, tk))
    if mt is not None:
        row = table[mt]
        if row["winner"] == "flash" and row.get("backward") == "pallas":
            return "pallas"
    return "dense"


def attention_policy(tq: int, tk: Optional[int] = None,
                     train: bool = False) -> AttentionPolicy:
    """Decide flash-vs-dense (and tile config) for one attention call.

    tq/tk are the query/key sequence lengths; `train` selects which
    measured mode (fwd-only vs fwd+bwd) the verdict comes from.
    """
    tk = tq if tk is None else tk
    t = _t_eff(tq, tk)
    forced = _env("DL4J_TPU_ATTN")
    can_flash = _shape_eligible(tq, tk, min_t=128)   # kernel capability
    blocks = _blocks_from_env()

    def flash(bq, bk, reason):
        if blocks is not None:
            bq, bk = blocks
        record_dispatch("attention", "flash")
        return AttentionPolicy("flash", bq, bk,
                               attention_backward(tq, tk), reason)

    def dense(reason):
        record_dispatch("attention", "dense")
        return AttentionPolicy("dense", 0, 0, "dense", reason)

    if forced == "dense":
        return dense("forced by DL4J_TPU_ATTN=dense")
    if forced == "flash":
        if not can_flash:
            return dense("DL4J_TPU_ATTN=flash but shape ineligible "
                         f"(backend/tiling, tq={tq} tk={tk})")
        return flash(512, 512, "forced by DL4J_TPU_ATTN=flash")
    if not can_flash:
        return dense(f"shape ineligible (tq={tq}, tk={tk})")
    if _mem_hazard(tq, tk):
        # capability floor (128), not the perf floor: a short-query
        # cross-attention over a huge context must still avoid the
        # [Tq, Tk] dense materialization
        row = _best_measured_flash("train" if train else "fwd", t)
        bq, bk = (row["block_q"], row["block_k"]) if row else (512, 512)
        return flash(bq, bk,
                     f"memory necessity: Tq*Tk >= {dense_max_t()}^2")
    if not _shape_eligible(tq, tk):     # perf floor for measured consults
        return dense(f"below flash perf floor (tq={tq}, tk={tk})")
    mode = "train" if train else "fwd"
    table = MEASURED.get("attention", {}).get(mode, {})
    mt = _nearest_measured(table, t)
    if mt is not None and table[mt]["winner"] == "flash":
        row = table[mt]
        return flash(row["block_q"], row["block_k"],
                     f"measured win at T={mt} "
                     f"({row['flash_ms']} vs {row['dense_ms']} ms)")
    if mt is not None:
        row = table[mt]
        return dense(f"measured loss at T={mt} "
                     f"({row.get('flash_ms')} vs {row['dense_ms']} ms)")
    return dense("no measured rows; conservative default")


def _best_measured_flash(mode: str, t: int) -> Optional[dict]:
    """Tile config worth adopting: only a WINNING flash row — a losing
    row's blocks are the measured-worst configuration (128^2 runs 2-5x
    behind dense), exactly what the memory-necessity path must not
    inherit. No winning row -> caller falls back to the 512^2 default."""
    table = MEASURED.get("attention", {}).get(mode, {})
    mt = _nearest_measured(table, t)
    if mt is None:
        return None
    row = table[mt]
    return row if (row.get("block_q") and row["winner"] == "flash") else None


def _best_measured_banded(mode: str, t: int) -> Optional[dict]:
    """Winning banded row's tile config (same rule as
    `_best_measured_flash`: a losing row's blocks are the measured-worst
    configuration and must not be inherited)."""
    table = MEASURED.get("banded", {}).get(mode, {})
    mt = _nearest_measured(table, t)
    if mt is None:
        return None
    row = table[mt]
    return row if (row.get("block_q") and row["winner"] == "banded") \
        else None


def banded_policy(t: int, h: int, hkv: int,
                  train: bool = False) -> BandedPolicy:
    """Banded-vs-dense for one windowed/GQA attention call (the shapes
    `attention_policy` never serves: its flash kernel is full-context).

    Same lattice as `attention_policy`: env force, then shape capability,
    then memory necessity, then the measured verdict, with dense the
    no-data default. Memory necessity applies to the FORWARD-only mode —
    the banded backward recomputes through the dense band-masked
    reference, so routing banded cannot relieve a training-shape [T, T]
    hazard and must not claim to."""
    forced = _env("DL4J_TPU_ATTN")
    blocks = _blocks_from_env()
    from deeplearning4j_tpu.ops.banded_attention import banded_eligible

    can = banded_eligible(t, h, hkv, min_t=128)

    def banded(bq, bk, reason):
        if blocks is not None:
            bq, bk = blocks
        record_dispatch("banded_attention", "banded")
        return BandedPolicy("banded", bq, bk, reason)

    def dense(reason):
        record_dispatch("banded_attention", "dense")
        return BandedPolicy("dense", 0, 0, reason)

    if forced == "dense":
        return dense("forced by DL4J_TPU_ATTN=dense")
    if forced == "flash":
        return dense("DL4J_TPU_ATTN=flash: the full-context flash kernel "
                     "cannot band; windowed shapes stay dense")
    if forced == "banded":
        # Backend is waived: the force must hold off-TPU too (the layer
        # runs the kernel in interpret mode there), or a CPU smoke of a
        # production config would silently exercise a different path.
        if not banded_eligible(t, h, hkv, min_t=128, any_backend=True):
            return dense("DL4J_TPU_ATTN=banded but shape ineligible "
                         f"(tiling, t={t} h={h} hkv={hkv})")
        return banded(256, 256, "forced by DL4J_TPU_ATTN=banded")
    if not can:
        return dense(f"shape ineligible (t={t}, h={h}, hkv={hkv})")
    if not train and _mem_hazard(t, t):
        row = _best_measured_banded("fwd", t)
        bq, bk = (row["block_q"], row["block_k"]) if row else (256, 256)
        return banded(bq, bk,
                      f"memory necessity: T^2 >= {dense_max_t()}^2")
    mode = "train" if train else "fwd"
    table = MEASURED.get("banded", {}).get(mode, {})
    mt = _nearest_measured(table, t)
    if mt is not None and table[mt]["winner"] == "banded":
        row = table[mt]
        return banded(row["block_q"], row["block_k"],
                      f"measured win at T={mt} "
                      f"({row['banded_ms']} vs {row['dense_ms']} ms)")
    if mt is not None:
        row = table[mt]
        return dense(f"measured loss at T={mt} "
                     f"({row.get('banded_ms')} vs {row['dense_ms']} ms)")
    return dense("no measured rows; conservative default")


class DecodePolicy(NamedTuple):
    kind: str            # "banded" | "dense"
    block_l: int
    reason: str


def decode_attention_policy(cache_len: int, h: int, hkv: int,
                            record: bool = True) -> DecodePolicy:
    """Single-query decode-step attention: the Pallas kernel that reads
    the KVSlotPool layout directly vs the layer's dense einsum. Env hatch
    DL4J_TPU_DECODE_ATTN=auto|banded|dense; measured rows live under
    MEASURED["decode"] keyed by cache length. `record=False` is for
    observers (serving snapshots) that ask what WOULD dispatch —
    kernel_dispatch_total must count only real dispatch sites."""
    forced = _env("DL4J_TPU_DECODE_ATTN")
    from deeplearning4j_tpu.ops.banded_attention import decode_eligible

    can = decode_eligible(cache_len, h, hkv)

    def banded(bl, reason):
        if record:
            record_dispatch("decode_attention", "banded")
        return DecodePolicy("banded", bl, reason)

    def dense(reason):
        if record:
            record_dispatch("decode_attention", "dense")
        return DecodePolicy("dense", 0, reason)

    if forced == "dense":
        return dense("forced by DL4J_TPU_DECODE_ATTN=dense")
    if forced == "banded":
        # An explicit force runs even off-TPU (interpret mode): that is
        # the CPU parity/integration seam, and production force-routing
        # must not silently un-force itself.
        return banded(512, "forced by DL4J_TPU_DECODE_ATTN=banded")
    if not can:
        return dense(f"shape ineligible (L={cache_len}, h={h}, "
                     f"hkv={hkv})")
    table = MEASURED.get("decode", {})
    mt = _nearest_measured(table, cache_len)
    if mt is not None and table[mt]["winner"] == "banded":
        row = table[mt]
        return banded(row.get("block_l", 512),
                      f"measured win at L={mt} "
                      f"({row['banded_ms']} vs {row['dense_ms']} ms)")
    if mt is not None:
        row = table[mt]
        return dense(f"measured loss at L={mt} "
                     f"({row.get('banded_ms')} vs {row['dense_ms']} ms)")
    return dense("no measured rows; conservative default")


class DecodeLoopPolicy(NamedTuple):
    kind: str            # "fused" | "stepwise"
    k: int               # window length (1 when stepwise)
    reason: str


# Fused decode windows compile one program per K, so K is snapped to a
# small bucket set exactly like the seq-ctx buckets: session churn and
# per-request budgets never mint new programs (the zero-recompile
# contract the watchdog polices).
DECODE_K_BUCKETS = (1, 2, 4, 8, 16)


def _bucket_k(k: int) -> int:
    for b in DECODE_K_BUCKETS:
        if b >= k:
            return b
    return DECODE_K_BUCKETS[-1]


def decode_loop_policy(k: Optional[int] = None, *, capable: bool = True,
                       record: bool = True) -> DecodeLoopPolicy:
    """Fused-K decode loop (one `lax.scan` dispatch advances every active
    session K tokens, sampling on-device) vs the stepwise one-token-per-
    dispatch loop. Same lattice as the other policies — env force, then
    capability, then the measured verdict — but the no-data default is
    FUSED, not conservative: both sides lower through the identical
    per-step XLA program (no hand-written kernel to mistrust), and the
    K-fold host round-trip amortization is structural, exactly like
    `lstm_policy`'s fused default. `k` is the caller's requested window
    (None = the default bucket); it is snapped to DECODE_K_BUCKETS so
    request churn costs zero compiles. `capable=False` (the model has no
    `session_decode_window`, e.g. a ComputationGraph endpoint) degrades
    to stepwise. `record=False` is for observers (serving snapshots)
    asking what WOULD dispatch."""
    forced = _env("DL4J_TPU_DECODE_LOOP")
    env_k = os.environ.get("DL4J_TPU_DECODE_K", "").strip()
    if env_k:
        k = int(env_k)
    want_k = _bucket_k(8 if k is None else max(1, int(k)))

    def fused(kk, reason):
        if record:
            record_dispatch("decode_loop", "fused")
        return DecodeLoopPolicy("fused", kk, reason)

    def stepwise(reason):
        if record:
            record_dispatch("decode_loop", "stepwise")
        return DecodeLoopPolicy("stepwise", 1, reason)

    if forced == "stepwise":
        return stepwise("forced by DL4J_TPU_DECODE_LOOP=stepwise")
    if forced == "fused":
        if not capable:
            return stepwise("DL4J_TPU_DECODE_LOOP=fused but the model "
                            "has no session_decode_window")
        return fused(want_k, "forced by DL4J_TPU_DECODE_LOOP=fused")
    if not capable:
        return stepwise("model has no session_decode_window")
    row = MEASURED.get("decode_loop")
    if row is not None:
        mt = _nearest_measured(row, want_k)
        if mt is not None and row[mt]["winner"] == "stepwise":
            return stepwise(f"measured loss at K={mt} "
                            f"({row[mt]['fused_ms']} vs "
                            f"{row[mt]['stepwise_ms']} ms)")
        if mt is not None:
            return fused(want_k, f"measured win at K={mt} "
                         f"({row[mt]['fused_ms']} vs "
                         f"{row[mt]['stepwise_ms']} ms)")
    return fused(want_k, "structural default: identical per-step XLA "
                 "program, K-fold fewer host round-trips")


class SpecDecodePolicy(NamedTuple):
    kind: str            # "spec" | "plain"
    k: int               # draft window length (0 when plain)
    reason: str


def spec_decode_policy(k: Optional[int] = None, *, capable: bool = True,
                       record: bool = True) -> SpecDecodePolicy:
    """Draft-model speculative decoding (draft proposes D tokens per
    lane, the target verifies all D in ONE chunk dispatch, accept/reject
    on device) vs the plain fused window. Same lattice as
    `decode_loop_policy` — env force, then capability, then the measured
    verdict. The no-data default is SPEC when a draft is wired up:
    verification lowers through the same chunked forward the prefill
    path already runs, and replacing D sequential target steps with one
    chunk is structural. `capable=False` means no draft model is
    registered, or either net cannot rewind its caches (recurrent
    carries / rolling rings hold state that cannot be un-written after
    a rejection) — degrades to plain. `k` is the requested draft window
    (None = default bucket), snapped to DECODE_K_BUCKETS so draft-length
    churn costs zero compiles."""
    forced = _env("DL4J_TPU_SPEC_DECODE")
    env_k = os.environ.get("DL4J_TPU_DRAFT_K", "").strip()
    if env_k:
        k = int(env_k)
    want_k = _bucket_k(8 if k is None else max(1, int(k)))

    def spec(kk, reason):
        if record:
            record_dispatch("spec_decode", "spec")
        return SpecDecodePolicy("spec", kk, reason)

    def plain(reason):
        if record:
            record_dispatch("spec_decode", "plain")
        return SpecDecodePolicy("plain", 0, reason)

    if forced == "off":
        return plain("forced by DL4J_TPU_SPEC_DECODE=off")
    if forced == "on":
        if not capable:
            return plain("DL4J_TPU_SPEC_DECODE=on but no rewindable "
                         "draft/target pair (draft missing, recurrent "
                         "carries, or rolling KV rings)")
        return spec(want_k, "forced by DL4J_TPU_SPEC_DECODE=on")
    if not capable:
        return plain("no rewindable draft/target pair (draft missing, "
                     "recurrent carries, or rolling KV rings)")
    row = MEASURED.get("spec_decode")
    if row is not None:
        mt = _nearest_measured(row, want_k)
        if mt is not None and row[mt]["winner"] == "plain":
            return plain(f"measured loss at D={mt} "
                         f"({row[mt]['spec_ms']} vs "
                         f"{row[mt]['plain_ms']} ms)")
        if mt is not None:
            return spec(want_k, f"measured win at D={mt} "
                        f"({row[mt]['spec_ms']} vs "
                        f"{row[mt]['plain_ms']} ms)")
    return spec(want_k, "structural default: one chunk verify replaces "
                "D sequential target dispatches")


class KVDtypePolicy(NamedTuple):
    kind: str            # "native" | "int8" | "fp8"
    reason: str


def _fp8_capable() -> bool:
    """fp8 KV storage needs the e4m3 dtype AND a backend whose cast
    lowering is trusted; off-TPU the int8 path is the portable one."""
    import jax
    import jax.numpy as jnp

    return hasattr(jnp, "float8_e4m3fn") and jax.default_backend() == "tpu"


def kv_dtype_policy(kind: Optional[str] = None, *,
                    record: bool = True) -> KVDtypePolicy:
    """Storage dtype for the KVSlotPool's attention caches: "native"
    (the model dtype), "int8" (per-(token, kv-head) scale rows,
    quantize-on-write / dequantize-on-read fused into the banded decode
    kernel's block loads and the dense fallback), or "fp8" (e4m3, same
    scale rows, capable backends only). Env hatch DL4J_TPU_KV_DTYPE
    always wins; `kind` is the caller's request (server knob); the
    no-data default is NATIVE — quantization trades ulps for slots, and
    that trade is opted into per deployment, not defaulted. A MEASURED
    ["kv_dtype"] verdict (from the autotune sweep) can flip the auto
    default once rows exist."""
    forced = _env("DL4J_TPU_KV_DTYPE")
    want = forced if forced != "auto" else (kind or "").strip().lower()
    if want not in ("", "auto", "native", "int8", "fp8"):
        # an explicit-but-unknown request must fail the deploy, not
        # silently serve unquantized
        raise ValueError(f"unknown kv_dtype {want!r} "
                         "(expected native|int8|fp8)")

    def verdict(kd, reason):
        if record:
            record_dispatch("kv_dtype", kd)
        return KVDtypePolicy(kd, reason)

    if want in ("native", "int8"):
        src = "DL4J_TPU_KV_DTYPE" if forced != "auto" else "caller"
        return verdict(want, f"forced by {src}={want}")
    if want == "fp8":
        if not _fp8_capable():
            src = "DL4J_TPU_KV_DTYPE" if forced != "auto" else "caller"
            return verdict("int8", f"{src}=fp8 but backend lacks e4m3 "
                           "support; int8 carries the same scale rows")
        src = "DL4J_TPU_KV_DTYPE" if forced != "auto" else "caller"
        return verdict("fp8", f"forced by {src}=fp8")
    row = MEASURED.get("kv_dtype")
    if row is not None and row.get("winner") in ("int8", "fp8"):
        kd = row["winner"]
        if kd == "fp8" and not _fp8_capable():
            kd = "int8"
        return verdict(kd, f"measured win ({row})")
    return verdict("native", "no measured rows; quantization is "
                   "opt-in per deployment")


class PrefixCachePolicy(NamedTuple):
    kind: str            # "paged" | "off"
    page_len: int        # KV page length in tokens (0 when off)
    reason: str


def prefix_cache_policy(page_len: Optional[int] = None, *,
                        max_cache: Optional[int] = None,
                        capable: bool = True,
                        record: bool = True) -> PrefixCachePolicy:
    """Paged KV storage + radix prefix cache vs monolithic per-slot
    caches. Same lattice as the other policies — env force, then
    capability — but like `decode_loop_policy` the no-data default is
    ON when the model is capable: a warm prefix replaces its whole
    prefill with admission-time page-table writes, and that bookkeeping
    costs the steady-state window nothing (page indices are traced
    scalars, one compiled program either way), so there is no measured
    trade to wait on. `capable=False` (recurrent carries, rolling KV
    rings, non-uniform max_cache, or an active draft model whose own
    cache cannot skip the prefill) degrades to off. The page length
    (DL4J_TPU_KV_PAGE, or `page_len`, default 128 — the TPU lane tile,
    so the banded paged kernel stays eligible) is snapped down to the
    largest divisor of `max_cache` so a slot's table tiles exactly."""
    forced = _env("DL4J_TPU_PREFIX_CACHE")
    env_p = os.environ.get("DL4J_TPU_KV_PAGE", "").strip()
    if env_p:
        page_len = int(env_p)
    want = max(1, int(page_len)) if page_len else 128
    if max_cache:
        mc = int(max_cache)
        want = min(want, mc)
        while mc % want:
            want -= 1

    def paged(reason):
        if record:
            record_dispatch("prefix_cache", "paged")
        return PrefixCachePolicy("paged", want, reason)

    def off(reason):
        if record:
            record_dispatch("prefix_cache", "off")
        return PrefixCachePolicy("off", 0, reason)

    if forced == "off":
        return off("forced by DL4J_TPU_PREFIX_CACHE=off")
    if forced == "on":
        if not capable:
            return off("DL4J_TPU_PREFIX_CACHE=on but the model cannot "
                       "page its KV (recurrent carries, rolling rings, "
                       "non-uniform max_cache, or active draft model)")
        return paged("forced by DL4J_TPU_PREFIX_CACHE=on")
    if not capable:
        return off("model cannot page its KV (recurrent carries, "
                   "rolling rings, non-uniform max_cache, or active "
                   "draft model)")
    return paged("structural default: a warm prefix replaces its whole "
                 "prefill; admission-time bookkeeping costs the "
                 "steady-state window nothing")


def fused_update_policy(kind: str) -> str:
    """"fused" (one-pass Pallas read-modify-write) or "xla" for the
    optimizer update of `kind` ("adam" | "nesterov"). Env hatch
    DL4J_TPU_FUSED_UPDATE forces either way (force runs off-TPU via
    interpret mode — the CPU integration seam); otherwise a winning
    MEASURED["fused_update"][kind] row is required, with the XLA path
    the conservative no-data default."""
    forced = _env("DL4J_TPU_FUSED_UPDATE")
    op = f"{kind}_update"
    if forced == "xla":
        record_dispatch(op, "xla")
        return "xla"
    if forced == "fused":
        record_dispatch(op, "fused")
        return "fused"
    from deeplearning4j_tpu.ops.fused_update import fused_update_available

    row = MEASURED.get("fused_update", {}).get(kind)
    if (fused_update_available() and row is not None
            and row["winner"] == "fused"):
        record_dispatch(op, "fused")
        return "fused"
    record_dispatch(op, "xla")
    return "xla"


def lstm_policy(train: bool = True) -> str:
    """"fused" (Pallas) or "scan" (lax.scan baseline) for the LSTM core.

    The fused kernel exists precisely because the recurrence carry is a
    fusion XLA cannot do across scan steps; the measured train win is
    2.35x (tools/kernel_bench_results.json: lstm_train_fused). An
    unmeasured mode falls back to the other mode's verdict (documented:
    both run the identical kernel; only the cotangent pass differs).
    """
    forced = _env("DL4J_TPU_LSTM")
    if forced in ("fused", "scan"):
        record_dispatch("lstm", forced)
        return forced
    table = MEASURED.get("lstm", {})
    mode = "train" if train else "fwd"
    row = table.get(mode) or table.get("fwd" if train else "train")
    verdict = "fused"   # no data at all: structural argument above
    if row is not None:
        verdict = "fused" if row["winner"] == "fused" else "scan"
    record_dispatch("lstm", verdict)
    return verdict
