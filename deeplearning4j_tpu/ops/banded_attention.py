"""Banded flash attention (Pallas TPU): sliding-window + GQA + ring decode.

The flash kernel in `ops/attention.py` is a full-context kernel: its grid
sweeps every K block for every Q block, so a sliding-window layer gains
nothing from it and `nn/layers/attention.py` historically forced window/
GQA/ring shapes onto the O(T²) dense band-masked path — the exact shapes
the decode serving stack runs on. This module closes that gap with one
kernel family:

* `banded_attention` — full-sequence forward whose GRID is banded: for
  each Q block only the `nkb` K blocks that can intersect the band are
  visited (`nkb` is a constant in T, derived from window/block sizes), so
  compile-time FLOPs scale with T·w, not T². GQA is native: the K/V tiles
  stay Hkv-wide while the query tile carries the whole `G = H/Hkv` group
  (`[1, G, Bq, Dh]` folded to `(G·Bq, Dh)` rows against one `[Bk, Dh]`
  KV tile), so KV HBM traffic really is Hkv/H of MHA — the cache is never
  broadcast to H heads the way the layer's dense GQA path must.
* `banded_decode_attention` — the single-query serving variant. It reads
  the `KVSlotPool` carry layout `[S, L, Hkv, Dh]` directly and evaluates
  the rolling-ring held-index arithmetic (`held = end - ((end - j) % L)`,
  see `nn/layers/attention.py` scalar-ring branch) inside the kernel from
  scalar-prefetched per-slot positions, so one compiled program serves
  every session position — the zero-recompile decode contract holds.

Both kernels run under `interpret=True` on CPU (the parity suite in
tests/test_banded_attention.py pins them against the layer's dense
band-masked oracle). Backward: banded training shapes recompute through
the dense band-masked reference (`banded_reference`) — the O(T²) scores
exist transiently on the backward only; a blockwise Pallas backward is
future work that `tools/roofline_report.py` exists to prioritize.

Dispatch is NOT decided here: `kernel_defaults.banded_policy` owns the
banded-vs-dense verdict under the measured-winner discipline (env hatch
`DL4J_TPU_ATTN=banded` forces it; new MEASURED rows come from
`tools/kernel_bench.py --banded` on hardware).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.attention import _CompilerParams

_NEG_INF = -1e30


# --------------------------------------------------------------- reference
def banded_reference(q, k, v, window: int, causal: bool, scale: float):
    """Dense band-masked oracle over native GQA layouts: q [B, T, H, Dh],
    k/v [B, T, Hkv, Dh]. Numerically the layer's `_masked_attention` band
    path (score-level -1e30 bias, f32 softmax); also the recompute
    backward for `banded_attention`."""
    b, t, h, dh = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, t, hkv, g, dh)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k) * scale
    qi = jnp.arange(t)[:, None]
    ki = jnp.arange(t)[None, :]
    if causal:
        vis = (ki <= qi) & (ki > qi - window)
    else:
        vis = jnp.abs(qi - ki) < window
    s = jnp.where(vis[None, None, None], s, _NEG_INF)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", jax.nn.softmax(s, axis=-1), v)
    return o.reshape(b, t, h, dh)


def _fit_block(block: int, t: int, *, interpret: bool) -> int:
    """Largest block <= requested that divides t. On TPU blocks walk down
    in 128-lane steps (Mosaic tiling); in interpret mode any divisor is
    legal, which is what lets the parity suite cover odd T/w shapes."""
    block = max(1, min(block, t))
    if interpret:
        while t % block:
            block -= 1
        return block
    while block > 128 and t % block:
        block -= 128
    if t % block:
        raise ValueError(f"seq len {t} not divisible by any block <= "
                         f"{block} (need a multiple of 128)")
    return block


def _band_geometry(t: int, window: int, causal: bool, block_q: int,
                   block_k: int):
    """Static band geometry: `nkb`, the number of K blocks any single Q
    block can intersect, is a function of window/block sizes ONLY — this
    is the T·w contract, enforced by making the grid's K extent `nkb`
    instead of `T // block_k`."""
    nk = t // block_k
    span = block_q + window - 1 + (0 if causal else window - 1)
    nkb = min(nk, (span + block_k - 1) // block_k + 1)
    return nk, nkb


def _kb_first(i, *, nk: int, nkb: int, block_q: int, block_k: int,
              window: int, causal: bool):
    """First K block visited for Q block `i` (shared by the BlockSpec
    index_map and the in-kernel mask arithmetic, so they can never
    disagree). The last needed block is `ub` = the block holding the
    band's rightmost visible key for the block's last row; the window of
    `nkb` blocks ending there always covers the leftmost too (nkb bounds
    the intersection count by construction)."""
    hi = (i + 1) * block_q - 1 + (0 if causal else window - 1)
    ub = jnp.minimum(hi // block_k, nk - 1)
    return jnp.clip(ub - (nkb - 1), 0, nk - nkb)


def _banded_kernel(q_ref, k_ref, v_ref, o_ref, acc_scr, m_scr, l_scr, *,
                   nk: int, window: int, causal: bool, scale: float):
    """Grid = (batch·Hkv, Q blocks, band K blocks). Per Q block only the
    `nkb` K blocks the band can touch are visited; the online-softmax
    state rides VMEM scratch across that innermost sweep exactly as in
    `ops/attention._flash_kernel`. The query tile is the whole GQA group
    ([G, Bq, Dh] folded to G·Bq rows) against one Hkv-wide KV tile."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    nkb = pl.num_programs(2)
    q = q_ref[0]                                   # [G, Bq, Dh]
    g, bq, d = q.shape
    block_k = k_ref.shape[1]
    kb = _kb_first(i, nk=nk, nkb=nkb, block_q=bq, block_k=block_k,
                   window=window, causal=causal) + j

    @pl.when(j == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # A clamped band (first/last rows of the sequence) can hand this step
    # a K block fully outside the visible interval — skip its FLOPs.
    lo = i * bq - window + 1
    hi = (i + 1) * bq - 1 + (0 if causal else window - 1)
    relevant = (kb * block_k <= hi) & (kb * block_k + block_k - 1 >= lo)

    @pl.when(relevant)
    def _():
        k = k_ref[0]                               # [Bk, Dh]
        v = v_ref[0]
        prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
                else jax.lax.Precision.DEFAULT)
        qf = q.reshape(g * bq, d)
        s = jnp.dot(qf, k.T, preferred_element_type=jnp.float32,
                    precision=prec) * scale        # [G·Bq, Bk]
        rows = jax.lax.broadcasted_iota(jnp.int32, (g * bq, block_k), 0)
        q_ids = i * bq + rows % bq                 # row r of group g -> q
        k_ids = (kb * block_k
                 + jax.lax.broadcasted_iota(jnp.int32, (g * bq, block_k), 1))
        if causal:
            vis = (k_ids <= q_ids) & (k_ids > q_ids - window)
        else:
            vis = (k_ids < q_ids + window) & (k_ids > q_ids - window)
        s = jnp.where(vis, s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # Explicit zeroing, not just the -1e30 bias: a row whose visible
        # band hasn't started yet has m_new == -1e30, where exp(s - m)
        # would be exp(0) = 1 for every masked entry — fake weight the
        # full-context kernel never sees (its first block is never fully
        # dead for a live row; a banded grid's can be).
        p = jnp.where(vis, jnp.exp(s - m_new), 0.0)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32,
            precision=prec)

    @pl.when(j == nkb - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).reshape(g, bq, d).astype(o_ref.dtype)


def _run_banded(q, k, v, *, window: int, causal: bool, scale: float,
                block_q: int, block_k: int, interpret: bool):
    b, t, h, dh = q.shape
    hkv = k.shape[2]
    g = h // hkv
    block_q = _fit_block(block_q, t, interpret=interpret)
    block_k = _fit_block(block_k, t, interpret=interpret)
    nk, nkb = _band_geometry(t, window, causal, block_q, block_k)
    # [B, T, H, Dh] -> [B·Hkv, G, T, Dh]; heads group as h = hkv·G + g,
    # matching the layer's `q.reshape(B, T, Hkv, G, Dh)` GQA grouping.
    q5 = q.transpose(0, 2, 1, 3).reshape(b, hkv, g, t, dh) \
        .reshape(b * hkv, g, t, dh)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * hkv, t, dh)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * hkv, t, dh)
    kmap = functools.partial(_kb_first, nk=nk, nkb=nkb, block_q=block_q,
                             block_k=block_k, window=window, causal=causal)
    o = pl.pallas_call(
        functools.partial(_banded_kernel, nk=nk, window=window,
                          causal=causal, scale=scale),
        grid=(b * hkv, t // block_q, nkb),
        in_specs=[
            pl.BlockSpec((1, g, block_q, dh), lambda bb, i, j: (bb, 0, i, 0)),
            pl.BlockSpec((1, block_k, dh),
                         lambda bb, i, j: (bb, kmap(i) + j, 0)),
            pl.BlockSpec((1, block_k, dh),
                         lambda bb, i, j: (bb, kmap(i) + j, 0)),
        ],
        out_specs=pl.BlockSpec((1, g, block_q, dh),
                               lambda bb, i, j: (bb, 0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hkv, g, t, dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((g * block_q, dh), jnp.float32),
            pltpu.VMEM((g * block_q, 1), jnp.float32),
            pltpu.VMEM((g * block_q, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q5, k3, v3)
    return o.reshape(b, hkv, g, t, dh).transpose(0, 3, 1, 2, 4) \
        .reshape(b, t, h, dh)


def banded_eligible(t: int, h: int, hkv: int, *, min_t: int = 256,
                    any_backend: bool = False) -> bool:
    """SHAPE eligibility for the full-sequence banded kernel: TPU backend,
    128-lane-tileable T, and a clean GQA grouping. `min_t` is the perf
    floor (below it the band is most of the matrix and dense wins on
    launch overhead); the measured verdict lives in
    `kernel_defaults.banded_policy`. `any_backend=True` waives the TPU
    requirement (env-forced routing runs interpret-mode off-TPU — a
    production force must not silently un-force itself)."""
    return ((any_backend or jax.default_backend() == "tpu")
            and t % 128 == 0
            and t >= min_t and hkv >= 1 and h % hkv == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def banded_attention(q, k, v, window: int, causal: bool = True,
                     scale: Optional[float] = None, block_q: int = 256,
                     block_k: int = 256, interpret: bool = False):
    """Banded (sliding-window) self-attention, GQA-native.

    q: [B, T, H, Dh]; k/v: [B, T, Hkv, Dh] with Hkv dividing H (Hkv == H
    is plain MHA). Causal visibility is `q - window < k <= q`;
    bidirectional is `|q - k| < window` — exactly the layer's dense band
    semantics. Forward is O(T·w) compute/HBM by grid construction;
    backward recomputes through the dense band-masked reference (scores
    exist transiently on the backward only)."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    return _run_banded(q, k, v, window=window, causal=causal, scale=s,
                       block_q=block_q, block_k=block_k,
                       interpret=interpret)


def _banded_fwd(q, k, v, window, causal, scale, block_q, block_k,
                interpret):
    out = banded_attention(q, k, v, window, causal, scale, block_q,
                           block_k, interpret)
    return out, (q, k, v)


def _banded_bwd(window, causal, scale, block_q, block_k, interpret, res,
                do):
    q, k, v = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    _, vjp = jax.vjp(
        lambda qq, kk, vv: banded_reference(qq, kk, vv, window, causal, s),
        q, k, v)
    return vjp(do)


banded_attention.defvjp(_banded_fwd, _banded_bwd)


# ------------------------------------------------------- decode (serving)
def decode_reference(q, cache_k, cache_v, qpos, end, window: Optional[int],
                     rolling: bool, scale: float, scale_k=None,
                     scale_v=None):
    """Dense oracle for the single-query decode kernel, mirroring the
    layer's per-slot `_decode` visibility arithmetic over the pool layout
    (q [S, H, Dh], caches [S, L, Hkv, Dh], qpos/end [S] int32). Rows with
    an empty visible set are garbage-by-contract on BOTH paths (softmax
    of a constant here, zeros in the kernel) — inactive lanes, never
    read back. Quantized caches pass their [S, L, Hkv] scale rows and are
    dequantized up front (the kernel fuses the same product into its
    block loads)."""
    if scale_k is not None:
        cache_k = cache_k.astype(q.dtype) * scale_k.astype(q.dtype)[..., None]
        cache_v = cache_v.astype(q.dtype) * scale_v.astype(q.dtype)[..., None]
    s_, h, dh = q.shape
    l = cache_k.shape[1]
    hkv = cache_k.shape[2]
    g = h // hkv
    j = jnp.arange(l)[None, :]                               # [1, L]
    qp = qpos[:, None]
    if rolling:
        held = end[:, None] - ((end[:, None] - j) % l)       # [S, L]
        vis = (held >= 0) & (held <= qp) & (held > qp - window)
    else:
        vis = j <= qp
        if window is not None:
            vis = vis & (j > qp - window)
    qg = q.reshape(s_, hkv, g, dh)
    sc = jnp.einsum("shgd,slhd->shgl", qg, cache_k) * scale
    sc = jnp.where(vis[:, None, None], sc, _NEG_INF)
    o = jnp.einsum("shgl,slhd->shgd", jax.nn.softmax(sc, axis=-1), cache_v)
    return o.reshape(s_, h, dh)


def _decode_kernel(qpos_ref, end_ref, *refs, cache_len: int,
                   window: Optional[int], rolling: bool, hkv: int,
                   scale: float, quant: bool = False):
    """Grid = (slots, L blocks): one slot's [L, Hkv, Dh] cache rows sweep
    through VMEM while the single-token query group stays resident. The
    per-slot positions arrive scalar-prefetched (SMEM) so visibility is
    computed from traced scalars — one compiled program for every session
    position, which is what keeps the decode zero-recompile contract.

    `quant=True` adds two [1, Bl, Hkv] scale-row refs after the caches:
    the per-(token, kv-head) dequantization product happens on the VMEM
    block right after the load, so quantized KV pays the narrow HBM sweep
    and never materializes a full-width cache."""
    if quant:
        (q_ref, k_ref, v_ref, sk_ref, sv_ref, o_ref,
         acc_scr, m_scr, l_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_scr, m_scr, l_scr = refs
        sk_ref = sv_ref = None
    si = pl.program_id(0)
    lb = pl.program_id(1)
    nlb = pl.num_programs(1)
    q = q_ref[0]                                   # [H, Dh]
    h, d = q.shape
    block_l = k_ref.shape[1]
    g = h // hkv
    pos = qpos_ref[si]
    end = end_ref[si]

    @pl.when(lb == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    if rolling:
        # Every ring slot can hold a live position (the ring IS the band
        # wrapped onto L slots) — no block is statically or dynamically
        # dead, so there is nothing to skip.
        relevant = lb >= 0
    else:
        # Linear cache: only blocks intersecting [pos-w+1, pos] live.
        relevant = lb * block_l <= pos
        if window is not None:
            relevant &= lb * block_l + block_l - 1 > pos - window

    @pl.when(relevant)
    def _():
        kc = k_ref[0]                              # [Bl, Hkv, Dh]
        vc = v_ref[0]
        if quant:
            # fused dequantize-on-load: widen the narrow block in VMEM
            kc = kc.astype(jnp.float32) * sk_ref[0][:, :, None]
            vc = vc.astype(jnp.float32) * sv_ref[0][:, :, None]
        prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
                else jax.lax.Precision.DEFAULT)
        # GQA: each Hkv tile scores its G-row query group; Hkv is a
        # static python loop (tiny: 1-16), so the kernel stays one fused
        # program with no H-wide KV broadcast.
        s = jnp.concatenate([
            jnp.dot(q[hk * g:(hk + 1) * g], kc[:, hk, :].T,
                    preferred_element_type=jnp.float32,
                    precision=prec)
            for hk in range(hkv)], axis=0) * scale  # [H, Bl]
        j = (lb * block_l
             + jax.lax.broadcasted_iota(jnp.int32, (h, block_l), 1))
        if rolling:
            held = end - ((end - j) % cache_len)
            vis = (held >= 0) & (held <= pos) & (held > pos - window)
        else:
            vis = j <= pos
            if window is not None:
                vis = vis & (j > pos - window)
        s = jnp.where(vis, s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(vis, jnp.exp(s - m_new), 0.0)   # dead-block guard
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.concatenate([
            jnp.dot(p[hk * g:(hk + 1) * g].astype(vc.dtype), vc[:, hk, :],
                    preferred_element_type=jnp.float32, precision=prec)
            for hk in range(hkv)], axis=0)            # [H, Dh]
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(lb == nlb - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def decode_eligible(cache_len: int, h: int, hkv: int) -> bool:
    """Shape eligibility for the decode kernel on hardware: TPU backend,
    lane-tileable ring length, clean GQA grouping."""
    return (jax.default_backend() == "tpu" and cache_len % 128 == 0
            and hkv >= 1 and h % hkv == 0)


def banded_decode_attention(q, cache_k, cache_v, qpos, end,
                            window: Optional[int] = None,
                            rolling: bool = False,
                            scale: Optional[float] = None,
                            block_l: int = 512,
                            interpret: bool = False,
                            scale_k=None, scale_v=None):
    """Single-query attention over the KVSlotPool layout.

    q: [S, H, Dh] (this step's query token per slot, post-RoPE);
    cache_k/cache_v: [S, L, Hkv, Dh] (post-write: this step's K/V already
    scattered in); qpos: [S] int32 global position of each slot's query;
    end: [S] int32 newest written global position per slot (rolling ring
    only; ignored otherwise — pass qpos). Returns [S, H, Dh].

    Visibility matches the layer's per-slot `_decode`: rolling recovers
    each ring slot's current occupant arithmetically
    (`held = end - ((end - j) % L)`, visible iff `0 <= held <= qpos` and
    `held > qpos - window`); linear caches see `j <= qpos` minus anything
    beyond the window. Inference-only (no vjp): the decode path never
    differentiates."""
    s_, h, dh = q.shape
    cache_len = cache_k.shape[1]
    hkv = cache_k.shape[2]
    if h % hkv:
        raise ValueError(f"H {h} not divisible by Hkv {hkv}")
    if rolling and window is None:
        raise ValueError("rolling decode requires a window")
    sc = scale if scale is not None else dh ** -0.5
    quant = scale_k is not None
    block_l = _fit_block(block_l, cache_len, interpret=interpret)
    qpos = qpos.astype(jnp.int32)
    end = end.astype(jnp.int32)
    in_specs = [
        pl.BlockSpec((1, h, dh), lambda si, lb, *refs: (si, 0, 0)),
        pl.BlockSpec((1, block_l, hkv, dh),
                     lambda si, lb, *refs: (si, lb, 0, 0)),
        pl.BlockSpec((1, block_l, hkv, dh),
                     lambda si, lb, *refs: (si, lb, 0, 0)),
    ]
    inputs = [q, cache_k, cache_v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, block_l, hkv),
                         lambda si, lb, *refs: (si, lb, 0)),
            pl.BlockSpec((1, block_l, hkv),
                         lambda si, lb, *refs: (si, lb, 0)),
        ]
        inputs += [scale_k.astype(jnp.float32),
                   scale_v.astype(jnp.float32)]
    out_dtype = q.dtype
    return pl.pallas_call(
        functools.partial(_decode_kernel, cache_len=cache_len,
                          window=window, rolling=rolling, hkv=hkv,
                          scale=sc, quant=quant),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(s_, cache_len // block_l),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, h, dh),
                                   lambda si, lb, *refs: (si, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((h, dh), jnp.float32),
                pltpu.VMEM((h, 1), jnp.float32),
                pltpu.VMEM((h, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((s_, h, dh), out_dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(qpos, end, *inputs)


def _paged_decode_kernel(qpos_ref, pt_ref, *refs, page_len: int,
                         window: Optional[int], hkv: int, scale: float,
                         quant: bool = False):
    """Paged twin of `_decode_kernel`: grid = (slots, NP logical pages),
    and the lb-th cache block is whatever PHYSICAL page the slot's
    scalar-prefetched page table maps logical page lb to — the BlockSpec
    index_map reads `pt_ref[si, lb]`, so block-scattered storage costs
    the kernel nothing (vLLM-style TPU paged attention). Visibility
    stays the linear `j <= pos` arithmetic over LOGICAL positions
    j = lb * page_len + offset. Unmapped tail entries of the table must
    still hold a valid physical index (the pool keeps them 0): their
    blocks DMA in, but the relevant-guard skips their math."""
    if quant:
        (q_ref, k_ref, v_ref, sk_ref, sv_ref, o_ref,
         acc_scr, m_scr, l_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_scr, m_scr, l_scr = refs
        sk_ref = sv_ref = None
    si = pl.program_id(0)
    lb = pl.program_id(1)
    nlb = pl.num_programs(1)
    q = q_ref[0]                                   # [H, Dh]
    h, d = q.shape
    g = h // hkv
    pos = qpos_ref[si]

    @pl.when(lb == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # only logical pages intersecting [pos-w+1, pos] hold live keys
    relevant = lb * page_len <= pos
    if window is not None:
        relevant &= lb * page_len + page_len - 1 > pos - window

    @pl.when(relevant)
    def _():
        kc = k_ref[0]                              # [Lp, Hkv, Dh]
        vc = v_ref[0]
        if quant:
            # fused dequantize-on-load: widen the narrow block in VMEM
            kc = kc.astype(jnp.float32) * sk_ref[0][:, :, None]
            vc = vc.astype(jnp.float32) * sv_ref[0][:, :, None]
        prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
                else jax.lax.Precision.DEFAULT)
        s = jnp.concatenate([
            jnp.dot(q[hk * g:(hk + 1) * g], kc[:, hk, :].T,
                    preferred_element_type=jnp.float32,
                    precision=prec)
            for hk in range(hkv)], axis=0) * scale  # [H, Lp]
        j = (lb * page_len
             + jax.lax.broadcasted_iota(jnp.int32, (h, page_len), 1))
        vis = j <= pos
        if window is not None:
            vis = vis & (j > pos - window)
        s = jnp.where(vis, s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(vis, jnp.exp(s - m_new), 0.0)   # dead-block guard
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.concatenate([
            jnp.dot(p[hk * g:(hk + 1) * g].astype(vc.dtype), vc[:, hk, :],
                    preferred_element_type=jnp.float32, precision=prec)
            for hk in range(hkv)], axis=0)            # [H, Dh]
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(lb == nlb - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def paged_decode_attention(q, cache_k, cache_v, page_table, qpos,
                           window: Optional[int] = None,
                           scale: Optional[float] = None,
                           interpret: bool = False,
                           scale_k=None, scale_v=None):
    """Single-query attention over the PAGED KVSlotPool layout.

    q: [S, H, Dh]; cache_k/cache_v: [P, Lp, Hkv, Dh] — the shared
    physical page pool (post-write); page_table: [S, NP] int32 mapping
    each slot's logical pages to physical rows; qpos: [S] int32 logical
    position of each slot's query. Returns [S, H, Dh].

    The page table rides the scalar-prefetch lane next to the
    positions: Mosaic resolves each grid step's cache block address from
    `page_table[si, lb]` BEFORE the DMA, so sessions sharing a prompt
    prefix stream the SAME physical blocks and nothing is gathered into
    a per-slot logical copy. One compiled program serves every
    page-table content — page indices are data, not shape, the same
    zero-recompile discipline as slot ids. The block length IS the page
    length (pages are the unit of sharing and of tiling); quantized
    pools pass their [P, Lp, Hkv] scale rows for fused
    dequantize-on-load. Inference-only, non-rolling (the prefix cache
    never pages a rolling ring)."""
    s_, h, dh = q.shape
    page_len = cache_k.shape[1]
    hkv = cache_k.shape[2]
    npg = page_table.shape[1]
    if h % hkv:
        raise ValueError(f"H {h} not divisible by Hkv {hkv}")
    if not interpret and page_len % 128:
        raise ValueError(
            f"page_len {page_len} must be 128-lane tileable on TPU")
    sc = scale if scale is not None else dh ** -0.5
    quant = scale_k is not None
    qpos = qpos.astype(jnp.int32)
    page_table = page_table.astype(jnp.int32)
    in_specs = [
        pl.BlockSpec((1, h, dh), lambda si, lb, *refs: (si, 0, 0)),
        # the paged indirection: block lb of slot si is physical page
        # pt[si, lb] (refs = the scalar-prefetch operands, qpos then pt)
        pl.BlockSpec((1, page_len, hkv, dh),
                     lambda si, lb, qpos_ref, pt_ref: (pt_ref[si, lb],
                                                       0, 0, 0)),
        pl.BlockSpec((1, page_len, hkv, dh),
                     lambda si, lb, qpos_ref, pt_ref: (pt_ref[si, lb],
                                                       0, 0, 0)),
    ]
    inputs = [q, cache_k, cache_v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, page_len, hkv),
                         lambda si, lb, qpos_ref, pt_ref: (pt_ref[si, lb],
                                                           0, 0)),
            pl.BlockSpec((1, page_len, hkv),
                         lambda si, lb, qpos_ref, pt_ref: (pt_ref[si, lb],
                                                           0, 0)),
        ]
        inputs += [scale_k.astype(jnp.float32),
                   scale_v.astype(jnp.float32)]
    return pl.pallas_call(
        functools.partial(_paged_decode_kernel, page_len=page_len,
                          window=window, hkv=hkv, scale=sc, quant=quant),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(s_, npg),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, h, dh),
                                   lambda si, lb, *refs: (si, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((h, dh), jnp.float32),
                pltpu.VMEM((h, 1), jnp.float32),
                pltpu.VMEM((h, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((s_, h, dh), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(qpos, page_table, *inputs)
