"""Fused 1x1-conv + batch-norm statistics (Pallas TPU) — the conv-epilogue
fusion targeting the HBM-bound BN sweeps of ResNet-style bottlenecks.

Reference parity: the cuDNN helper seam
(`nn/layers/convolution/ConvolutionLayer.java:67-77` +
`CudnnBatchNormalizationHelper.java`) — DL4J points conv/BN at hand-fused
vendor kernels; here the vendor kernel is written in Pallas. PERF_NOTES
sink #2: at b128 every unfused BN costs a full read+write sweep of the
activation (819 GB/s HBM on v5e), and training-mode BN needs the batch
stats BEFORE it can normalize, forcing XLA into
    conv -> write y -> read y (stats reduce) -> read y -> write out
(= 2 reads + 2 writes of the activation per conv+BN pair). The kernel
below computes the matmul AND the per-channel sum / sum-of-squares in one
pass while the output tile is still in VMEM:
    pass 1 (Pallas) -> write y + tiny partials ; pass 2 (XLA, fused
    normalize+activation) -> read y, write out
(= 1 read + 2 writes) — the stats sweep rides the matmul for free, ~25%
of the epilogue traffic saved per conv+BN. A 1x1 conv over NHWC IS a
matmul [B*H*W, C_in] @ [C_in, C_out] — exactly what the MXU wants; the
ResNet-50 bottleneck 1x1s (reduce/expand/projection) carry ~2/3 of its
conv FLOPs.

Backward is `jax.custom_vjp` with the standard BN-through-matmul formulas
in plain XLA (two matmuls + fused elementwise; Pallas buys nothing there
because every term is already a single fused sweep).

On non-TPU backends the kernel runs in interpret mode (tests).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _divisor_block(n: int, candidates) -> Optional[int]:
    for c in candidates:
        if n % c == 0:
            return c
    return None


def pick_blocks(m: int, k: int, n: int
                ) -> Optional[Tuple[int, int, int]]:
    """Block sizes (bm, bk, bn) that exactly tile [m, k] @ [k, n], or None
    if the shape does not tile cleanly (caller falls back to XLA)."""
    bm = _divisor_block(m, (512, 256, 128, 64, 32, 16, 8))
    bk = _divisor_block(k, (512, 256, 128, 64, 32, 16, 8, 4, 2, 1))
    bn = _divisor_block(n, (256, 128, 64, 32, 16, 8))
    if bm is None or bk is None or bn is None:
        return None
    return bm, bk, bn


def _mm_stats_kernel(x_ref, w_ref, y_ref, s_ref, q_ref, acc,
                     acc_dtype=jnp.float32):
    """One (i, j) output tile: accumulate over k in VMEM, then emit the
    y tile plus its per-channel partial sum / sum-of-squares."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    acc[:] += jnp.dot(x_ref[:], w_ref[:],
                      preferred_element_type=acc_dtype)

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        t = acc[:]
        y_ref[:] = t.astype(y_ref.dtype)
        s_ref[:] = t.sum(axis=0, keepdims=True)[None]
        q_ref[:] = (t * t).sum(axis=0, keepdims=True)[None]


def _acc_dtype(dtype):
    """f32 accumulation normally; f64 when the inputs are f64 (the
    gradient-check path runs the whole net in double precision)."""
    return jnp.promote_types(dtype, jnp.float32)


def matmul_with_channel_stats(x2d, w, *, interpret: bool = False):
    """y = x2d @ w plus per-output-channel (sum, sum_of_squares) of y,
    computed inside the matmul kernel. Returns (y [M,N] in x2d.dtype,
    sums [N], sumsqs [N] in the accumulation dtype — f32, or f64 under
    double precision). Falls back to plain XLA when the shape does not
    tile."""
    m, k = x2d.shape
    k2, n = w.shape
    assert k == k2, (x2d.shape, w.shape)
    acc = _acc_dtype(x2d.dtype)
    blocks = pick_blocks(m, k, n)
    if blocks is None:
        y = jnp.dot(x2d, w, preferred_element_type=acc)
        return (y.astype(x2d.dtype), jnp.sum(y, axis=0),
                jnp.sum(y * y, axis=0))
    bm, bk, bn = blocks
    nm, nn, nk = m // bm, n // bn, k // bk
    y, ps, pq = pl.pallas_call(
        functools.partial(_mm_stats_kernel, acc_dtype=acc),
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
            # per-(i, j) partials, reduced over i below — each grid step
            # owns its own block, no cross-step output revisiting
            pl.BlockSpec((1, 1, bn), lambda i, j, kk: (i, 0, j)),
            pl.BlockSpec((1, 1, bn), lambda i, j, kk: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x2d.dtype),
            jax.ShapeDtypeStruct((nm, 1, n), acc),
            jax.ShapeDtypeStruct((nm, 1, n), acc),
        ],
        scratch_shapes=[pltpu.VMEM((bm, bn), acc)],
        interpret=interpret,
    )(x2d, w)
    return y, ps.sum(axis=(0, 1)), pq.sum(axis=(0, 1))


# ------------------------------------------------------------- train path
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _conv1x1_bn_train(x2d, w, gamma, beta, eps, relu, interpret):
    out, _, mean, var = _train_fwd_impl(x2d, w, gamma, beta, eps, relu,
                                        interpret)
    return out, mean, var


def _train_fwd_impl(x2d, w, gamma, beta, eps, relu, interpret):
    mval = x2d.shape[0]
    acc = _acc_dtype(x2d.dtype)
    y, s, q = matmul_with_channel_stats(x2d, w, interpret=interpret)
    mean = s / mval
    var = jnp.maximum(q / mval - mean * mean, 0.0)  # biased, clamped
    inv = jax.lax.rsqrt(var + eps)
    scale = gamma.astype(acc) * inv
    shift = beta.astype(acc) - mean * scale
    pre = y.astype(acc) * scale + shift
    out = jnp.maximum(pre, 0.0) if relu else pre
    return out.astype(x2d.dtype), y, mean, var


def _train_vjp_fwd(x2d, w, gamma, beta, eps, relu, interpret):
    out, y, mean, var = _train_fwd_impl(x2d, w, gamma, beta, eps, relu,
                                        interpret)
    return (out, mean, var), (x2d, w, gamma, beta, y, mean, var)


def _train_vjp_bwd(eps, relu, interpret, res, cts):
    # cotangents for (out, mean, var); the layer stop-gradients the
    # running-stat outputs, so d_mean/d_var are structurally zero here
    dout = cts[0]
    x2d, w, gamma, beta, y, mean, var = res
    mval = x2d.shape[0]
    ct = _acc_dtype(x2d.dtype)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (y.astype(ct) - mean) * inv
    g = dout.astype(ct)
    if relu:
        g = g * ((gamma.astype(ct) * xhat + beta.astype(ct)) > 0)
    dbeta = g.sum(axis=0)
    dgamma = (g * xhat).sum(axis=0)
    dxhat = g * gamma.astype(ct)
    # training-mode BN backward: mean/var depend on every row
    dy = inv * (dxhat - dxhat.mean(axis=0)
                - xhat * (dxhat * xhat).mean(axis=0))
    dx = jnp.dot(dy, w.astype(ct).T,
                 preferred_element_type=ct).astype(x2d.dtype)
    dw = jnp.dot(x2d.astype(ct).T, dy,
                 preferred_element_type=ct).astype(w.dtype)
    return dx, dw, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype)


_conv1x1_bn_train.defvjp(_train_vjp_fwd, _train_vjp_bwd)


# ------------------------------------------------------------ public API
def conv1x1_bn_act(x, w, gamma, beta, *, mean=None, var=None,
                   stride=(1, 1), eps: float = 1e-5, relu: bool = True,
                   train: bool = False, interpret: bool = False):
    """Fused 1x1-conv + batch norm + (optional) ReLU over NHWC input.

    x: [B, H, W, C_in]; w: [C_in, C_out]; gamma/beta: [C_out].
    train=True  -> (out, batch_mean, batch_var) — stats computed inside
                   the matmul kernel; running-stat update is the caller's
                   (they carry no gradient).
    train=False -> out, normalized with the provided running mean/var as
                   one folded scale/shift epilogue (plain XLA: a matmul
                   with a fused affine+relu consumer is already a single
                   kernel — Pallas buys nothing in eval mode).
    """
    sh, sw = stride
    if (sh, sw) != (1, 1):
        x = x[:, ::sh, ::sw, :]
    b, h, wd, c = x.shape
    n = w.shape[1]
    x2d = x.reshape(b * h * wd, c)
    if train:
        out2d, bmean, bvar = _conv1x1_bn_train(
            x2d, w, gamma, beta, eps, relu, interpret)
        return (out2d.reshape(b, h, wd, n),
                jax.lax.stop_gradient(bmean),
                jax.lax.stop_gradient(bvar))
    acc = _acc_dtype(x.dtype)
    inv = jax.lax.rsqrt(var.astype(acc) + eps)
    scale = gamma.astype(acc) * inv
    shift = beta.astype(acc) - mean.astype(acc) * scale
    pre = jnp.dot(x2d, w, preferred_element_type=acc)
    pre = pre * scale + shift
    if relu:
        pre = jnp.maximum(pre, 0.0)
    return pre.astype(x.dtype).reshape(b, h, wd, n)
