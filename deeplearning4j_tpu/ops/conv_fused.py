"""Fused conv + batch-norm statistics (Pallas TPU) — the conv-epilogue
fusion targeting the HBM-bound BN sweeps of ResNet-style bottlenecks.

STATUS: FROZEN/EXPERIMENTAL (2026-07-31) — measured 2x SLOWER than XLA
on the flagship (PERF_NOTES "DECISION"); kept opt-in for numerics and
as the cuDNN-helper-seam analogue. No new feature work; prefer deletion
over rework if a layer change would require touching the kernels.
Two kernel shapes are fused: 1x1 any stride (`conv1x1_bn_act`, a matmul)
and 3x3 stride-1 SAME (`conv3x3_bn_act`, nine shifted matmuls over a
VMEM halo) — together they cover every conv+BN pair in a ResNet-50
bottleneck; only the 7x7 stem stays on plain XLA.

Reference parity: the cuDNN helper seam
(`nn/layers/convolution/ConvolutionLayer.java:67-77` +
`CudnnBatchNormalizationHelper.java`) — DL4J points conv/BN at hand-fused
vendor kernels; here the vendor kernel is written in Pallas. PERF_NOTES
sink #2: at b128 every unfused BN costs a full read+write sweep of the
activation (819 GB/s HBM on v5e), and training-mode BN needs the batch
stats BEFORE it can normalize, forcing XLA into
    conv -> write y -> read y (stats reduce) -> read y -> write out
(= 2 reads + 2 writes of the activation per conv+BN pair). The kernel
below computes the matmul AND the per-channel sum / sum-of-squares in one
pass while the output tile is still in VMEM:
    pass 1 (Pallas) -> write y + tiny partials ; pass 2 (XLA, fused
    normalize+activation) -> read y, write out
(= 1 read + 2 writes) — the stats sweep rides the matmul for free, ~25%
of the epilogue traffic saved per conv+BN. A 1x1 conv over NHWC IS a
matmul [B*H*W, C_in] @ [C_in, C_out] — exactly what the MXU wants; the
ResNet-50 bottleneck 1x1s (reduce/expand/projection) carry ~2/3 of its
conv FLOPs.

Backward is `jax.custom_vjp` with the standard BN-through-matmul formulas
in plain XLA (two matmuls + fused elementwise; Pallas buys nothing there
because every term is already a single fused sweep).

On non-TPU backends the kernel runs in interpret mode (tests).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _divisor_block(n: int, candidates) -> Optional[int]:
    for c in candidates:
        if n % c == 0:
            return c
    return None


def pick_blocks(m: int, k: int, n: int
                ) -> Optional[Tuple[int, int, int]]:
    """Block sizes (bm, bk, bn) that exactly tile [m, k] @ [k, n], or None
    if the shape does not tile cleanly (caller falls back to XLA)."""
    bm = _divisor_block(m, (512, 256, 128, 64, 32, 16, 8))
    bk = _divisor_block(k, (512, 256, 128, 64, 32, 16, 8, 4, 2, 1))
    bn = _divisor_block(n, (256, 128, 64, 32, 16, 8))
    if bm is None or bk is None or bn is None:
        return None
    return bm, bk, bn


def _mm_stats_kernel(x_ref, w_ref, y_ref, s_ref, q_ref, acc,
                     acc_dtype=jnp.float32):
    """One (i, j) output tile: accumulate over k in VMEM, then emit the
    y tile plus its per-channel partial sum / sum-of-squares."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    acc[:] += jnp.dot(x_ref[:], w_ref[:],
                      preferred_element_type=acc_dtype)

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        t = acc[:]
        y_ref[:] = t.astype(y_ref.dtype)
        s_ref[:] = t.sum(axis=0, keepdims=True)[None]
        q_ref[:] = (t * t).sum(axis=0, keepdims=True)[None]


def _acc_dtype(dtype):
    """f32 accumulation normally; f64 when the inputs are f64 (the
    gradient-check path runs the whole net in double precision)."""
    return jnp.promote_types(dtype, jnp.float32)


def matmul_with_channel_stats(x2d, w, *, interpret: bool = False):
    """y = x2d @ w plus per-output-channel (sum, sum_of_squares) of y,
    computed inside the matmul kernel. Returns (y [M,N] in x2d.dtype,
    sums [N], sumsqs [N] in the accumulation dtype — f32, or f64 under
    double precision). Falls back to plain XLA when the shape does not
    tile."""
    m, k = x2d.shape
    k2, n = w.shape
    assert k == k2, (x2d.shape, w.shape)
    acc = _acc_dtype(x2d.dtype)
    blocks = pick_blocks(m, k, n)
    if blocks is None:
        y = jnp.dot(x2d, w, preferred_element_type=acc)
        return (y.astype(x2d.dtype), jnp.sum(y, axis=0),
                jnp.sum(y * y, axis=0))
    bm, bk, bn = blocks
    nm, nn, nk = m // bm, n // bn, k // bk
    y, ps, pq = pl.pallas_call(
        functools.partial(_mm_stats_kernel, acc_dtype=acc),
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
            # per-(i, j) partials, reduced over i below — each grid step
            # owns its own block, no cross-step output revisiting
            pl.BlockSpec((1, 1, bn), lambda i, j, kk: (i, 0, j)),
            pl.BlockSpec((1, 1, bn), lambda i, j, kk: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x2d.dtype),
            jax.ShapeDtypeStruct((nm, 1, n), acc),
            jax.ShapeDtypeStruct((nm, 1, n), acc),
        ],
        scratch_shapes=[pltpu.VMEM((bm, bn), acc)],
        interpret=interpret,
    )(x2d, w)
    return y, ps.sum(axis=(0, 1)), pq.sum(axis=(0, 1))


# ----------------------------------------------------- 3x3 conv variant
def _pick_conv3_blocks(b: int, h: int, w: int, cin: int, cout: int,
                       itemsize: int) -> Optional[Tuple[int, int]]:
    """(nb, bn) batch-group / cout-tile sizes for the 3x3 kernel, or None
    to fall back to XLA. nb groups images so the matmul M-dim (nb*h*w)
    feeds the MXU properly even at late-stage 7x7 maps; the VMEM guard
    keeps xpad + weight + accumulator tiles comfortably on-core."""
    nb = None
    for cand in (1, 2, 4, 8, 16, 32):
        if b % cand == 0 and cand * h * w >= 256:
            nb = cand
            break
    if nb is None:
        nb = b
    bn = _divisor_block(cout, (256, 128, 64, 32, 16, 8))
    if bn is None:
        return None
    xblk = nb * h * w * cin * itemsize
    wblk = 9 * cin * bn * itemsize
    yblk = nb * h * w * bn * itemsize
    xpad = nb * (h + 2) * (w + 2) * cin * itemsize
    acc = nb * h * w * bn * jnp.dtype(jnp.float32).itemsize
    # in/out blocks are double-buffered by the pipeline; scratch and the
    # accumulator temp are not. Budget well under the ~16MB/core VMEM.
    if 2 * (xblk + wblk + yblk) + xpad + acc > 10 * 1024 * 1024:
        return None
    return nb, bn


def _conv3_stats_kernel(x_ref, w_ref, y_ref, s_ref, q_ref, xpad,
                        acc_dtype=jnp.float32):
    """One (batch-group i, cout-tile j) step: zero-padded halo copy of the
    input group into VMEM, nine shifted matmuls (the 3x3 taps), then the
    output tile plus its per-channel partial sum / sum-of-squares — the
    BN statistics ride the conv exactly as in the 1x1 kernel."""
    nb, h, w, cin = x_ref.shape
    bn = w_ref.shape[3]

    # j (cout tiles) is the innermost grid axis and the x block depends
    # only on i, so the halo copy persists in scratch across the j sweep
    @pl.when(pl.program_id(1) == 0)
    def _():
        xpad[:] = jnp.zeros(xpad.shape, xpad.dtype)
        xpad[:, 1:h + 1, 1:w + 1, :] = x_ref[:]

    m = nb * h * w
    tot = jnp.zeros((m, bn), acc_dtype)
    for dh in range(3):
        for dw in range(3):
            xs = xpad[:, dh:dh + h, dw:dw + w, :].reshape(m, cin)
            tot += jnp.dot(xs, w_ref[dh, dw],
                           preferred_element_type=acc_dtype)
    y_ref[:] = tot.reshape(nb, h, w, bn).astype(y_ref.dtype)
    s_ref[:] = tot.sum(axis=0, keepdims=True)[None]
    q_ref[:] = (tot * tot).sum(axis=0, keepdims=True)[None]


def _conv3_xla(x, w, acc_dtype):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=acc_dtype)


def conv3x3_with_channel_stats(x, w, *, interpret: bool = False):
    """y = conv2d(x, w, stride 1, SAME) plus per-output-channel
    (sum, sum_of_squares) of y computed inside the conv kernel.
    x: [B, H, W, C_in] NHWC; w: [3, 3, C_in, C_out] HWIO. Returns
    (y in x.dtype, sums [C_out], sumsqs [C_out] in the accumulation
    dtype). Falls back to XLA conv + XLA reductions when the shape does
    not tile or would overflow VMEM."""
    b, h, wd, cin = x.shape
    assert w.shape[:2] == (3, 3) and w.shape[2] == cin, (x.shape, w.shape)
    cout = w.shape[3]
    acc = _acc_dtype(x.dtype)
    blocks = _pick_conv3_blocks(b, h, wd, cin, cout, x.dtype.itemsize)
    if blocks is None:
        y = _conv3_xla(x, w, acc)
        return (y.astype(x.dtype), jnp.sum(y, axis=(0, 1, 2)),
                jnp.sum(y * y, axis=(0, 1, 2)))
    nb, bn = blocks
    nm, nn = b // nb, cout // bn
    y, ps, pq = pl.pallas_call(
        functools.partial(_conv3_stats_kernel, acc_dtype=acc),
        grid=(nm, nn),
        in_specs=[
            pl.BlockSpec((nb, h, wd, cin), lambda i, j: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, cin, bn), lambda i, j: (0, 0, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((nb, h, wd, bn), lambda i, j: (i, 0, 0, j)),
            pl.BlockSpec((1, 1, bn), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 1, bn), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, wd, cout), x.dtype),
            jax.ShapeDtypeStruct((nm, 1, cout), acc),
            jax.ShapeDtypeStruct((nm, 1, cout), acc),
        ],
        scratch_shapes=[pltpu.VMEM((nb, h + 2, wd + 2, cin), x.dtype)],
        interpret=interpret,
    )(x, w)
    return y, ps.sum(axis=(0, 1)), pq.sum(axis=(0, 1))


# --------------------------------------------------- shared BN epilogue
def _bn_train_epilogue(y, s, q, mval, gamma, beta, eps, relu, acc):
    """Normalize a linear-op output y from its in-kernel (sum, sumsq)
    partials: returns (out in acc dtype, batch mean, biased clamped
    batch var). Shared by the 1x1 (reduce over rows) and 3x3 (reduce
    over B,H,W) paths — the per-channel stats broadcast identically."""
    mean = s / mval
    var = jnp.maximum(q / mval - mean * mean, 0.0)  # biased, clamped
    inv = jax.lax.rsqrt(var + eps)
    scale = gamma.astype(acc) * inv
    shift = beta.astype(acc) - mean * scale
    pre = y.astype(acc) * scale + shift
    out = jnp.maximum(pre, 0.0) if relu else pre
    return out, mean, var


def _bn_eval_fold(y, gamma, beta, mean, var, eps, relu, acc, out_dtype):
    """Eval-mode fold: running stats become one affine(+relu) epilogue
    on the linear-op output (XLA fuses this into the producing kernel).
    Shared by the 1x1 and 3x3 eval paths."""
    inv = jax.lax.rsqrt(var.astype(acc) + eps)
    scale = gamma.astype(acc) * inv
    shift = beta.astype(acc) - mean.astype(acc) * scale
    pre = y * scale + shift
    if relu:
        pre = jnp.maximum(pre, 0.0)
    return pre.astype(out_dtype)


def _bn_backward(dout, y, gamma, beta, mean, var, eps, relu, axes, mval,
                 ct):
    """Training-mode BN backward through the epilogue: returns (dy wrt
    the linear-op output, dgamma, dbeta) in the accumulation dtype; the
    caller finishes with the linear op's own transpose (matmul or conv
    VJP). `axes` are the reduction axes of the batch statistics, whose
    mean/var depend on every element of the reduction group."""
    inv = jax.lax.rsqrt(var + eps)
    xhat = (y.astype(ct) - mean) * inv
    g = dout.astype(ct)
    if relu:
        g = g * ((gamma.astype(ct) * xhat + beta.astype(ct)) > 0)
    dbeta = g.sum(axis=axes)
    dgamma = (g * xhat).sum(axis=axes)
    dxhat = g * gamma.astype(ct)
    dy = inv * (dxhat - dxhat.sum(axis=axes) / mval
                - xhat * (dxhat * xhat).sum(axis=axes) / mval)
    return dy, dgamma, dbeta


# ------------------------------------------------------------- train path
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _conv1x1_bn_train(x2d, w, gamma, beta, eps, relu, interpret):
    out, _, mean, var = _train_fwd_impl(x2d, w, gamma, beta, eps, relu,
                                        interpret)
    return out, mean, var


def _train_fwd_impl(x2d, w, gamma, beta, eps, relu, interpret):
    acc = _acc_dtype(x2d.dtype)
    y, s, q = matmul_with_channel_stats(x2d, w, interpret=interpret)
    out, mean, var = _bn_train_epilogue(y, s, q, x2d.shape[0], gamma,
                                        beta, eps, relu, acc)
    return out.astype(x2d.dtype), y, mean, var


def _train_vjp_fwd(x2d, w, gamma, beta, eps, relu, interpret):
    out, y, mean, var = _train_fwd_impl(x2d, w, gamma, beta, eps, relu,
                                        interpret)
    return (out, mean, var), (x2d, w, gamma, beta, y, mean, var)


def _train_vjp_bwd(eps, relu, interpret, res, cts):
    # cotangents for (out, mean, var); the layer stop-gradients the
    # running-stat outputs, so d_mean/d_var are structurally zero here
    dout = cts[0]
    x2d, w, gamma, beta, y, mean, var = res
    ct = _acc_dtype(x2d.dtype)
    dy, dgamma, dbeta = _bn_backward(dout, y, gamma, beta, mean, var,
                                     eps, relu, (0,), x2d.shape[0], ct)
    dx = jnp.dot(dy, w.astype(ct).T,
                 preferred_element_type=ct).astype(x2d.dtype)
    dw = jnp.dot(x2d.astype(ct).T, dy,
                 preferred_element_type=ct).astype(w.dtype)
    return dx, dw, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype)


_conv1x1_bn_train.defvjp(_train_vjp_fwd, _train_vjp_bwd)


# ------------------------------------------------------------ public API
def conv1x1_bn_act(x, w, gamma, beta, *, mean=None, var=None,
                   stride=(1, 1), eps: float = 1e-5, relu: bool = True,
                   train: bool = False, interpret: bool = False):
    """Fused 1x1-conv + batch norm + (optional) ReLU over NHWC input.

    x: [B, H, W, C_in]; w: [C_in, C_out]; gamma/beta: [C_out].
    train=True  -> (out, batch_mean, batch_var) — stats computed inside
                   the matmul kernel; running-stat update is the caller's
                   (they carry no gradient).
    train=False -> out, normalized with the provided running mean/var as
                   one folded scale/shift epilogue (plain XLA: a matmul
                   with a fused affine+relu consumer is already a single
                   kernel — Pallas buys nothing in eval mode).
    """
    sh, sw = stride
    if (sh, sw) != (1, 1):
        x = x[:, ::sh, ::sw, :]
    b, h, wd, c = x.shape
    n = w.shape[1]
    x2d = x.reshape(b * h * wd, c)
    if train:
        out2d, bmean, bvar = _conv1x1_bn_train(
            x2d, w, gamma, beta, eps, relu, interpret)
        return (out2d.reshape(b, h, wd, n),
                jax.lax.stop_gradient(bmean),
                jax.lax.stop_gradient(bvar))
    acc = _acc_dtype(x.dtype)
    pre = jnp.dot(x2d, w, preferred_element_type=acc)
    return _bn_eval_fold(pre, gamma, beta, mean, var, eps, relu, acc,
                         x.dtype).reshape(b, h, wd, n)


# --------------------------------------------- 3x3 train path + public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _conv3x3_bn_train(x, w, gamma, beta, eps, relu, interpret):
    out, _, mean, var = _conv3_train_fwd_impl(x, w, gamma, beta, eps,
                                              relu, interpret)
    return out, mean, var


def _conv3_train_fwd_impl(x, w, gamma, beta, eps, relu, interpret):
    b, h, wd, _ = x.shape
    acc = _acc_dtype(x.dtype)
    y, s, q = conv3x3_with_channel_stats(x, w, interpret=interpret)
    out, mean, var = _bn_train_epilogue(y, s, q, b * h * wd, gamma,
                                        beta, eps, relu, acc)
    return out.astype(x.dtype), y, mean, var


def _conv3_vjp_fwd(x, w, gamma, beta, eps, relu, interpret):
    out, y, mean, var = _conv3_train_fwd_impl(x, w, gamma, beta, eps,
                                              relu, interpret)
    return (out, mean, var), (x, w, gamma, beta, y, mean, var)


def _conv3_vjp_bwd(eps, relu, interpret, res, cts):
    # shared BN backward, then the conv's own VJP instead of the matmul
    # transposes (XLA derives the flipped-kernel conv for dx and the
    # patch correlation for dw)
    dout = cts[0]
    x, w, gamma, beta, y, mean, var = res
    b, h, wd, _ = x.shape
    ct = _acc_dtype(x.dtype)
    dy, dgamma, dbeta = _bn_backward(dout, y, gamma, beta, mean, var,
                                     eps, relu, (0, 1, 2), b * h * wd, ct)
    _, conv_vjp = jax.vjp(
        lambda xx, ww: _conv3_xla(xx, ww, ct),
        x.astype(ct), w.astype(ct))
    dx, dw = conv_vjp(dy)
    return (dx.astype(x.dtype), dw.astype(w.dtype),
            dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype))


_conv3x3_bn_train.defvjp(_conv3_vjp_fwd, _conv3_vjp_bwd)


def conv3x3_bn_act(x, w, gamma, beta, *, mean=None, var=None,
                   eps: float = 1e-5, relu: bool = True,
                   train: bool = False, interpret: bool = False):
    """Fused 3x3 stride-1 SAME conv + batch norm + (optional) ReLU over
    NHWC input — the 3x3 sibling of `conv1x1_bn_act`, covering the
    remaining third of ResNet-50's conv FLOPs (the bottleneck middle
    convs are all 3x3/1/SAME). Same contract: train=True returns
    (out, batch_mean, batch_var) with the statistics accumulated inside
    the conv kernel; train=False folds the running stats into one XLA
    conv+affine(+relu) epilogue."""
    if train:
        out, bmean, bvar = _conv3x3_bn_train(x, w, gamma, beta, eps,
                                             relu, interpret)
        return (out, jax.lax.stop_gradient(bmean),
                jax.lax.stop_gradient(bvar))
    acc = _acc_dtype(x.dtype)
    return _bn_eval_fold(_conv3_xla(x, w, acc), gamma, beta, mean, var,
                         eps, relu, acc, x.dtype)
