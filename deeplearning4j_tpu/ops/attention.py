"""Blockwise fused attention kernel (Pallas TPU).

No reference counterpart (DL4J predates attention — SURVEY §5 "no attention
layers at all"); this backs the framework's transformer extension
(`nn/layers/attention.py`, `parallel/ring_attention.py`) the way cuDNN
helpers backed conv layers in the reference (SURVEY §2.3 seam).

Design: classic flash-attention forward — grid over (batch·heads, q blocks,
K blocks); one [Bk, D] K/V tile is resident in VMEM at a time, with the
online-softmax statistics (running max m, normalizer l, accumulator) carried
in VMEM scratch across the innermost K grid dimension, so neither the
[T, T] score matrix nor the full K/V sequence ever sits in VMEM/HBM at
once. Causal masking skips dead K blocks' FLOPs via block-index
comparison.

Backward (FlashAttention-2 style, `backward="pallas"`): the
forward rule additionally saves the per-row log-sum-exp L = m + log(l)
(O(T) residual memory — q/k/v/o/L, never the [T, T] scores). Two Pallas
kernels then rematerialize score tiles blockwise: a dK/dV kernel with the
K/V tile pinned in VMEM scratch while sweeping Q blocks, and a dQ kernel
with the Q tile pinned while sweeping K blocks, using the softmax-vjp
identity ds = p * (dp - Δ) with Δ = rowsum(do · o) precomputed by XLA.
`backward="dense"` keeps the previous whole-[T, T] XLA recompute as a
fallback/oracle path. The default (`backward=None`) resolves from the
measured-winner table in `ops/kernel_defaults.py` — see that module for
the dispatch policy and its env escape hatches.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LSE_LANES = 128   # lane width for per-row statistics outputs (TPU tiling)

# jax renamed TPUCompilerParams -> CompilerParams across releases; accept
# whichever this jax ships so the kernels are not pinned to one side.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def _dense_attention(q, k, v, causal: bool, scale: float):
    """Reference O(T^2) attention used for the recompute backward."""
    scores = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), jnp.bool_))
        scores = jnp.where(mask[None], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", w, v)


def _prec(dtype):
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *rest, causal: bool,
                  scale: float, with_lse: bool):
    """Grid = (batch·heads, q blocks, K blocks): the K/V HBM→VMEM transfer
    is blocked by the grid itself (one [Bk, D] tile resident at a time),
    with the online-softmax state carried in VMEM scratch across the
    innermost (K) grid dimension. With `with_lse` the per-row
    log-sum-exp L = m + log(l) is emitted too (the training-path residual
    the Pallas backward rematerializes scores from)."""
    if with_lse:
        lse_ref, acc_scr, m_scr, l_scr = rest
    else:
        acc_scr, m_scr, l_scr = rest
    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    q = q_ref[0]                                  # [Bq, D]
    bq, d = q.shape
    block_k = k_ref.shape[1]

    @pl.when(kb == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # Causal: K blocks strictly above this Q block's last row are dead —
    # skip their FLOPs (the DMA still happens; acceptable at Bk=128).
    relevant = (kb * block_k <= (qb + 1) * bq - 1) if causal else (kb >= 0)

    @pl.when(relevant)
    def _():
        k = k_ref[0]                              # [Bk, D]
        v = v_ref[0]
        prec = _prec(q.dtype)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                    precision=prec) * scale
        if causal:
            q_ids = (qb * bq
                     + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0))
            k_ids = (kb * block_k
                     + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1))
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32,
            precision=prec)

    @pl.when(kb == nk - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        if with_lse:
            # Per-row scalar broadcast across a 128-lane last dim — the
            # narrowest output layout Mosaic accepts for row statistics
            # (cf. MIN_BLOCK_SIZE in jax's in-tree TPU flash kernel).
            lse_ref[0] = jnp.broadcast_to(m_scr[:] + jnp.log(l),
                                          (bq, _LSE_LANES))


def _fit_block(block: int, t: int) -> int:
    """Largest block <= requested that divides t (t must be a multiple of
    the 128-lane minimum). Block size is the decisive perf lever on TPU;
    the production sizes come from the measured-winner table in
    ops/kernel_defaults.py, populated by tools/kernel_bench.py."""
    block = min(block, t)
    while block > 128 and t % block:
        block -= 128
    if t % block:
        raise ValueError(f"seq len {t} not divisible by any block <= "
                         f"{block} (need a multiple of 128)")
    return block


def _run_flash(q, k, v, *, causal: bool, scale: float, block_q: int,
               block_k: int, interpret: bool, with_lse: bool = False):
    bh, tq, d = q.shape
    tk = k.shape[1]
    if causal and tq != tk:
        raise ValueError(
            f"causal attention requires Tq == Tk (got {tq} vs {tk}); "
            "cross-attention is non-causal")
    block_q = _fit_block(block_q, tq)
    block_k = _fit_block(block_k, tk)
    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale,
                               with_lse=with_lse)
    out_specs = [pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((bh, tq, d), q.dtype)]
    if with_lse:
        out_specs.append(pl.BlockSpec((1, block_q, _LSE_LANES),
                                      lambda b, i, j: (b, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((bh, tq, _LSE_LANES), jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid=(bh, tq // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=tuple(out_shape) if with_lse else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        # batch/Q-block dims have no cross-step state -> Mosaic may
        # parallelize and pipeline them; the K sweep carries scratch.
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    if with_lse:
        o, lse = out
        # Keep only one lane of the lane-broadcast row stats: residual
        # memory between forward and backward is O(T), not O(128*T).
        return o, lse[..., 0]
    return out, None


# ----------------------------------------------------- blockwise backward
def _bwd_tile(q, k, v, do, lse_col, delta_col, qb, kb, bq, block_k, causal,
              scale):
    """Shared score-tile rematerialization for both backward kernels:
    p = exp(s - L) row-wise, ds = p * (do·vᵀ - Δ) * scale."""
    prec = _prec(q.dtype)
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                precision=prec) * scale
    if causal:
        q_ids = (qb * bq
                 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0))
        k_ids = (kb * block_k
                 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1))
        s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
    p = jnp.exp(s - lse_col)                       # [Bq, Bk] f32
    dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32,
                 precision=prec)
    ds = p * (dp - delta_col) * scale
    return p, ds


def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                           scale: float):
    """Grid = (batch·heads, K blocks, Q blocks): the K/V tile's gradient
    accumulates in VMEM scratch across the innermost Q sweep."""
    kb = pl.program_id(1)
    qb = pl.program_id(2)
    nq = pl.num_programs(2)
    q = q_ref[0]
    bq = q.shape[0]
    block_k = k_ref.shape[1]

    @pl.when(qb == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # Causal: Q blocks entirely above this K block's first row are dead.
    relevant = ((qb + 1) * bq - 1 >= kb * block_k) if causal else (qb >= 0)

    @pl.when(relevant)
    def _():
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        prec = _prec(q.dtype)
        p, ds = _bwd_tile(q, k, v, do, lse_ref[0, :, 0:1],
                          delta_ref[0, :, 0:1], qb, kb, bq, block_k,
                          causal, scale)
        dv_scr[:] += jnp.dot(p.astype(do.dtype).T, do,
                             preferred_element_type=jnp.float32, precision=prec)
        dk_scr[:] += jnp.dot(ds.astype(q.dtype).T, q,
                             preferred_element_type=jnp.float32, precision=prec)

    @pl.when(qb == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, causal: bool, scale: float):
    """Grid = (batch·heads, Q blocks, K blocks): the Q tile's gradient
    accumulates in VMEM scratch across the innermost K sweep."""
    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    q = q_ref[0]
    bq = q.shape[0]
    block_k = k_ref.shape[1]

    @pl.when(kb == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    relevant = (kb * block_k <= (qb + 1) * bq - 1) if causal else (kb >= 0)

    @pl.when(relevant)
    def _():
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        prec = _prec(q.dtype)
        _, ds = _bwd_tile(q, k, v, do, lse_ref[0, :, 0:1],
                          delta_ref[0, :, 0:1], qb, kb, bq, block_k,
                          causal, scale)
        dq_scr[:] += jnp.dot(ds.astype(k.dtype), k,
                             preferred_element_type=jnp.float32, precision=prec)

    @pl.when(kb == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _run_flash_bwd(q, k, v, o, lse, do, *, causal: bool, scale: float,
                   block_q: int, block_k: int, interpret: bool,
                   dlse=None):
    """Blockwise dq/dk/dv from O(T) residuals (q, k, v, o, L).

    `lse` is the narrow [BH, Tq] log-sum-exp saved by the forward; both
    row stats are re-broadcast here to the lane-wide layout the kernels
    read. `dlse` (optional, [BH, Tq]) is the cotangent of the emitted
    log-sum-exp when the caller exposes it as an output (ring attention's
    merge does): since dL/ds_ij = p_ij, it folds into the softmax-vjp
    identity as a shift on Δ — ds = p * (dp - (Δ - dL)).
    """
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q = _fit_block(block_q, tq)
    block_k = _fit_block(block_k, tk)
    lse = jnp.broadcast_to(lse[..., None], (bh, tq, _LSE_LANES))
    # Δ = rowsum(do · o): one cheap fused elementwise+reduce in XLA.
    delta2 = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                     axis=-1, keepdims=True)
    if dlse is not None:
        delta2 = delta2 - dlse.astype(jnp.float32)[..., None]
    delta = jnp.broadcast_to(delta2, (bh, tq, _LSE_LANES))
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, block_q, _LSE_LANES),
                            lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    # dK/dV: K/V tile pinned (grid dim 1), Q swept (innermost dim 2)
    q_spec_t = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    row_spec_t = pl.BlockSpec((1, block_q, _LSE_LANES),
                              lambda b, j, i: (b, i, 0))
    kv_spec_t = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, causal=causal, scale=scale),
        grid=(bh, tk // block_k, tq // block_q),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, tk, d), v.dtype)],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal, scale=scale),
        grid=(bh, tq // block_q, tk // block_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def flash_eligible(tq: int, tk: Optional[int] = None, *,
                   min_t: int = 512) -> bool:
    """SHAPE eligibility for the flash kernel: TPU backend and
    128-lane-tileable sequence lengths. `min_t` is a PERF floor, not a
    capability one — the kernel runs from 128 up, but below ~512 it
    cannot amortize its block machinery, so the default floor suits
    structural users (ring attention's lse merge) that gate on this
    alone. The measured flash-vs-dense verdict, block sizes, and
    backward selection live in `kernel_defaults.attention_policy`,
    which consults capability (min_t=128) for the memory-necessity
    path."""
    tk = tq if tk is None else tk
    return (jax.default_backend() == "tpu" and tq % 128 == 0
            and tk % 128 == 0 and min(tq, tk) >= min_t)


def _fold3(x):
    """[B, T, H, D] → [BH, T, D] (identity for 3-D inputs)."""
    if x.ndim == 3:
        return x, None
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d), (b, t, h, d)


def _unfold3(x, shape):
    if shape is None:
        return x
    b, t, h, d = shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _resolve_backward(backward: Optional[str], tq: int, tk: int) -> str:
    """None -> the measured-winner default (kernel_defaults). Resolved
    ONCE, in the forward rule; the backward rule keys off whether lse
    was actually saved, so a mid-process env flip can never make the
    two rules disagree."""
    if backward is not None:
        return backward
    from deeplearning4j_tpu.ops.kernel_defaults import attention_backward

    return attention_backward(tq, tk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 512,
                    block_k: int = 512, interpret: bool = False,
                    backward: Optional[str] = None):
    """Fused attention. q/k/v: [B, T, H, D] or [BH, T, D]; returns same
    layout.

    Residual memory of the forward is O(T) either way: the forward rule
    saves q/k/v/o and the per-row log-sum-exp. `backward` selects how
    dq/dk/dv are produced: "pallas" rematerializes score tiles blockwise
    in two Pallas kernels — the [T, T] matrix never exists; "dense" is
    the whole-matrix XLA recompute kept as the oracle/fallback path.
    None (default) resolves to the measured winner via
    `kernel_defaults.attention_backward` (env hatch:
    DL4J_TPU_ATTN_BACKWARD)."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    q3, shape = _fold3(q)
    k3, _ = _fold3(k)
    v3, _ = _fold3(v)
    o, _ = _run_flash(q3, k3, v3, causal=causal, scale=s, block_q=block_q,
                      block_k=block_k, interpret=interpret)
    return _unfold3(o, shape)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               backward):
    backward = _resolve_backward(backward, q.shape[1], k.shape[1])
    s = scale if scale is not None else q.shape[-1] ** -0.5
    q3, shape_q = _fold3(q)
    k3, shape_k = _fold3(k)   # cross-attention: Tk may differ from Tq
    v3, _ = _fold3(v)
    o3, lse = _run_flash(q3, k3, v3, causal=causal, scale=s,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret,
                         with_lse=(backward == "pallas"))
    return _unfold3(o3, shape_q), (q3, k3, v3, o3, lse, shape_q, shape_k)


def _flash_bwd(causal, scale, block_q, block_k, interpret, backward, res,
               do):
    q3, k3, v3, o3, lse, shape_q, shape_k = res
    if backward is None:
        # Follow the forward rule's resolved choice (visible as whether
        # it saved the lse residual) rather than re-consulting the env —
        # re-resolving could pick "pallas" with lse=None after a
        # mid-process DL4J_TPU_ATTN_BACKWARD flip.
        backward = "pallas" if lse is not None else "dense"
    s = scale if scale is not None else q3.shape[-1] ** -0.5
    do3, _ = _fold3(do)
    if backward == "pallas":
        dq, dk, dv = _run_flash_bwd(q3, k3, v3, o3, lse, do3, causal=causal,
                                    scale=s, block_q=block_q,
                                    block_k=block_k, interpret=interpret)
    else:
        _, vjp = jax.vjp(
            lambda qq, kk, vv: _dense_attention(qq, kk, vv, causal, s),
            q3, k3, v3)
        dq, dk, dv = vjp(do3)
    return (_unfold3(dq, shape_q), _unfold3(dk, shape_k),
            _unfold3(dv, shape_k))


flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_with_lse(q, k, v, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: int = 512, block_k: int = 512,
                             interpret: bool = False):
    """Fused attention over 3-D [BH, T, D] inputs returning
    (o [BH, T, D], lse [BH, T]) — the building block for attention
    protocols that merge partial results across K/V shards (ring
    attention): two shards' outputs combine exactly via
    lse' = logaddexp(lse_a, lse_b), o' = o_a·e^{lse_a−lse'} +
    o_b·e^{lse_b−lse'}. Differentiable in both outputs (the lse
    cotangent rides the Pallas backward's Δ term)."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    o, lse = _run_flash(q, k, v, causal=causal, scale=s, block_q=block_q,
                        block_k=block_k, interpret=interpret, with_lse=True)
    return o, lse


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    s = scale if scale is not None else q.shape[-1] ** -0.5
    o, lse = _run_flash(q, k, v, causal=causal, scale=s, block_q=block_q,
                        block_k=block_k, interpret=interpret, with_lse=True)
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret, res, cts):
    do, dlse = cts
    q, k, v, o, lse = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    dq, dk, dv = _run_flash_bwd(q, k, v, o, lse, do, causal=causal,
                                scale=s, block_q=block_q, block_k=block_k,
                                interpret=interpret, dlse=dlse)
    return dq, dk, dv


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)
