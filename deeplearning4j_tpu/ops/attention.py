"""Blockwise fused attention kernel (Pallas TPU).

No reference counterpart (DL4J predates attention — SURVEY §5 "no attention
layers at all"); this backs the framework's transformer extension
(`nn/layers/attention.py`, `parallel/ring_attention.py`) the way cuDNN
helpers backed conv layers in the reference (SURVEY §2.3 seam).

Design: classic flash-attention forward — grid over (batch·heads, q blocks,
K blocks); one [Bk, D] K/V tile is resident in VMEM at a time, with the
online-softmax statistics (running max m, normalizer l, accumulator) carried
in VMEM scratch across the innermost K grid dimension, so neither the
[T, T] score matrix nor the full K/V sequence ever sits in VMEM/HBM at
once. Causal masking skips dead K blocks' FLOPs via block-index
comparison. The backward pass recomputes
attention with XLA (rematerialization — the standard flash trade: O(T)
memory for extra FLOPs) via `jax.custom_vjp`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _dense_attention(q, k, v, causal: bool, scale: float):
    """Reference O(T^2) attention used for the recompute backward."""
    scores = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), jnp.bool_))
        scores = jnp.where(mask[None], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", w, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_scr, m_scr, l_scr, *,
                  causal: bool, scale: float):
    """Grid = (batch·heads, q blocks, K blocks): the K/V HBM→VMEM transfer
    is blocked by the grid itself (one [Bk, D] tile resident at a time),
    with the online-softmax state carried in VMEM scratch across the
    innermost (K) grid dimension."""
    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    q = q_ref[0]                                  # [Bq, D]
    bq, d = q.shape
    block_k = k_ref.shape[1]

    @pl.when(kb == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # Causal: K blocks strictly above this Q block's last row are dead —
    # skip their FLOPs (the DMA still happens; acceptable at Bk=128).
    relevant = (kb * block_k <= (qb + 1) * bq - 1) if causal else (kb >= 0)

    @pl.when(relevant)
    def _():
        k = k_ref[0]                              # [Bk, D]
        v = v_ref[0]
        prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
                else jax.lax.Precision.DEFAULT)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                    precision=prec) * scale
        if causal:
            q_ids = (qb * bq
                     + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0))
            k_ids = (kb * block_k
                     + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1))
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32,
            precision=prec)

    @pl.when(kb == nk - 1)
    def _():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)
                    ).astype(o_ref.dtype)


def _run_flash(q, k, v, *, causal: bool, scale: float, block_q: int,
               block_k: int, interpret: bool):
    bh, t, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"seq len {t} not divisible by blocks "
                         f"({block_q}, {block_k})")
    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale)
    return pl.pallas_call(
        kernel,
        grid=(bh, t // block_q, t // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Fused attention. q/k/v: [B, T, H, D] or [BH, T, D]; returns same
    layout.

    Forward saves only q/k/v (O(T) residual memory). The backward, however,
    is currently a DENSE recompute via XLA — it materializes the [T, T]
    scores again — so for training at long T prefer the plain XLA path (the
    MultiHeadAttention layer auto-uses this kernel for inference only); a
    blockwise Pallas backward is future work."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    mh = q.ndim == 4
    if mh:
        b, t, h, d = q.shape
        fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        q3, k3, v3 = fold(q), fold(k), fold(v)
    else:
        q3, k3, v3 = q, k, v
    o = _run_flash(q3, k3, v3, causal=causal, scale=s, block_q=block_q,
                   block_k=block_k, interpret=interpret)
    if mh:
        o = o.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    return (flash_attention(q, k, v, causal, scale, block_q, block_k,
                            interpret),
            (q, k, v))


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    mh = q.ndim == 4
    if mh:
        b, t, h, d = q.shape
        fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        unfold = lambda x: x.reshape(b, h, t, d).transpose(0, 2, 1, 3)
        q3, k3, v3, do3 = fold(q), fold(k), fold(v), fold(do)
    else:
        q3, k3, v3, do3 = q, k, v, do
    _, vjp = jax.vjp(
        lambda qq, kk, vv: _dense_attention(qq, kk, vv, causal, s),
        q3, k3, v3)
    dq, dk, dv = vjp(do3)
    if mh:
        dq, dk, dv = unfold(dq), unfold(dk), unfold(dv)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd, _flash_bwd)
