"""One-pass fused optimizer update (Pallas TPU).

The XLA update path walks each leaf three times at the HBM level: the
moment updates and the parameter subtraction are separate read-modify-
write sweeps over tensors that share no compute (`optim/updaters.py`
builds `updates` then the step function applies `params - updates`). At
optimizer-bound scales (large embeddings, f32 moments against bf16
params) that is pure memory-bandwidth waste. These kernels do the whole
read-modify-write in ONE pass per leaf — param + both Adam moments (or
the Nesterov velocity) stream through VMEM once, with
`input_output_aliases` making the update genuinely in-place in HBM.

Layout: every leaf is flattened and tiled to [rows, 128] lanes (zero-
padded; pads compute to zero and are sliced away), so one kernel serves
every parameter shape. The traced scalar coefficient (lr · bias-
correction) rides in as a tiny lane-broadcast array, which keeps the
compiled program independent of step — the train step stays one program.

Dispatch discipline is `kernel_defaults.fused_update_policy`: the XLA
path remains the default until a measured winning row exists
(tools/kernel_bench.py --fused-update); `DL4J_TPU_FUSED_UPDATE=fused`
forces it. `optim/updaters.py::Updater.update_with_params` is the seam.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.attention import _CompilerParams

_LANES = 128
_SUBLANES = 8


def fused_update_available() -> bool:
    """Hardware capability only — whether the fused path WINS is the
    measured question `kernel_defaults.fused_update_policy` answers."""
    return jax.default_backend() == "tpu"


def _adam_kernel(c_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref, vo_ref,
                 *, b1: float, b2: float, eps: float):
    """p/m/v read-modify-write in one VMEM residency: m' and v' never
    round-trip to HBM between their update and their use."""
    lrbc = c_ref[0, 0]
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    po_ref[:] = (p - lrbc * m_new
                 / (jnp.sqrt(v_new) + eps)).astype(po_ref.dtype)
    mo_ref[:] = m_new.astype(mo_ref.dtype)
    vo_ref[:] = v_new.astype(vo_ref.dtype)


def _nesterov_kernel(c_ref, p_ref, g_ref, v_ref, po_ref, vo_ref, *,
                     mu: float):
    """ND4J Nesterovs semantics (optim/updaters.py): v' = mu·v - lr·g,
    p' = p + mu·v' - lr·g."""
    lr = c_ref[0, 0]
    g = g_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    v_new = mu * v - lr * g
    po_ref[:] = (p + mu * v_new - lr * g).astype(po_ref.dtype)
    vo_ref[:] = v_new.astype(vo_ref.dtype)


def _tile(x, rows: int):
    flat = x.reshape(-1)
    pad = rows * _LANES - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, _LANES)


def _untile(t, shape, size: int):
    return t.reshape(-1)[:size].reshape(shape)


def _geometry(n: int, block_rows: int):
    """Rows padded to the f32 sublane tile and to a whole number of
    blocks, so one BlockSpec covers every leaf size."""
    rows = max(1, -(-n // _LANES))
    rows = -(-rows // _SUBLANES) * _SUBLANES
    block = min(block_rows, rows)
    rows = -(-rows // block) * block
    return rows, block


def _run(kernel, coeff, arrays, out_dtypes, *, block_rows: int,
         interpret: bool):
    """Shared driver: tile leaves to [rows, 128], sweep row blocks, alias
    every state input onto its output slot (inputs after the coefficient
    and the gradient are in-place by construction)."""
    n = arrays[0].size
    shape = arrays[0].shape
    rows, block = _geometry(n, block_rows)
    c = jnp.broadcast_to(jnp.asarray(coeff, jnp.float32).reshape(1, 1),
                         (1, _LANES))
    tiles = [_tile(a, rows) for a in arrays]
    row_spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
    # inputs: [c, p, g, *state]; outputs: [p', *state'] — p and each
    # state tensor alias their output (g and c are read-only)
    aliases = {1: 0}
    for idx in range(3, len(arrays) + 1):
        aliases[idx] = idx - 2
    out = pl.pallas_call(
        kernel,
        grid=(rows // block,),
        in_specs=[pl.BlockSpec((1, _LANES), lambda i: (0, 0))]
        + [row_spec] * len(tiles),
        out_specs=[row_spec] * len(out_dtypes),
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), d)
                   for d in out_dtypes],
        input_output_aliases=aliases,
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(c, *tiles)
    return tuple(_untile(o, shape, n) for o in out)


def adam_update(p, g, m, v, lrbc, *, beta1: float = 0.9,
                beta2: float = 0.999, eps: float = 1e-8,
                block_rows: int = 512, interpret: bool = False):
    """One-leaf fused Adam step. `lrbc` is the traced scalar
    lr · sqrt(1-β2^t)/(1-β1^t) (the caller owns the schedule and bias
    correction — they are per-step scalars, not per-element work).
    Returns (p', m', v') in the argument dtypes."""
    return _run(functools.partial(_adam_kernel, b1=beta1, b2=beta2,
                                  eps=eps),
                lrbc, [p, g, m, v], [p.dtype, m.dtype, v.dtype],
                block_rows=block_rows, interpret=interpret)


def nesterov_update(p, g, vel, lr, *, momentum: float = 0.9,
                    block_rows: int = 512, interpret: bool = False):
    """One-leaf fused Nesterovs step; returns (p', v')."""
    return _run(functools.partial(_nesterov_kernel, mu=momentum),
                lr, [p, g, vel], [p.dtype, vel.dtype],
                block_rows=block_rows, interpret=interpret)
