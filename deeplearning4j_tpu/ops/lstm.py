"""Fused LSTM sequence kernel (Pallas TPU).

Reference parity: `nn/layers/recurrent/LSTMHelpers.java` — the hand-fused
forward (`:62`) and backward (`:291`) passes DL4J wrote because eager
op-at-a-time execution of the recurrence was too slow; SURVEY §7 names the
fused LSTM cell as the framework's Pallas obligation.

Design:
- The big input projection x@W+b for ALL timesteps happens OUTSIDE the
  kernel as one [B*T, F]@[F, 4H] MXU matmul (XLA's strength). The kernel
  fuses what XLA cannot: the sequential recurrence. It runs a grid over
  timesteps keeping h/c resident in VMEM scratch, so each step is one
  small [B,H]@[H,4H] MXU matmul plus VPU gate math — no HBM round-trip for
  the carry between steps, no per-step kernel launch.
- Backward is a hand-written reverse-time Pallas kernel wired up via
  `jax.custom_vjp`, accumulating dRW/dP in VMEM scratch across the grid
  (the moral equivalent of LSTMHelpers' backpropGradientHelper). dW/dx/db
  fall out of autodiff OUTSIDE the kernel since xw is the custom-vjp input.
- Gate order i,f,g,o; sigmoid gates, tanh cell — matching
  `layers/recurrent.py` (which matches GravesLSTMParamInitializer).
  Peepholes (GravesLSTM) are supported branch-free: P=zeros disables them.
- Per-timestep masking holds the carry where mask==0 (reference
  variable-length semantics).

On non-TPU backends the kernels run in interpret mode (tests) or layers
fall back to the lax.scan path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def fused_lstm_available(gate_activation: str, activation: str) -> bool:
    return gate_activation == "sigmoid" and activation == "tanh"


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# --------------------------------------------------------------- forward
def _cell(xw_t, h_prev, c_prev, rw, p):
    """Shared gate math for both forward kernel variants."""
    hsz = h_prev.shape[-1]
    gates = xw_t + jnp.dot(h_prev, rw, preferred_element_type=h_prev.dtype)
    i = _sigmoid(gates[:, :hsz] + c_prev * p[0:1, :])
    f = _sigmoid(gates[:, hsz:2 * hsz] + c_prev * p[1:2, :])
    g = jnp.tanh(gates[:, 2 * hsz:3 * hsz])
    c_new = f * c_prev + i * g
    o = _sigmoid(gates[:, 3 * hsz:] + c_new * p[2:3, :])
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new, i, f, g, o


def _fwd_kernel(xw_ref, rw_ref, p_ref, h0_ref, c0_ref, m_ref,
                hs_ref, cs_ref, gates_ref, hT_ref, cT_ref,
                h_scr, c_scr):
    """Training forward: also emits the cs/gates residuals for backward."""
    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h_prev, c_prev = h_scr[:], c_scr[:]
    h_new, c_new, i, f, g, o = _cell(
        xw_ref[0], h_prev, c_prev, rw_ref[:], p_ref[:])
    m = jnp.transpose(m_ref[pl.ds(t, 1), :])    # [B, 1]
    h = m * h_new + (1.0 - m) * h_prev
    c = m * c_new + (1.0 - m) * c_prev

    h_scr[:] = h
    c_scr[:] = c
    hs_ref[0] = h
    cs_ref[0] = c
    gates_ref[0] = jnp.concatenate([i, f, g, o], axis=-1)

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h
        cT_ref[:] = c


def _fwd_kernel_inference(xw_ref, rw_ref, p_ref, h0_ref, c0_ref, m_ref,
                          hs_ref, hT_ref, cT_ref, h_scr, c_scr):
    """Inference forward: writes only hs/h_T/c_T — ~5x less HBM output
    bandwidth than the training variant (no cs/gates residuals)."""
    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h_prev, c_prev = h_scr[:], c_scr[:]
    h_new, c_new, _, _, _, _ = _cell(
        xw_ref[0], h_prev, c_prev, rw_ref[:], p_ref[:])
    m = jnp.transpose(m_ref[pl.ds(t, 1), :])
    h = m * h_new + (1.0 - m) * h_prev
    c = m * c_new + (1.0 - m) * c_prev
    h_scr[:] = h
    c_scr[:] = c
    hs_ref[0] = h

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h
        cT_ref[:] = c


def _run_forward(xw, rw, p, h0, c0, mask, *, interpret: bool,
                 with_residuals: bool = True):
    T, B, H4 = xw.shape
    H = H4 // 4
    dt = xw.dtype
    res_out = [
        jax.ShapeDtypeStruct((T, B, H), dt),    # cs
        jax.ShapeDtypeStruct((T, B, H4), dt),   # activated gates
    ] if with_residuals else []
    res_spec = [
        pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
        pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0)),
    ] if with_residuals else []
    out = pl.pallas_call(
        _fwd_kernel if with_residuals else _fwd_kernel_inference,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((3, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((T, B), lambda t: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, B, H), lambda t: (t, 0, 0))] + res_spec
        + [
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_shape=tuple([jax.ShapeDtypeStruct((T, B, H), dt)] + res_out + [
            jax.ShapeDtypeStruct((B, H), dt),
            jax.ShapeDtypeStruct((B, H), dt),
        ]),
        scratch_shapes=[
            pltpu.VMEM((B, H), dt),
            pltpu.VMEM((B, H), dt),
        ],
        interpret=interpret,
    )(xw, rw, p, h0, c0, mask)
    if with_residuals:
        return out  # (hs, cs, gates, hT, cT)
    hs, hT, cT = out
    return hs, None, None, hT, cT


# -------------------------------------------------------------- backward
def _bwd_kernel(dhs_ref, gates_ref, cs_ref, csp_ref, hsp_ref, rw_ref, p_ref,
                m_ref, dhT_ref, dcT_ref, h0_ref, c0_ref,
                dxw_ref, dh0_ref, dc0_ref, drw_ref, dp_ref,
                dh_scr, dc_scr, drw_scr, dp_scr):
    idx = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(idx == 0)
    def _():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]
        drw_scr[:] = jnp.zeros_like(drw_scr)
        dp_scr[:] = jnp.zeros_like(dp_scr)

    gates = gates_ref[0]
    hsz = gates.shape[-1] // 4
    i = gates[:, :hsz]
    f = gates[:, hsz:2 * hsz]
    g = gates[:, 2 * hsz:3 * hsz]
    o = gates[:, 3 * hsz:]
    c_t = cs_ref[0]
    # csp/hsp alias cs/hs with a t-1 index map (clamped at 0); the true t=0
    # predecessors are the initial carry.
    t_is_0 = idx == T - 1
    c_prev = jnp.where(t_is_0, c0_ref[:], csp_ref[0])
    h_prev = jnp.where(t_is_0, h0_ref[:], hsp_ref[0])
    p = p_ref[:]
    m = jnp.transpose(m_ref[pl.ds(T - 1 - idx, 1), :])   # [B, 1]

    dh_in = dhs_ref[0] + dh_scr[:]
    dh_t = m * dh_in            # grad into the freshly computed h at step t
    pass_h = (1.0 - m) * dh_in  # grad flowing straight to h_{t-1} (mask hold)

    tanh_c = jnp.tanh(c_t)
    do_pre = dh_t * tanh_c * o * (1.0 - o)
    dc_new = (m * dc_scr[:] + dh_t * o * (1.0 - tanh_c * tanh_c)
              + do_pre * p[2:3, :])
    di_pre = dc_new * g * i * (1.0 - i)
    df_pre = dc_new * c_prev * f * (1.0 - f)
    dg_pre = dc_new * i * (1.0 - g * g)
    dc_prev = (dc_new * f + (1.0 - m) * dc_scr[:]
               + di_pre * p[0:1, :] + df_pre * p[1:2, :])

    dgates = jnp.concatenate([di_pre, df_pre, dg_pre, do_pre], axis=-1)
    dh_prev = jnp.dot(dgates, rw_ref[:].T,
                      preferred_element_type=dgates.dtype) + pass_h

    dxw_ref[0] = dgates
    drw_scr[:] = drw_scr[:] + jnp.dot(
        h_prev.T, dgates, preferred_element_type=dgates.dtype)
    dp_scr[0:1, :] = dp_scr[0:1, :] + jnp.sum(di_pre * c_prev, axis=0,
                                               keepdims=True)
    dp_scr[1:2, :] = dp_scr[1:2, :] + jnp.sum(df_pre * c_prev, axis=0,
                                              keepdims=True)
    dp_scr[2:3, :] = dp_scr[2:3, :] + jnp.sum(do_pre * c_t, axis=0,
                                              keepdims=True)
    dh_scr[:] = dh_prev
    dc_scr[:] = dc_prev

    @pl.when(idx == T - 1)
    def _():
        dh0_ref[:] = dh_scr[:]
        dc0_ref[:] = dc_scr[:]
        drw_ref[:] = drw_scr[:]
        dp_ref[:] = dp_scr[:]


def _run_backward(res, dhs, dhT, dcT, *, interpret: bool):
    rw, p, mask, hs, cs, gates, h0, c0 = res
    T, B, H = hs.shape
    H4 = 4 * H
    dt = hs.dtype
    rev = lambda t: (T - 1 - t, 0, 0)
    # Previous-step blocks read from hs/cs themselves (no shifted copies):
    # grid step i handles t = T-1-i and wants index t-1, clamped at 0 (the
    # clamped read is discarded in-kernel in favour of h0/c0).
    rev_prev = lambda t: (jnp.maximum(T - 2 - t, 0), 0, 0)
    out_shape = (
        jax.ShapeDtypeStruct((T, B, H4), dt),   # dxw
        jax.ShapeDtypeStruct((B, H), dt),       # dh0
        jax.ShapeDtypeStruct((B, H), dt),       # dc0
        jax.ShapeDtypeStruct((H, H4), dt),      # dRW
        jax.ShapeDtypeStruct((3, H), dt),       # dP
    )
    return pl.pallas_call(
        _bwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H), rev),       # dhs
            pl.BlockSpec((1, B, H4), rev),      # gates
            pl.BlockSpec((1, B, H), rev),       # cs
            pl.BlockSpec((1, B, H), rev_prev),  # cs at t-1
            pl.BlockSpec((1, B, H), rev_prev),  # hs at t-1
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((3, H), lambda t: (0, 0)),
            pl.BlockSpec((T, B), lambda t: (0, 0)),   # mask (full)
            pl.BlockSpec((B, H), lambda t: (0, 0)),   # dh_T
            pl.BlockSpec((B, H), lambda t: (0, 0)),   # dc_T
            pl.BlockSpec((B, H), lambda t: (0, 0)),   # h0
            pl.BlockSpec((B, H), lambda t: (0, 0)),   # c0
        ],
        out_specs=[
            pl.BlockSpec((1, B, H4), rev),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((3, H), lambda t: (0, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((B, H), dt),
            pltpu.VMEM((B, H), dt),
            pltpu.VMEM((H, H4), dt),
            pltpu.VMEM((3, H), dt),
        ],
        interpret=interpret,
    )(dhs, gates, cs, cs, hs, rw, p, mask, dhT, dcT, h0, c0)


# ------------------------------------------------------------ public op
@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def fused_lstm(xw, rw, p, h0, c0, mask, interpret=False):
    """Fused LSTM over a whole sequence.

    xw:   [T, B, 4H] precomputed x@W + b (gate order i,f,g,o)
    rw:   [H, 4H] recurrent weights; p: [3, H] peepholes (zeros = none)
    h0/c0:[B, H] initial carry; mask: [T, B] 1=valid (carry held at 0)
    Returns (hs [T, B, H], h_T, c_T).
    """
    hs, _, _, hT, cT = _run_forward(
        xw, rw, p, h0, c0, mask, interpret=interpret, with_residuals=False)
    return hs, hT, cT


def _fused_fwd(xw, rw, p, h0, c0, mask, interpret):
    hs, cs, gates, hT, cT = _run_forward(
        xw, rw, p, h0, c0, mask, interpret=interpret)
    return (hs, hT, cT), (rw, p, mask, hs, cs, gates, h0, c0)


def _fused_bwd(interpret, res, cts):
    dhs, dhT, dcT = cts
    rw, p, mask, hs, cs, gates, h0, c0 = res
    dxw, dh0, dc0, drw, dp = _run_backward(
        res, dhs, dhT, dcT, interpret=interpret)
    return dxw, drw, dp, dh0, dc0, None


fused_lstm.defvjp(_fused_fwd, _fused_bwd)
