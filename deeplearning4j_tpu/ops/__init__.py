"""Pallas TPU kernels for the hot ops.

Reference parity: this package plays the role of deeplearning4j-cuda's
cuDNN helper plug-ins (SURVEY §2.3 — `ConvolutionHelper` etc. loaded
reflectively by layer impls) — hand-tuned kernels behind the layer seam.
TPU-first difference: XLA already emits excellent conv/BN/pool kernels, so
those need no helpers; the wins are the ops XLA can't fuse across time
steps — the LSTM recurrence (the reference's `LSTMHelpers.java` fused
fwd/bwd, flagged in SURVEY §7 as the Pallas obligation) and blockwise
attention. Layers pick these up automatically on TPU and fall back to the
pure-XLA path elsewhere (mirroring the reference's helper-or-builtin
dispatch, `ConvolutionLayer.java:67-77`).
"""

from deeplearning4j_tpu.ops.lstm import fused_lstm, fused_lstm_available
from deeplearning4j_tpu.ops.attention import flash_attention
from deeplearning4j_tpu.ops.banded_attention import (
    banded_attention,
    banded_decode_attention,
    banded_eligible,
    decode_eligible,
)
from deeplearning4j_tpu.ops.fused_update import (
    adam_update,
    fused_update_available,
    nesterov_update,
)

__all__ = [
    "fused_lstm",
    "fused_lstm_available",
    "flash_attention",
    "banded_attention",
    "banded_decode_attention",
    "banded_eligible",
    "decode_eligible",
    "adam_update",
    "fused_update_available",
    "nesterov_update",
]
