"""Interop with external checkpoint formats (DL4J zip containers)."""

from deeplearning4j_tpu.interop.dl4j import (  # noqa: F401
    export_dl4j_model,
    import_dl4j_model,
    read_nd4j_array,
    write_nd4j_array,
)

__all__ = [
    "export_dl4j_model",
    "import_dl4j_model",
    "read_nd4j_array",
    "write_nd4j_array",
]
