"""DL4J checkpoint-container interop: read/write the reference's zip format.

The reference persists models as a zip of three entries
(`util/ModelSerializer.java:37-119`):

- ``configuration.json`` — the Jackson-serialized MultiLayerConfiguration
  (`nn/conf/MultiLayerConfiguration.java`), layers wrapped in one-key type
  objects per ``@JsonTypeInfo(WRAPPER_OBJECT)`` (`nn/conf/layers/Layer.java:47-68`).
- ``coefficients.bin`` — the single flattened parameter row vector written
  with ``Nd4j.write`` (two ND4J DataBuffers: shape-info then data, each as
  ``writeUTF(allocationMode), writeInt(length), writeUTF(dtype), elements``
  big-endian).
- ``updaterState.bin`` — the flat updater state view, same array codec.

Per-layer flat layouts (the param-initializer ordering):
- Dense/Output/Embedding (`nn/params/DefaultParamInitializer.java:60-88`):
  [W ('f'-order, (nIn, nOut)), b (nOut)].
- Convolution (`nn/params/ConvolutionParamInitializer.java:76-100`):
  [b (nOut), W ('c'-order, (nOut, nIn, kH, kW))] — note bias FIRST and 'c'
  order, unlike everything else.
- BatchNormalization (`nn/params/BatchNormalizationParamInitializer.java:56-80`):
  [gamma, beta, mean, var] (gamma/beta absent when lockGammaBeta).
- GravesLSTM (`nn/params/GravesLSTMParamInitializer.java:57-120`):
  [W_in ('f', (nIn, 4H)), RW ('f', (H, 4H+3)), b (4H)]. Gate column blocks
  are [candidate, forget, output, input] (`nn/layers/recurrent/LSTMHelpers.java:
  180-250` — their "inputActivations" block 0 is the tanh candidate and
  their "input modulation gate" block 3 is the sigmoid input gate); the
  three extra RW columns are the peepholes wFF (forget, col 4H), wOO
  (output, col 4H+1), wGG (input, col 4H+2). This framework's gate order is
  [input, forget, candidate, output] with peepholes P=[input, forget,
  output] (nn/layers/recurrent.py), so columns are permuted on the way in.

This is an interop adapter, not a port: imported configs become this
framework's dataclass configs and imported params land in the pytree param
store, after which everything runs the TPU-native jit path.
"""

from __future__ import annotations

import dataclasses
import io
import json
import struct
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# --------------------------------------------------------------------------
# ND4J binary array codec (Nd4j.write / Nd4j.read wire format)
# --------------------------------------------------------------------------

_DTYPES = {
    "FLOAT": (">f4", 4),
    "DOUBLE": (">f8", 8),
    "INT": (">i4", 4),
    "LONG": (">i8", 8),
    "HALF": (">f2", 2),
}


def _read_java_utf(f) -> str:
    (n,) = struct.unpack(">H", f.read(2))
    return f.read(n).decode("utf-8")


def _write_java_utf(f, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack(">H", len(b)))
    f.write(b)


def _read_buffer(f) -> Tuple[str, np.ndarray]:
    """One ND4J DataBuffer: (allocation mode UTF, length int32, dtype UTF,
    big-endian elements)."""
    alloc = _read_java_utf(f)
    (length,) = struct.unpack(">i", f.read(4))
    dtype = _read_java_utf(f)
    if dtype not in _DTYPES:
        raise ValueError(f"unsupported ND4J buffer dtype {dtype!r}")
    fmt, size = _DTYPES[dtype]
    raw = f.read(length * size)
    if len(raw) != length * size:
        raise ValueError(
            f"truncated ND4J buffer: header promises {length} {dtype} "
            f"elements ({length * size} bytes) but only {len(raw)} bytes "
            "remain — corrupt or cut-off coefficients/updater stream")
    data = np.frombuffer(raw, dtype=fmt, count=length)
    return alloc, data


def _write_buffer(f, data: np.ndarray, dtype: str, alloc: str = "DIRECT"):
    fmt, _ = _DTYPES[dtype]
    _write_java_utf(f, alloc)
    f.write(struct.pack(">i", data.size))
    _write_java_utf(f, dtype)
    f.write(np.ascontiguousarray(data, dtype=fmt).tobytes())


def read_nd4j_array(f) -> np.ndarray:
    """Parse one Nd4j.write'd array: shape-info buffer then data buffer.

    Shape info is an int buffer [rank, *shape, *stride, offset,
    elementWiseStride, order-char] of length 2*rank + 4.
    """
    if isinstance(f, (bytes, bytearray)):
        f = io.BytesIO(f)
    _, shape_info = _read_buffer(f)
    rank = int(shape_info[0])
    if len(shape_info) < 2 * rank + 4:
        raise ValueError(
            f"malformed ND4J shape info: rank {rank}, len {len(shape_info)}")
    shape = tuple(int(s) for s in shape_info[1:1 + rank])
    order = chr(int(shape_info[2 * rank + 3]))
    _, data = _read_buffer(f)
    arr = np.asarray(data)
    if int(np.prod(shape)) != arr.size:
        raise ValueError(f"shape {shape} does not match {arr.size} elements")
    return arr.reshape(shape, order="F" if order == "f" else "C").astype(
        arr.dtype.newbyteorder("="))


def write_nd4j_array(f, arr: np.ndarray, dtype: str = "FLOAT") -> None:
    """Write `arr` in Nd4j.write format ('c' order, contiguous)."""
    arr = np.asarray(arr)
    rank = arr.ndim
    strides = []
    acc = 1
    for s in reversed(arr.shape):   # 'c'-order element strides
        strides.insert(0, acc)
        acc *= s
    shape_info = np.asarray(
        [rank, *arr.shape, *strides, 0, 1, ord("c")], dtype=">i4")
    _write_buffer(f, shape_info, "INT")
    _write_buffer(f, arr.reshape(-1, order="C"), dtype)


# --------------------------------------------------------------------------
# Enum / name translation
# --------------------------------------------------------------------------

# IActivation impl class suffix (or legacy enum string) -> framework name.
_ACT_MAP = {
    "relu": "relu", "leakyrelu": "leakyrelu", "lrelu": "leakyrelu",
    "tanh": "tanh", "sigmoid": "sigmoid", "softmax": "softmax",
    "identity": "identity", "linear": "identity", "elu": "elu",
    "selu": "selu", "softplus": "softplus", "softsign": "softsign",
    "hardtanh": "hardtanh", "hardsigmoid": "hardsigmoid", "cube": "cube",
    "rationaltanh": "rationaltanh", "rectifiedtanh": "rectifiedtanh",
    "swish": "swish", "gelu": "gelu",
}

# LossFn impl class suffix / legacy LossFunctions enum -> framework name.
_LOSS_MAP = {
    "mcxent": "mcxent", "negativeloglikelihood": "negativeloglikelihood",
    "mse": "mse", "l2": "l2", "l1": "l1", "xent": "xent",
    "binaryxent": "xent", "kldivergence": "kl_divergence", "kld": "kl_divergence",
    "mae": "mae", "meanabsoluteerror": "mae",
    "meansquaredlogarithmicerror": "msle", "msle": "msle",
    "meanabsolutepercentageerror": "mape", "mape": "mape",
    "hinge": "hinge", "squaredhinge": "squared_hinge",
    "poisson": "poisson", "cosineproximity": "cosine_proximity",
    "reconstructioncrossentropy": "reconstruction_crossentropy",
    "squaredloss": "squared_loss", "wasserstein": "wasserstein",
}

_LOSS_TO_DL4J = {
    "mcxent": "MCXENT", "negativeloglikelihood": "NEGATIVELOGLIKELIHOOD",
    "mse": "MSE", "l2": "L2", "l1": "L1", "xent": "XENT",
    "kl_divergence": "KL_DIVERGENCE", "mae": "MEAN_ABSOLUTE_ERROR",
    "msle": "MEAN_SQUARED_LOGARITHMIC_ERROR",
    "mape": "MEAN_ABSOLUTE_PERCENTAGE_ERROR", "hinge": "HINGE",
    "squared_hinge": "SQUARED_HINGE", "poisson": "POISSON",
    "cosine_proximity": "COSINE_PROXIMITY",
    "reconstruction_crossentropy": "RECONSTRUCTION_CROSSENTROPY",
    "squared_loss": "SQUARED_LOSS", "wasserstein": "WASSERSTEIN",
}


def _act_from_dl4j(layer_json: Dict[str, Any]) -> Optional[str]:
    fn = layer_json.get("activationFn")
    if isinstance(fn, dict):
        cls = fn.get("@class", "")
        name = cls.rsplit(".", 1)[-1]        # e.g. ActivationReLU
        key = name.replace("Activation", "").replace("H", "h").lower()
        key = key.replace("-", "")
        hit = _ACT_MAP.get(key) or _ACT_MAP.get(
            name.replace("Activation", "").lower())
        if hit:
            return hit
        raise ValueError(f"unmapped DL4J activation {cls!r}")
    legacy = layer_json.get("activationFunction") or layer_json.get(
        "activation")
    if isinstance(legacy, str):
        key = legacy.replace("_", "").lower()
        if key in _ACT_MAP:
            return _ACT_MAP[key]
        raise ValueError(f"unmapped DL4J activation {legacy!r}")
    return None


def _act_to_dl4j(name: Optional[str]) -> Dict[str, Any]:
    cls = {
        "relu": "ActivationReLU", "leakyrelu": "ActivationLReLU",
        "tanh": "ActivationTanH", "sigmoid": "ActivationSigmoid",
        "softmax": "ActivationSoftmax", "identity": "ActivationIdentity",
        "elu": "ActivationELU", "selu": "ActivationSELU",
        "softplus": "ActivationSoftPlus", "softsign": "ActivationSoftSign",
        "hardtanh": "ActivationHardTanH",
        "hardsigmoid": "ActivationHardSigmoid", "cube": "ActivationCube",
        "rationaltanh": "ActivationRationalTanh",
        "rectifiedtanh": "ActivationRectifiedTanh",
    }.get(name or "identity", "ActivationIdentity")
    return {"@class": f"org.nd4j.linalg.activations.impl.{cls}"}


def _loss_from_dl4j(layer_json: Dict[str, Any]) -> str:
    fn = layer_json.get("lossFn")
    if isinstance(fn, dict):
        cls = fn.get("@class", "").rsplit(".", 1)[-1]   # e.g. LossMCXENT
        key = cls.replace("Loss", "", 1).replace("_", "").lower()
        if key in _LOSS_MAP:
            return _LOSS_MAP[key]
        raise ValueError(f"unmapped DL4J loss {cls!r}")
    legacy = layer_json.get("lossFunction")
    if isinstance(legacy, str):
        key = legacy.replace("_", "").lower()
        if key in _LOSS_MAP:
            return _LOSS_MAP[key]
        raise ValueError(f"unmapped DL4J loss {legacy!r}")
    return "mcxent"


def _weight_init_from_dl4j(name: Optional[str]) -> Optional[str]:
    return name.lower() if isinstance(name, str) else None


def _get(d: Dict[str, Any], *keys, default=None):
    for k in keys:
        if k in d and d[k] is not None:
            return d[k]
    return default


# --------------------------------------------------------------------------
# Layer config translation
# --------------------------------------------------------------------------

def _layer_from_dl4j(type_name: str, d: Dict[str, Any]):
    """One DL4J layer JSON (already unwrapped from its type object) ->
    framework layer dataclass."""
    from deeplearning4j_tpu.nn.layers import (
        ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
        DropoutLayer, EmbeddingLayer, GlobalPoolingLayer, GravesLSTM,
        LocalResponseNormalization, LossLayer, LSTM, OutputLayer,
        RnnOutputLayer, SubsamplingLayer, ZeroPaddingLayer,
    )

    common = dict(
        name=d.get("layerName"),
        activation=_act_from_dl4j(d),
        weight_init=_weight_init_from_dl4j(d.get("weightInit")),
        l1=d.get("l1") or None,
        l2=d.get("l2") or None,
        dropout=d.get("dropOut") or None,
        bias_init=d.get("biasInit") or None,
    )
    nin = _get(d, "nin", "nIn", "NIn")
    nout = _get(d, "nout", "nOut", "NOut")

    if type_name == "dense":
        return DenseLayer(n_in=nin, n_out=nout, **common)
    if type_name == "output":
        return OutputLayer(n_in=nin, n_out=nout, loss=_loss_from_dl4j(d),
                           **common)
    if type_name == "rnnoutput":
        return RnnOutputLayer(n_in=nin, n_out=nout, loss=_loss_from_dl4j(d),
                              **common)
    if type_name == "loss":
        return LossLayer(loss=_loss_from_dl4j(d), **common)
    if type_name == "embedding":
        return EmbeddingLayer(n_in=nin, n_out=nout, **common)
    if type_name == "convolution":
        return ConvolutionLayer(
            n_in=nin, n_out=nout,
            kernel=tuple(d.get("kernelSize", (3, 3))),
            stride=tuple(d.get("stride", (1, 1))),
            padding=tuple(d.get("padding", (0, 0))),
            convolution_mode=(d.get("convolutionMode") or "truncate").lower(),
            **common)
    if type_name == "subsampling":
        return SubsamplingLayer(
            pooling=(d.get("poolingType") or "MAX").lower(),
            kernel=tuple(d.get("kernelSize", (2, 2))),
            stride=tuple(d.get("stride", (2, 2))),
            padding=tuple(d.get("padding", (0, 0))),
            convolution_mode=(d.get("convolutionMode") or "truncate").lower(),
            **common)
    if type_name == "batchNormalization":
        return BatchNormalization(
            n_out=nout or nin,
            decay=d.get("decay", 0.9), eps=d.get("eps", 1e-5),
            lock_gamma_beta=bool(d.get("lockGammaBeta", False)),
            **common)
    if type_name == "localResponseNormalization":
        return LocalResponseNormalization(
            n=d.get("n", 5), k=d.get("k", 2.0),
            alpha=d.get("alpha", 1e-4), beta=d.get("beta", 0.75), **common)
    if type_name in ("gravesLSTM", "LSTM"):
        cls = GravesLSTM if type_name == "gravesLSTM" else LSTM
        fb = d.get("forgetGateBiasInit", 1.0)
        return cls(n_in=nin, n_out=nout, forget_gate_bias_init=fb, **common)
    if type_name == "activation":
        return ActivationLayer(**common)
    if type_name == "dropout":
        return DropoutLayer(**common)
    if type_name == "GlobalPooling":
        return GlobalPoolingLayer(
            pooling=(d.get("poolingType") or "MAX").lower(), **common)
    if type_name == "zeroPadding":
        pad = d.get("padding", (0, 0))
        return ZeroPaddingLayer(pad=tuple(pad), **common)
    raise ValueError(f"unsupported DL4J layer type {type_name!r} "
                     f"(supported: dense/output/rnnoutput/loss/embedding/"
                     f"convolution/subsampling/batchNormalization/LRN/"
                     f"gravesLSTM/LSTM/activation/dropout/GlobalPooling/"
                     f"zeroPadding)")


def _layer_to_dl4j(layer) -> Tuple[str, Dict[str, Any]]:
    from deeplearning4j_tpu.nn.layers import (
        ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
        DropoutLayer, EmbeddingLayer, GlobalPoolingLayer, GravesLSTM,
        LocalResponseNormalization, LossLayer, LSTM, OutputLayer,
        RnnOutputLayer, SubsamplingLayer, ZeroPaddingLayer,
    )

    d: Dict[str, Any] = {
        "layerName": layer.name,
        "activationFn": _act_to_dl4j(layer.activation),
        "weightInit": (layer.weight_init or "xavier").upper(),
        "biasInit": layer.bias_init or 0.0,
        "l1": layer.l1 or 0.0, "l2": layer.l2 or 0.0,
        "dropOut": layer.dropout or 0.0,
    }

    def ff(extra=None):
        d.update({"nin": layer.n_in, "nout": layer.n_out})
        d.update(extra or {})
        return d

    if isinstance(layer, RnnOutputLayer):
        return "rnnoutput", ff({"lossFn": _loss_ref(layer.loss)})
    if isinstance(layer, OutputLayer):
        return "output", ff({"lossFn": _loss_ref(layer.loss)})
    if isinstance(layer, LossLayer):
        d["lossFn"] = _loss_ref(layer.loss)
        return "loss", d
    if isinstance(layer, EmbeddingLayer):
        return "embedding", ff()
    if isinstance(layer, ConvolutionLayer) and type(layer).__name__ == "ConvolutionLayer":
        return "convolution", ff({
            "kernelSize": list(_pair(layer.kernel)),
            "stride": list(_pair(layer.stride)),
            "padding": list(_pair(layer.padding)),
            "convolutionMode": (layer.convolution_mode or "truncate").title(),
        })
    if isinstance(layer, SubsamplingLayer):
        d.update({
            "poolingType": layer.pooling.upper(),
            "kernelSize": list(_pair(layer.kernel)),
            "stride": list(_pair(layer.stride)),
            "padding": list(_pair(layer.padding)),
        })
        return "subsampling", d
    if isinstance(layer, BatchNormalization):
        d.update({"nin": layer.n_out, "nout": layer.n_out,
                  "decay": layer.decay, "eps": layer.eps,
                  "lockGammaBeta": layer.lock_gamma_beta})
        return "batchNormalization", d
    if isinstance(layer, LocalResponseNormalization):
        d.update({"n": layer.n, "k": layer.k, "alpha": layer.alpha,
                  "beta": layer.beta})
        return "localResponseNormalization", d
    if isinstance(layer, GravesLSTM):
        return "gravesLSTM", ff(
            {"forgetGateBiasInit": layer.forget_gate_bias_init})
    if isinstance(layer, LSTM):
        return "LSTM", ff(
            {"forgetGateBiasInit": layer.forget_gate_bias_init})
    if isinstance(layer, ActivationLayer):
        return "activation", d
    if isinstance(layer, DropoutLayer):
        return "dropout", d
    if isinstance(layer, GlobalPoolingLayer):
        d["poolingType"] = layer.pooling.upper()
        return "GlobalPooling", d
    if isinstance(layer, ZeroPaddingLayer):
        d["padding"] = list(layer.pad) if isinstance(
            layer.pad, (tuple, list)) else [layer.pad, layer.pad]
        return "zeroPadding", d
    if isinstance(layer, DenseLayer):
        return "dense", ff()
    raise ValueError(
        f"layer type {type(layer).__name__} has no DL4J JSON mapping")


_LOSS_CLASS = {
    # exact DL4J impl class names (org.nd4j.linalg.lossfunctions.impl.*)
    "mcxent": "LossMCXENT", "negativeloglikelihood": "LossNegativeLogLikelihood",
    "mse": "LossMSE", "l1": "LossL1", "l2": "LossL2",
    "xent": "LossBinaryXENT", "kl_divergence": "LossKLD",
    "mae": "LossMAE", "msle": "LossMSLE", "mape": "LossMAPE",
    "hinge": "LossHinge", "squared_hinge": "LossSquaredHinge",
    "poisson": "LossPoisson", "cosine_proximity": "LossCosineProximity",
}


def _loss_ref(name) -> Dict[str, Any]:
    cls = _LOSS_CLASS.get(str(name), "LossMCXENT")
    return {"@class": f"org.nd4j.linalg.lossfunctions.impl.{cls}"}


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


# --------------------------------------------------------------------------
# Flat parameter codec (param-initializer ordering)
# --------------------------------------------------------------------------

def _lstm_col_perm(h: int, to_framework: bool) -> np.ndarray:
    """Column permutation between DL4J gate blocks [cand, f, o, i] and this
    framework's [i, f, cand, o] (see module docstring)."""
    blocks_dl4j_to_fw = [3, 1, 0, 2]   # fw block j comes from dl4j block[j]
    idx = np.arange(4 * h).reshape(4, h)
    if to_framework:
        return np.concatenate([idx[b] for b in blocks_dl4j_to_fw])
    # inverse: dl4j block j comes from fw block inv[j]
    inv = [blocks_dl4j_to_fw.index(j) for j in range(4)]
    return np.concatenate([idx[b] for b in inv])


def _params_from_flat(layer, flat: np.ndarray) -> Tuple[
        Dict[str, np.ndarray], Dict[str, np.ndarray], int]:
    """Consume `layer`'s DL4J flat segment; return (params, state, used)."""
    from deeplearning4j_tpu.nn.layers import (
        BatchNormalization, ConvolutionLayer, LSTM,
    )
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, EmbeddingLayer

    if isinstance(layer, ConvolutionLayer) and hasattr(layer, "kernel") \
            and type(layer).__name__ == "ConvolutionLayer":
        kh, kw = _pair(layer.kernel)
        nin, nout = layer.n_in, layer.n_out
        nb, nw = nout, nout * nin * kh * kw
        b = flat[:nb]
        w = flat[nb:nb + nw].reshape((nout, nin, kh, kw), order="C")
        return ({"W": np.transpose(w, (2, 3, 1, 0)).copy(), "b": b.copy()},
                {}, nb + nw)
    if isinstance(layer, BatchNormalization):
        n = layer.n_out
        used = 0
        params: Dict[str, np.ndarray] = {}
        if not layer.lock_gamma_beta:
            params = {"gamma": flat[:n].copy(), "beta": flat[n:2 * n].copy()}
            used = 2 * n
        state = {"mean": flat[used:used + n].copy(),
                 "var": flat[used + n:used + 2 * n].copy()}
        return params, state, used + 2 * n
    if isinstance(layer, LSTM):          # covers GravesLSTM
        h, nin = layer.n_out, layer.n_in
        peep = layer.peephole
        rw_cols = 4 * h + (3 if peep else 0)
        n_w, n_rw, n_b = nin * 4 * h, h * rw_cols, 4 * h
        perm = _lstm_col_perm(h, to_framework=True)
        w = flat[:n_w].reshape((nin, 4 * h), order="F")[:, perm]
        rw_full = flat[n_w:n_w + n_rw].reshape((h, rw_cols), order="F")
        rw = rw_full[:, :4 * h][:, perm]
        b = flat[n_w + n_rw:n_w + n_rw + n_b][perm]
        params = {"W": w.copy(), "RW": rw.copy(), "b": b.copy()}
        if peep:
            # cols: 4H=wFF(forget), 4H+1=wOO(output), 4H+2=wGG(input)
            params["P"] = np.stack([
                rw_full[:, 4 * h + 2],   # input peephole
                rw_full[:, 4 * h],       # forget peephole
                rw_full[:, 4 * h + 1],   # output peephole
            ]).copy()
        return params, {}, n_w + n_rw + n_b
    if isinstance(layer, (DenseLayer, EmbeddingLayer)):  # + Output subclasses
        nin, nout = layer.n_in, layer.n_out
        nw = nin * nout
        w = flat[:nw].reshape((nin, nout), order="F")
        params = {"W": w.copy()}
        used = nw
        if getattr(layer, "has_bias", True):
            params["b"] = flat[nw:nw + nout].copy()
            used += nout
        return params, {}, used
    return {}, {}, 0    # parameterless layer


def _params_to_flat(layer, params: Dict[str, Any],
                    state: Dict[str, Any]) -> np.ndarray:
    from deeplearning4j_tpu.nn.layers import (
        BatchNormalization, ConvolutionLayer, LSTM,
    )
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, EmbeddingLayer

    def f32(a):
        return np.asarray(a, dtype=np.float32)

    if isinstance(layer, ConvolutionLayer) \
            and type(layer).__name__ == "ConvolutionLayer":
        w = np.transpose(f32(params["W"]), (3, 2, 0, 1))  # HWIO -> OIHW
        # DL4J convs ALWAYS carry a bias (this 0.8-era reference predates
        # the hasBias option entirely — no such field exists in its conf
        # package); a has_bias=False conv (conv+BN stacks like ResNet)
        # exports a zero bias — numerically identical
        b = (f32(params["b"]) if "b" in params
             else np.zeros((layer.n_out,), np.float32))
        return np.concatenate([b.ravel(), w.reshape(-1, order="C")])
    if isinstance(layer, BatchNormalization):
        parts = []
        if not layer.lock_gamma_beta:
            parts += [f32(params["gamma"]).ravel(), f32(params["beta"]).ravel()]
        parts += [f32(state["mean"]).ravel(), f32(state["var"]).ravel()]
        return np.concatenate(parts)
    if isinstance(layer, LSTM):
        h = layer.n_out
        perm = _lstm_col_perm(h, to_framework=False)
        w = f32(params["W"])[:, perm]
        rw = f32(params["RW"])[:, perm]
        b = f32(params["b"])[perm]
        if layer.peephole:
            p = f32(params["P"])
            extra = np.stack([p[1], p[2], p[0]], axis=1)  # wFF, wOO, wGG
            rw = np.concatenate([rw, extra], axis=1)
        return np.concatenate([w.reshape(-1, order="F"),
                               rw.reshape(-1, order="F"), b.ravel()])
    if isinstance(layer, (DenseLayer, EmbeddingLayer)):
        parts = [f32(params["W"]).reshape(-1, order="F")]
        # same asymmetry guard as the conv branch: the config JSON never
        # carries hasBias, so the importer always reads a bias — export a
        # zero one for has_bias=False layers to keep offsets aligned
        parts.append(f32(params["b"]).ravel() if "b" in params
                     else np.zeros((layer.n_out,), np.float32))
        return np.concatenate(parts)
    return np.zeros((0,), np.float32)


# --------------------------------------------------------------------------
# Zip container import / export
# --------------------------------------------------------------------------

def import_dl4j_model(path, *, input_type=None, updater=None, dtype=None):
    """Load a DL4J MultiLayerNetwork zip (configuration.json +
    coefficients.bin [+ updaterState.bin]) into a MultiLayerNetwork.

    input_type: optional InputType for shape-dependent nets (CNNs); when
    omitted the layer nIn/nOut fields from the config are used as-is.
    The raw updater-state vector (if present) is attached as
    ``net.dl4j_updater_state`` — DL4J updater blocks don't map 1:1 onto
    this framework's per-layer optimizer pytrees, so remapping is left to
    the caller.
    """
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.optim.updaters import Sgd

    with zipfile.ZipFile(path) as zf:
        conf_json = json.loads(zf.read("configuration.json"))
        coeffs = read_nd4j_array(zf.read("coefficients.bin"))
        upd_raw = None
        for entry in ("updaterState.bin", "updater.bin"):
            if entry in zf.namelist():
                try:
                    upd_raw = read_nd4j_array(zf.read(entry))
                except ValueError as e:
                    # old updater.bin is Java serialization — silent skip
                    # is correct; a corrupt/truncated updaterState.bin
                    # must be VISIBLE (params still import fine)
                    if entry == "updaterState.bin":
                        import warnings

                        warnings.warn(
                            f"ignoring unreadable {entry}: {e}")
                break

    if "vertices" in conf_json:
        return _import_dl4j_graph(conf_json, coeffs, upd_raw,
                                  updater=updater, dtype=dtype,
                                  input_type=input_type)

    layers = []
    for conf in conf_json.get("confs", []):
        wrapper = conf["layer"]
        (type_name, layer_json), = wrapper.items()
        layers.append(_layer_from_dl4j(type_name, layer_json))

    builder = NeuralNetConfiguration.builder()
    if updater is not None:
        builder = builder.updater(updater)
    else:
        builder = builder.updater(Sgd(0.1))
    lb = builder.list(*layers)
    if input_type is not None:
        lb = lb.set_input_type(input_type)
    tf = conf_json.get("tbpttFwdLength") or 0
    tb = conf_json.get("tbpttBackLength") or 0
    if tf:
        lb = lb.tbptt(tf, tb or tf)
    mlconf = lb.build()
    if dtype is not None:
        mlconf = dataclasses.replace(mlconf, dtype=dtype)
    net = MultiLayerNetwork(mlconf).init()

    flat = np.asarray(coeffs, np.float32).ravel(order="C")
    off = 0
    for layer in net.layers:
        off = _assign_flat_segment(net, layer.name, layer, flat, off)
    if off != flat.size:
        raise ValueError(
            f"coefficients.bin has {flat.size} params, config consumes {off}")
    net.dl4j_updater_state = upd_raw
    return net


def _assign_flat_segment(net, name, layer, flat, off):
    """Slice one layer's DL4J flat segment into net.params_tree/state_tree
    with error context (shared by the MLN and graph importers)."""
    import jax.numpy as jnp

    try:
        p, s, used = _params_from_flat(layer, flat[off:])
    except ValueError as e:
        raise ValueError(
            f"coefficients.bin too short for layer {name!r} "
            f"({type(layer).__name__}) at offset {off}: {e}") from None
    if p:
        net.params_tree[name] = {
            k: jnp.asarray(v, net.params_tree[name][k].dtype)
            if k in net.params_tree[name] else jnp.asarray(v)
            for k, v in p.items()
        }
    if s:
        net.state_tree[name] = {k: jnp.asarray(v) for k, v in s.items()}
    return off + used


def _dl4j_topo_order(network_inputs, vertex_names, vertex_inputs):
    """Reproduce DL4J's topological order (`ComputationGraph.
    topologicalSortOrder():1082` — Kahn's with a FIFO queue over integer
    vertex ids: network inputs first, then vertices in JSON insertion
    order; successor sets iterate in ascending id order, matching
    HashSet<Integer> behavior for small ints). Flat params are sliced in
    THIS order (`init():416-430`), so it defines coefficient layout."""
    names = list(network_inputs) + list(vertex_names)
    idx = {n: i for i, n in enumerate(names)}
    preds = {i: set() for i in range(len(names))}
    succs = {i: set() for i in range(len(names))}
    for name in vertex_names:
        for src in vertex_inputs.get(name, ()):
            preds[idx[name]].add(idx[src])
            succs[idx[src]].add(idx[name])
    queue = [i for i in range(len(names)) if not preds[i]]
    order = []
    while queue:
        nxt = queue.pop(0)
        order.append(nxt)
        for v in sorted(succs[nxt]):
            preds[v].discard(nxt)
            if not preds[v]:
                queue.append(v)
    if len(order) != len(names):
        raise ValueError("cycle in ComputationGraph configuration")
    return [names[i] for i in order]


def _vertex_from_dl4j(type_name: str, body: Dict[str, Any]):
    """DL4J graph-vertex JSON (wrapper-object unwrapped) → (our vertex,
    layer-or-None). Reference: `nn/conf/graph/*` @JsonSubTypes names."""
    from deeplearning4j_tpu.nn import graph as G

    if type_name == "LayerVertex":
        layer_wrapper = body["layerConf"]["layer"]
        (ltype, ljson), = layer_wrapper.items()
        layer = _layer_from_dl4j(ltype, ljson)
        pre = None
        pp = body.get("preProcessor")
        if pp:
            pre = _preprocessor_from_dl4j(pp)
        return G.LayerVertex(layer=layer, preprocessor=pre), layer
    if type_name == "MergeVertex":
        return G.MergeVertex(), None
    if type_name == "ElementWiseVertex":
        return G.ElementWiseVertex(op=str(body.get("op", "Add")).lower()), None
    if type_name == "SubsetVertex":
        return G.SubsetVertex(from_=body.get("from", 0),
                              to=body.get("to", 0)), None
    if type_name == "ScaleVertex":
        return G.ScaleVertex(scale=body.get("scaleFactor", 1.0)), None
    if type_name == "StackVertex":
        return G.StackVertex(), None
    if type_name == "UnstackVertex":
        return G.UnstackVertex(from_=body.get("from", 0),
                               stack_size=body.get("stackSize", 1)), None
    if type_name == "L2NormalizeVertex":
        return G.L2NormalizeVertex(), None
    if type_name == "L2Vertex":
        return G.L2Vertex(), None
    if type_name == "PoolHelperVertex":
        return G.PoolHelperVertex(), None
    if type_name == "LastTimeStepVertex":
        return G.LastTimeStepVertex(
            mask_input=body.get("maskArrayInputName")), None
    if type_name == "DuplicateToTimeSeriesVertex":
        # our vertex carries STATIC timesteps (XLA needs static shapes);
        # DL4J reads T at runtime from the named input. Our exports write
        # a "timesteps" key; genuine DL4J zips don't have one, and
        # guessing would silently broadcast to the wrong length.
        t = body.get("timesteps")
        if t is None:
            raise ValueError(
                "DuplicateToTimeSeriesVertex in a DL4J zip carries no "
                "static timestep count (DL4J resolves it at runtime from "
                f"input {body.get('inputName')!r}); rebuild this vertex "
                "with an explicit length after import")
        return G.DuplicateToTimeSeriesVertex(timesteps=int(t)), None
    raise ValueError(f"unsupported DL4J graph vertex type {type_name!r}")


_PP_CLASS_BASE = "org.deeplearning4j.nn.conf.preprocessor."


def _preprocessor_to_dl4j(pre) -> Dict[str, Any]:
    from deeplearning4j_tpu.nn import preprocessors as P

    if isinstance(pre, P.CnnToFeedForward):
        return {"@class": _PP_CLASS_BASE + "CnnToFeedForwardPreProcessor",
                "inputHeight": pre.height, "inputWidth": pre.width,
                "numChannels": pre.channels}
    if isinstance(pre, P.FeedForwardToCnn):
        return {"@class": _PP_CLASS_BASE + "FeedForwardToCnnPreProcessor",
                "inputHeight": pre.height, "inputWidth": pre.width,
                "numChannels": pre.channels}
    if isinstance(pre, P.FeedForwardToRnn):
        return {"@class": _PP_CLASS_BASE + "FeedForwardToRnnPreProcessor"}
    if isinstance(pre, P.RnnToFeedForward):
        return {"@class": _PP_CLASS_BASE + "RnnToFeedForwardPreProcessor"}
    if isinstance(pre, P.RnnToCnn):
        return {"@class": _PP_CLASS_BASE + "RnnToCnnPreProcessor",
                "inputHeight": pre.height, "inputWidth": pre.width,
                "numChannels": pre.channels}
    if isinstance(pre, P.CnnToRnn):
        return {"@class": _PP_CLASS_BASE + "CnnToRnnPreProcessor"}
    raise ValueError(
        f"preprocessor {type(pre).__name__} has no DL4J JSON mapping")


def _preprocessor_from_dl4j(pp: Dict[str, Any]):
    from deeplearning4j_tpu.nn import preprocessors as P

    cls = pp.get("@class", "")
    if "CnnToFeedForward" in cls:
        return P.CnnToFeedForward()
    if "FeedForwardToCnn" in cls:
        return P.FeedForwardToCnn(
            height=pp.get("inputHeight"), width=pp.get("inputWidth"),
            channels=pp.get("numChannels"))
    if "FeedForwardToRnn" in cls:
        return P.FeedForwardToRnn()
    if "RnnToFeedForward" in cls:
        return P.RnnToFeedForward()
    if "RnnToCnn" in cls:
        return P.RnnToCnn(height=pp.get("inputHeight"),
                          width=pp.get("inputWidth"),
                          channels=pp.get("numChannels"))
    if "CnnToRnn" in cls:
        return P.CnnToRnn()
    raise ValueError(f"unsupported DL4J preprocessor {cls!r}")


def _import_dl4j_graph(conf_json, coeffs, upd_raw, *, updater=None,
                       dtype=None, input_type=None):
    """DL4J ComputationGraph zip → ComputationGraph. Reference:
    `nn/conf/ComputationGraphConfiguration.java` (vertices/vertexInputs/
    networkInputs/networkOutputs JSON) + the topological flat-param
    layout of `ComputationGraph.init():382-443`."""
    from deeplearning4j_tpu.models import ComputationGraph
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.optim.updaters import Sgd

    vertices_json = conf_json["vertices"]
    vertex_inputs = {k: list(v)
                     for k, v in conf_json.get("vertexInputs", {}).items()}
    net_inputs = list(conf_json.get("networkInputs", []))
    net_outputs = list(conf_json.get("networkOutputs", []))

    g = (NeuralNetConfiguration.builder()
         .updater(updater if updater is not None else Sgd(0.1))
         .graph_builder())
    g.add_inputs(*net_inputs)
    if input_type is not None:
        # single InputType (applied to the sole/first input) or a dict
        # name → InputType for multi-input graphs
        if isinstance(input_type, dict):
            g.set_input_types(*[input_type[n] for n in net_inputs])
        else:
            g.set_input_types(input_type)
    layer_for_vertex: Dict[str, Any] = {}
    for name, wrapper in vertices_json.items():
        (type_name, body), = wrapper.items()
        vertex, layer = _vertex_from_dl4j(type_name, body)
        if layer is not None:
            layer_for_vertex[name] = layer
            g.add_layer(name, layer, *vertex_inputs.get(name, ()),
                        preprocessor=vertex.preprocessor)
        else:
            g.add_vertex(name, vertex, *vertex_inputs.get(name, ()))
    g.set_outputs(*net_outputs)
    conf = g.build()
    if dtype is not None:
        conf = dataclasses.replace(conf, dtype=dtype)
    net = ComputationGraph(conf).init()

    flat = np.asarray(coeffs, np.float32).ravel(order="C")
    off = 0
    for name in _dl4j_topo_order(net_inputs, vertices_json.keys(),
                                 vertex_inputs):
        if name not in layer_for_vertex:
            continue
        # our LayerVertex stores the (possibly n_in-inferred) layer copy
        built = conf.vertices[name].layer
        off = _assign_flat_segment(net, name, built, flat, off)
    if off != flat.size:
        raise ValueError(
            f"coefficients.bin has {flat.size} params, graph config "
            f"consumes {off}")
    net.dl4j_updater_state = upd_raw
    return net


def _vertex_to_dl4j(vertex) -> Tuple[str, Dict[str, Any]]:
    from deeplearning4j_tpu.nn import graph as G

    if isinstance(vertex, G.LayerVertex):
        ltype, ljson = _layer_to_dl4j(vertex.layer)
        body: Dict[str, Any] = {"layerConf": {"layer": {ltype: ljson}}}
        if vertex.preprocessor is not None:
            body["preProcessor"] = _preprocessor_to_dl4j(vertex.preprocessor)
        return "LayerVertex", body
    if isinstance(vertex, G.MergeVertex):
        return "MergeVertex", {}
    if isinstance(vertex, G.ElementWiseVertex):
        # canonical DL4J Op enum names (ElementWiseVertex.Op), not .title()
        ops = {"add": "Add", "sub": "Subtract", "subtract": "Subtract",
               "mul": "Product", "product": "Product",
               "avg": "Average", "average": "Average", "max": "Max"}
        return "ElementWiseVertex", {"op": ops.get(vertex.op.lower(),
                                                   vertex.op.title())}
    if isinstance(vertex, G.SubsetVertex):
        return "SubsetVertex", {"from": vertex.from_, "to": vertex.to}
    if isinstance(vertex, G.ScaleVertex):
        return "ScaleVertex", {"scaleFactor": vertex.scale}
    if isinstance(vertex, G.StackVertex):
        return "StackVertex", {}
    if isinstance(vertex, G.UnstackVertex):
        return "UnstackVertex", {"from": vertex.from_,
                                 "stackSize": vertex.stack_size}
    if isinstance(vertex, G.L2NormalizeVertex):
        return "L2NormalizeVertex", {}
    if isinstance(vertex, G.L2Vertex):
        return "L2Vertex", {}
    if isinstance(vertex, G.PoolHelperVertex):
        return "PoolHelperVertex", {}
    if isinstance(vertex, G.LastTimeStepVertex):
        return "LastTimeStepVertex", {"maskArrayInputName": vertex.mask_input}
    if isinstance(vertex, G.DuplicateToTimeSeriesVertex):
        # "timesteps" is our static-shape extension (see import side)
        return "DuplicateToTimeSeriesVertex", {"timesteps": vertex.timesteps}
    raise ValueError(
        f"vertex type {type(vertex).__name__} has no DL4J JSON mapping")


def _export_dl4j_graph(net, path, *, save_updater: bool = False) -> None:
    """ComputationGraph → DL4J-layout zip (vertices/vertexInputs JSON +
    topologically-ordered flat coefficients, matching
    `ComputationGraph.init():416-430`)."""
    conf = net.conf
    net_inputs = list(conf.network_inputs)
    vertices_json: Dict[str, Any] = {}
    vertex_inputs: Dict[str, List[str]] = {}
    for name, v in conf.vertices.items():
        if name in net_inputs:
            continue
        type_name, body = _vertex_to_dl4j(v)
        vertices_json[name] = {type_name: body}
        vertex_inputs[name] = list(conf.vertex_inputs.get(name, ()))

    conf_json = {
        "vertices": vertices_json,
        "vertexInputs": vertex_inputs,
        "networkInputs": net_inputs,
        "networkOutputs": list(conf.network_outputs),
        "backprop": True, "pretrain": False,
    }

    segs: List[np.ndarray] = []
    from deeplearning4j_tpu.nn.graph import LayerVertex

    for name in _dl4j_topo_order(net_inputs, vertices_json.keys(),
                                 vertex_inputs):
        v = conf.vertices.get(name)
        if not isinstance(v, LayerVertex):
            continue
        segs.append(_params_to_flat(
            v.layer, net.params_tree.get(name, {}),
            net.state_tree.get(name, {})))
    flat = (np.concatenate([s for s in segs if s.size])
            if any(s.size for s in segs) else np.zeros((0,), np.float32))

    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("configuration.json", json.dumps(conf_json, indent=2))
        buf = io.BytesIO()
        write_nd4j_array(buf, flat.reshape(1, -1))
        zf.writestr("coefficients.bin", buf.getvalue())
        if save_updater:
            import jax

            leaves = jax.tree_util.tree_leaves(net.updater_state)
            state = (np.concatenate(
                [np.asarray(l, np.float32).ravel() for l in leaves])
                if leaves else np.zeros((0,), np.float32))
            buf = io.BytesIO()
            write_nd4j_array(buf, state.reshape(1, -1))
            zf.writestr("updaterState.bin", buf.getvalue())


def export_dl4j_model(net, path, *, save_updater: bool = False) -> None:
    """Write `net` as a DL4J-layout zip: the reference's ModelSerializer
    container (configuration.json + coefficients.bin). MultiLayerNetwork
    and ComputationGraph both supported.

    save_updater flattens this framework's optimizer pytree in parameter
    order — layout differs from DL4J's updater blocks (documented; primarily
    for round-trips within this framework).
    """
    if hasattr(net.conf, "vertices"):
        return _export_dl4j_graph(net, path, save_updater=save_updater)
    confs = []
    for layer in net.layers:
        type_name, layer_json = _layer_to_dl4j(layer)
        confs.append({"layer": {type_name: layer_json}})

    conf_json = {
        "backprop": True,
        "backpropType": "Standard",
        "pretrain": False,
        "confs": confs,
        "tbpttFwdLength": getattr(net.conf, "tbptt_fwd_length", 0) or 0,
        "tbpttBackLength": getattr(net.conf, "tbptt_back_length", 0) or 0,
    }

    segs: List[np.ndarray] = []
    for layer in net.layers:
        segs.append(_params_to_flat(
            layer, net.params_tree.get(layer.name, {}),
            net.state_tree.get(layer.name, {})))
    flat = (np.concatenate([s for s in segs if s.size])
            if any(s.size for s in segs) else np.zeros((0,), np.float32))

    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("configuration.json", json.dumps(conf_json, indent=2))
        buf = io.BytesIO()
        write_nd4j_array(buf, flat.reshape(1, -1))
        zf.writestr("coefficients.bin", buf.getvalue())
        if save_updater:
            import jax

            leaves = jax.tree_util.tree_leaves(net.updater_state)
            state = (np.concatenate(
                [np.asarray(l, np.float32).ravel() for l in leaves])
                if leaves else np.zeros((0,), np.float32))
            buf = io.BytesIO()
            write_nd4j_array(buf, state.reshape(1, -1))
            zf.writestr("updaterState.bin", buf.getvalue())
