"""REST k-nearest-neighbors server over a VPTree.

Reference parity: `nearestneighbor/server/NearestNeighborsServer.java:37`
(Play REST → stdlib http.server here):
  POST /knn        {"ndarray": [...], "k": 5}        → neighbors of a vector
  POST /knnindex   {"index": 3, "k": 5}              → neighbors of a row
  GET  /healthz
Responses: {"results": [{"index": i, "distance": d}, ...]}
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree


class NearestNeighborsServer:
    def __init__(self, points: np.ndarray, *, port: int = 9000,
                 metric: str = "euclidean"):
        self.points = np.asarray(points)
        self.tree = VPTree(self.points, metric=metric)
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ control
    def start(self) -> int:
        tree = self.tree
        points = self.points

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, {"status": "ok", "points": len(points)})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    k = int(req.get("k", 5))
                    if self.path == "/knn":
                        vec = np.asarray(req["ndarray"], np.float64)
                        idx, dist = tree.search(vec, k)
                    elif self.path == "/knnindex":
                        i = int(req["index"])
                        idx, dist = tree.search(points[i], k + 1)
                        pairs = [(j, d) for j, d in zip(idx, dist) if j != i]
                        idx = [j for j, _ in pairs][:k]
                        dist = [d for _, d in pairs][:k]
                    else:
                        return self._json(404, {"error": "not found"})
                    self._json(200, {"results": [
                        {"index": int(i2), "distance": float(d)}
                        for i2, d in zip(idx, dist)]})
                except Exception as e:  # surface errors as JSON
                    self._json(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
