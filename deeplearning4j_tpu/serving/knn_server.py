"""REST k-nearest-neighbors server over a VPTree.

Reference parity: `nearestneighbor/server/NearestNeighborsServer.java:37`
(Play REST → shared stdlib plumbing in serving/http_base.py):
  POST /knn        {"ndarray": [...], "k": 5}        → neighbors of a vector
  POST /knnindex   {"index": 3, "k": 5}              → neighbors of a row
  GET  /healthz
Responses: {"results": [{"index": i, "distance": d}, ...]}
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.serving.http_base import JsonHttpServer


class NearestNeighborsServer(JsonHttpServer):
    def __init__(self, points: np.ndarray, *, port: int = 9000,
                 metric: str = "euclidean"):
        super().__init__(port=port)
        self.points = np.asarray(points)
        self.tree = VPTree(self.points, metric=metric)

    @staticmethod
    def _results(idx, dist):
        return {"results": [{"index": int(i), "distance": float(d)}
                            for i, d in zip(idx, dist)]}

    def _knn(self, req: dict):
        vec = np.asarray(req["ndarray"], np.float64)
        idx, dist = self.tree.search(vec, int(req.get("k", 5)))
        return self._results(idx, dist)

    def _knn_index(self, req: dict):
        i = int(req["index"])
        k = int(req.get("k", 5))
        idx, dist = self.tree.search(self.points[i], k + 1)
        pairs = [(j, d) for j, d in zip(idx, dist) if j != i][:k]
        return self._results([j for j, _ in pairs], [d for _, d in pairs])

    def get_routes(self):
        return {"/healthz":
                lambda: {"status": "ok", "points": len(self.points)}}

    def post_routes(self):
        return {"/knn": self._knn, "/knnindex": self._knn_index}
