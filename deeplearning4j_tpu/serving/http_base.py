"""Shared stdlib JSON-over-HTTP plumbing for the serving endpoints.

One implementation of the request/response mechanics (header parsing, JSON
encode/decode, status mapping, threaded serve/shutdown) used by the
inference server, the k-NN server (reference:
`NearestNeighborsServer.java:37`) and the Keras gateway — the role Play
filled for the reference's REST modules.

Status contract (clients must be able to tell their bug from ours):
  400 — the request is at fault: malformed JSON, a non-object body, or a
        missing field (`KeyError` from a handler)
  4xx/5xx via `HttpError` — a handler's explicit verdict (the serving
        control plane uses 503 for shed/draining and 504 for deadlines)
  500 — any other handler exception: a server fault, never blamed on the
        client
"""

from __future__ import annotations

import inspect
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs


class HttpError(Exception):
    """Raise from a handler to pick the response status explicitly."""

    def __init__(self, status: int, message: str, **extra):
        super().__init__(message)
        self.status = int(status)
        self.payload = {"error": message, **extra}


class TextResponse:
    """Return from a GET handler to send a non-JSON body with an explicit
    Content-Type (the Prometheus /metrics rendering uses
    `text/plain; version=0.0.4`)."""

    def __init__(self, body: str,
                 content_type: str = "text/plain; charset=utf-8",
                 status: int = 200):
        self.body = body
        self.content_type = content_type
        self.status = int(status)


class StreamResponse:
    """Return from a POST handler to stream incrementally instead of
    sending one JSON body: each event from the iterable is written as an
    SSE frame (`data: <json>\\n\\n`) and flushed immediately. The server
    speaks HTTP/1.0, so connection-close delimits the stream — no
    chunked encoding needed. If the client disconnects mid-stream the
    event iterable is `close()`d (a generator sees GeneratorExit there,
    which the /generate handler turns into a session cancel)."""

    def __init__(self, events,
                 content_type: str = "text/event-stream",
                 status: int = 200):
        self.events = events
        self.content_type = content_type
        self.status = int(status)


def _wants_request(fn: Callable) -> bool:
    """True when a GET handler declares a parameter — it then receives
    {"query": ..., "headers": ...} for content negotiation; zero-arg
    handlers keep the original contract."""
    try:
        return bool(inspect.signature(fn).parameters)
    except (TypeError, ValueError):
        return False


class JsonHttpServer:
    """Subclass and override get_routes()/post_routes().

    GET handlers: () -> payload dict, or (request) -> payload dict |
    TextResponse when they declare a parameter (request carries parsed
    query params + headers). POST handlers: (request dict) -> payload
    dict. Errors map per the module-level status contract."""

    def __init__(self, *, port: int = 0, host: str = "127.0.0.1"):
        self.port = port
        self.host = host
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def get_routes(self) -> Dict[str, Callable[[], dict]]:
        return {"/healthz": lambda: {"status": "ok"}}

    def get_prefix_routes(self) -> Dict[str, Callable[[str, dict], dict]]:
        """Path-parameter GET routes, consulted after an exact-route
        miss: `{"/trace/": fn}` serves `/trace/<id>` with
        fn(suffix, request). Longest prefix wins."""
        return {}

    def post_routes(self) -> Dict[str, Callable[[dict], dict]]:
        return {}

    def start(self) -> int:
        gets = self.get_routes()
        get_arity = {path: _wants_request(fn) for path, fn in gets.items()}
        prefixes = sorted(self.get_prefix_routes().items(),
                          key=lambda kv: -len(kv[0]))
        posts = self.post_routes()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _text(self, resp: TextResponse):
                body = resp.body.encode()
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                fn = gets.get(path)
                suffix = prefix_fn = None
                if fn is None:
                    for pre, pfn in prefixes:
                        if path.startswith(pre):
                            prefix_fn, suffix = pfn, path[len(pre):]
                            break
                    if prefix_fn is None:
                        return self._json(404, {"error": "not found"})
                try:
                    if prefix_fn is not None:
                        out = prefix_fn(suffix,
                                        {"query": parse_qs(query),
                                         "headers": self.headers})
                    elif get_arity[path]:
                        out = fn({"query": parse_qs(query),
                                  "headers": self.headers})
                    else:
                        out = fn()
                    if isinstance(out, TextResponse):
                        self._text(out)
                    else:
                        self._json(200, out)
                except HttpError as e:
                    self._json(e.status, e.payload)
                except Exception as e:
                    self._json(500, {"error": str(e)})

            def _stream(self, resp: StreamResponse):
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                try:
                    for ev in resp.events:
                        data = (ev if isinstance(ev, str)
                                else json.dumps(ev))
                        self.wfile.write(f"data: {data}\n\n".encode())
                        self.wfile.flush()
                # graft: allow(GL403): client hung up mid-stream — the
                # finally block cancels the producer; nothing to report
                except (BrokenPipeError, ConnectionResetError):
                    pass
                except Exception as e:
                    try:        # headers are gone; best-effort in-band
                        self.wfile.write(
                            f"data: {json.dumps({'error': str(e)})}"
                            f"\n\n".encode())
                    # graft: allow(GL403): the socket is already dead;
                    # the in-band error frame was best-effort
                    except OSError:
                        pass
                finally:
                    close = getattr(resp.events, "close", None)
                    if close is not None:
                        close()

            def do_POST(self):
                fn = posts.get(self.path)
                if fn is None:
                    return self._json(404, {"error": "not found"})
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) or b"{}"
                try:
                    req = json.loads(raw)
                except (json.JSONDecodeError, UnicodeDecodeError) as e:
                    return self._json(400, {"error": f"malformed JSON: {e}"})
                if not isinstance(req, dict):
                    return self._json(
                        400, {"error": "request body must be a JSON object"})
                try:
                    out = fn(req)
                    if isinstance(out, StreamResponse):
                        return self._stream(out)
                    self._json(200, out)
                except HttpError as e:
                    self._json(e.status, e.payload)
                except KeyError as e:
                    self._json(400, {"error": f"missing field/model: {e}"})
                except Exception as e:
                    self._json(500, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
