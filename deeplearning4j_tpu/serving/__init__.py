"""Serving: the model-serving control plane + REST endpoints.

Reference parity: deeplearning4j-nearestneighbor-server
(`NearestNeighborsServer.java:37`, `NearestNeighbor.java:19` — REST k-NN
over a VPTree) plus the model server. The control plane
(registry/scheduler/metrics) is the TPU-native extension: multi-model
hosting with hot-swap, continuous batching, admission control, and a
/metrics surface over the ParallelInference data plane.
"""

from deeplearning4j_tpu.serving.http_base import (
    HttpError, JsonHttpServer, StreamResponse,
)
from deeplearning4j_tpu.serving.inference_server import (
    InferenceServer, ModelServer,
)
from deeplearning4j_tpu.serving.knn_server import NearestNeighborsServer
from deeplearning4j_tpu.serving.kv_pool import (
    IncompatibleSessionSwapError, KVSlotPool, SlotPoolExhaustedError,
)
from deeplearning4j_tpu.serving.metrics import ServingStats
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache
from deeplearning4j_tpu.serving.registry import (
    DeployRolledBackError, ModelEntry, ModelRegistry,
)
from deeplearning4j_tpu.serving.scheduler import (
    AdmissionPolicy, ContinuousBatchingScheduler, DeadlineExceededError,
    RequestShedError, SchedulerClosedError, WorkerCrashError,
)
from deeplearning4j_tpu.serving.sessions import (
    DecodeSession, DecodeSessionManager,
)

__all__ = [
    "AdmissionPolicy", "ContinuousBatchingScheduler", "DecodeSession",
    "DecodeSessionManager", "DeadlineExceededError",
    "DeployRolledBackError", "HttpError", "IncompatibleSessionSwapError",
    "InferenceServer", "JsonHttpServer", "KVSlotPool", "ModelEntry",
    "ModelRegistry", "ModelServer", "NearestNeighborsServer",
    "PrefixCache",
    "RequestShedError", "SchedulerClosedError", "ServingStats",
    "SlotPoolExhaustedError", "StreamResponse", "WorkerCrashError",
]
