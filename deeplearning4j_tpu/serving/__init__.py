"""Serving: REST nearest-neighbor server + model inference endpoint.

Reference parity: deeplearning4j-nearestneighbor-server
(`NearestNeighborsServer.java:37`, `NearestNeighbor.java:19` — REST k-NN
over a VPTree) plus an /output endpoint backed by ParallelInference
(the reference serves models via ParallelInference embedded in user code).
"""

from deeplearning4j_tpu.serving.knn_server import NearestNeighborsServer
from deeplearning4j_tpu.serving.inference_server import InferenceServer

__all__ = ["NearestNeighborsServer", "InferenceServer"]
